// faultfs: FUSE passthrough filesystem with runtime fault injection.
//
// The TPU-native equivalent of CharybdeFS (reference:
// charybdefs/src/jepsen/charybdefs.clj:40-85, which builds ScyllaDB's
// thrift-controlled FUSE fs on each DB node and mounts /faulty over
// /real).  Same mechanism, no thrift: a control thread listens on a
// TCP port for newline-delimited commands and every filesystem op
// consults the fault table first.
//
// Control protocol (port 7656, one command per line, replies "OK"):
//   all <errno>            every op fails with -errno
//   prob <ppm> <errno>     each op fails with probability ppm/1e6
//   clear                  passthrough (no faults)
//
// Build on the node (the wrapper does this):
//   g++ -O2 -Wall faultfs.cc -o faultfs $(pkg-config fuse --cflags --libs) -lpthread
// Mount:
//   ./faultfs /faulty -oallow_other,nonempty -r /real

#define FUSE_USE_VERSION 29
#include <fuse.h>

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <random>
#include <string>

#include <dirent.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <pthread.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/statvfs.h>
#include <sys/time.h>
#include <sys/types.h>
#include <unistd.h>

static std::string g_root;                 // backing directory (/real)
static std::atomic<int> g_mode{0};         // 0=off 1=all 2=probability
static std::atomic<int> g_errno{EIO};
static std::atomic<int> g_ppm{0};          // failures per million ops
static int g_ctl_port = 7656;

static thread_local std::mt19937 tls_rng{std::random_device{}()};

// Returns 0 to proceed, or a negative errno to inject.
static int maybe_fail() {
  int mode = g_mode.load(std::memory_order_relaxed);
  if (mode == 0) return 0;
  if (mode == 1) return -g_errno.load(std::memory_order_relaxed);
  std::uniform_int_distribution<int> d(0, 999999);
  if (d(tls_rng) < g_ppm.load(std::memory_order_relaxed))
    return -g_errno.load(std::memory_order_relaxed);
  return 0;
}

static std::string real_path(const char *path) { return g_root + path; }

#define INJECT()                 \
  do {                           \
    int _f = maybe_fail();       \
    if (_f != 0) return _f;      \
  } while (0)

// ---- passthrough ops -------------------------------------------------

static int ff_getattr(const char *path, struct stat *st) {
  INJECT();
  return lstat(real_path(path).c_str(), st) == -1 ? -errno : 0;
}

static int ff_readlink(const char *path, char *buf, size_t size) {
  INJECT();
  ssize_t n = readlink(real_path(path).c_str(), buf, size - 1);
  if (n == -1) return -errno;
  buf[n] = '\0';
  return 0;
}

static int ff_mknod(const char *path, mode_t mode, dev_t rdev) {
  INJECT();
  return mknod(real_path(path).c_str(), mode, rdev) == -1 ? -errno : 0;
}

static int ff_mkdir(const char *path, mode_t mode) {
  INJECT();
  return mkdir(real_path(path).c_str(), mode) == -1 ? -errno : 0;
}

static int ff_unlink(const char *path) {
  INJECT();
  return unlink(real_path(path).c_str()) == -1 ? -errno : 0;
}

static int ff_rmdir(const char *path) {
  INJECT();
  return rmdir(real_path(path).c_str()) == -1 ? -errno : 0;
}

static int ff_symlink(const char *from, const char *to) {
  INJECT();
  return symlink(from, real_path(to).c_str()) == -1 ? -errno : 0;
}

static int ff_rename(const char *from, const char *to) {
  INJECT();
  return rename(real_path(from).c_str(), real_path(to).c_str()) == -1
             ? -errno : 0;
}

static int ff_link(const char *from, const char *to) {
  INJECT();
  return link(real_path(from).c_str(), real_path(to).c_str()) == -1
             ? -errno : 0;
}

static int ff_chmod(const char *path, mode_t mode) {
  INJECT();
  return chmod(real_path(path).c_str(), mode) == -1 ? -errno : 0;
}

static int ff_chown(const char *path, uid_t uid, gid_t gid) {
  INJECT();
  return lchown(real_path(path).c_str(), uid, gid) == -1 ? -errno : 0;
}

static int ff_truncate(const char *path, off_t size) {
  INJECT();
  return truncate(real_path(path).c_str(), size) == -1 ? -errno : 0;
}

static int ff_utimens(const char *path, const struct timespec ts[2]) {
  INJECT();
  return utimensat(AT_FDCWD, real_path(path).c_str(), ts,
                   AT_SYMLINK_NOFOLLOW) == -1 ? -errno : 0;
}

static int ff_open(const char *path, struct fuse_file_info *fi) {
  INJECT();
  int fd = open(real_path(path).c_str(), fi->flags);
  if (fd == -1) return -errno;
  fi->fh = fd;
  return 0;
}

static int ff_create(const char *path, mode_t mode,
                     struct fuse_file_info *fi) {
  INJECT();
  int fd = open(real_path(path).c_str(), fi->flags, mode);
  if (fd == -1) return -errno;
  fi->fh = fd;
  return 0;
}

static int ff_read(const char *path, char *buf, size_t size, off_t off,
                   struct fuse_file_info *fi) {
  (void)path;
  INJECT();
  ssize_t n = pread(fi->fh, buf, size, off);
  return n == -1 ? -errno : (int)n;
}

static int ff_write(const char *path, const char *buf, size_t size,
                    off_t off, struct fuse_file_info *fi) {
  (void)path;
  INJECT();
  ssize_t n = pwrite(fi->fh, buf, size, off);
  return n == -1 ? -errno : (int)n;
}

static int ff_statfs(const char *path, struct statvfs *st) {
  INJECT();
  return statvfs(real_path(path).c_str(), st) == -1 ? -errno : 0;
}

static int ff_flush(const char *path, struct fuse_file_info *fi) {
  (void)path;
  INJECT();
  return 0;
}

static int ff_release(const char *path, struct fuse_file_info *fi) {
  (void)path;
  close(fi->fh);
  return 0;
}

static int ff_fsync(const char *path, int datasync,
                    struct fuse_file_info *fi) {
  (void)path;
  INJECT();
  int r = datasync ? fdatasync(fi->fh) : fsync(fi->fh);
  return r == -1 ? -errno : 0;
}

static int ff_readdir(const char *path, void *buf, fuse_fill_dir_t filler,
                      off_t off, struct fuse_file_info *fi) {
  (void)off;
  (void)fi;
  INJECT();
  DIR *dp = opendir(real_path(path).c_str());
  if (dp == nullptr) return -errno;
  struct dirent *de;
  while ((de = readdir(dp)) != nullptr) {
    struct stat st;
    memset(&st, 0, sizeof(st));
    st.st_ino = de->d_ino;
    st.st_mode = (mode_t)(de->d_type << 12);
    if (filler(buf, de->d_name, &st, 0)) break;
  }
  closedir(dp);
  return 0;
}

static int ff_access(const char *path, int mask) {
  INJECT();
  return access(real_path(path).c_str(), mask) == -1 ? -errno : 0;
}

// ---- control thread --------------------------------------------------

static void handle_command(char *line, char *reply, size_t reply_sz) {
  char cmd[16] = {0};
  long a = 0, b = 0;
  int n = sscanf(line, "%15s %ld %ld", cmd, &a, &b);
  if (n >= 1 && strcmp(cmd, "clear") == 0) {
    g_mode.store(0);
    snprintf(reply, reply_sz, "OK\n");
  } else if (n >= 2 && strcmp(cmd, "all") == 0) {
    g_errno.store((int)a);
    g_mode.store(1);
    snprintf(reply, reply_sz, "OK\n");
  } else if (n >= 3 && strcmp(cmd, "prob") == 0) {
    g_ppm.store((int)a);
    g_errno.store((int)b);
    g_mode.store(2);
    snprintf(reply, reply_sz, "OK\n");
  } else if (n >= 1 && strcmp(cmd, "status") == 0) {
    snprintf(reply, reply_sz, "mode=%d errno=%d ppm=%d\n", g_mode.load(),
             g_errno.load(), g_ppm.load());
  } else {
    snprintf(reply, reply_sz, "ERR unknown command\n");
  }
}

static void *control_thread(void *) {
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) return nullptr;
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  // loopback only: the wrapper always connects from the node itself,
  // and an open fault port would let anyone break/heal disks mid-test
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)g_ctl_port);
  if (bind(srv, (struct sockaddr *)&addr, sizeof(addr)) != 0) {
    fprintf(stderr, "faultfs: control bind failed: %s\n", strerror(errno));
    close(srv);
    return nullptr;
  }
  listen(srv, 8);
  for (;;) {
    int c = accept(srv, nullptr, nullptr);
    if (c < 0) continue;
    char buf[256] = {0};
    ssize_t n = read(c, buf, sizeof(buf) - 1);
    if (n > 0) {
      char reply[128];
      handle_command(buf, reply, sizeof(reply));
      ssize_t w = write(c, reply, strlen(reply));
      (void)w;
    }
    close(c);
  }
  return nullptr;
}

// ---- main ------------------------------------------------------------

static struct fuse_operations ff_ops;

int main(int argc, char *argv[]) {
  // usage: faultfs <mountpoint> [fuse opts] -r <rootdir> [-p <ctl port>]
  char *fuse_argv[32];
  int fuse_argc = 0;
  for (int i = 0; i < argc && fuse_argc < 30; i++) {
    if (strcmp(argv[i], "-r") == 0 && i + 1 < argc) {
      g_root = argv[++i];
    } else if (strcmp(argv[i], "-p") == 0 && i + 1 < argc) {
      g_ctl_port = atoi(argv[++i]);
    } else {
      fuse_argv[fuse_argc++] = argv[i];
    }
  }
  if (g_root.empty()) {
    fprintf(stderr, "usage: %s <mount> [-o opts] -r <rootdir> [-p port]\n",
            argv[0]);
    return 2;
  }

  memset(&ff_ops, 0, sizeof(ff_ops));
  ff_ops.getattr = ff_getattr;
  ff_ops.readlink = ff_readlink;
  ff_ops.mknod = ff_mknod;
  ff_ops.mkdir = ff_mkdir;
  ff_ops.unlink = ff_unlink;
  ff_ops.rmdir = ff_rmdir;
  ff_ops.symlink = ff_symlink;
  ff_ops.rename = ff_rename;
  ff_ops.link = ff_link;
  ff_ops.chmod = ff_chmod;
  ff_ops.chown = ff_chown;
  ff_ops.truncate = ff_truncate;
  ff_ops.utimens = ff_utimens;
  ff_ops.open = ff_open;
  ff_ops.create = ff_create;
  ff_ops.read = ff_read;
  ff_ops.write = ff_write;
  ff_ops.statfs = ff_statfs;
  ff_ops.flush = ff_flush;
  ff_ops.release = ff_release;
  ff_ops.fsync = ff_fsync;
  ff_ops.readdir = ff_readdir;
  ff_ops.access = ff_access;

  pthread_t ctl;
  pthread_create(&ctl, nullptr, control_thread, nullptr);
  pthread_detach(ctl);

  return fuse_main(fuse_argc, fuse_argv, &ff_ops, nullptr);
}
