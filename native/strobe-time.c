/* strobe-time: oscillate the system wall clock by +/- delta ms every
 * period ms, for duration seconds, using CLOCK_MONOTONIC as the
 * untouched reference timeline.
 *
 * Usage: strobe-time <delta-ms> <period-ms> <duration-s>
 *
 * Behavior mirrors the reference's resources/strobe-time.c interface
 * (re-implemented): at each period boundary the wall clock flips
 * between base+delta and base-delta, where base tracks real elapsed
 * monotonic time from the start, so the clock averages true time while
 * strobing around it.  Requires CAP_SYS_TIME.
 */

#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>
#include <sys/time.h>

static long long mono_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static int set_wall_ms(long long wall_ms) {
  struct timeval tv;
  tv.tv_sec = wall_ms / 1000;
  tv.tv_usec = (wall_ms % 1000) * 1000;
  return settimeofday(&tv, NULL);
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
            argv[0]);
    return 2;
  }

  long long delta_ms = atoll(argv[1]);
  long long period_ms = atoll(argv[2]);
  long long duration_s = atoll(argv[3]);

  if (period_ms <= 0 || duration_s <= 0) {
    fprintf(stderr, "period and duration must be positive\n");
    return 2;
  }

  struct timeval tv0;
  if (gettimeofday(&tv0, NULL) != 0) {
    perror("gettimeofday");
    return 1;
  }
  long long wall0_ms = (long long)tv0.tv_sec * 1000LL + tv0.tv_usec / 1000;
  long long mono0 = mono_ns();
  long long end_ns = mono0 + duration_s * 1000000000LL;
  int sign = 1;

  while (mono_ns() < end_ns) {
    long long elapsed_ms = (mono_ns() - mono0) / 1000000LL;
    long long target = wall0_ms + elapsed_ms + sign * delta_ms;
    if (set_wall_ms(target) != 0) {
      perror("settimeofday");
      return 1;
    }
    sign = -sign;
    usleep((useconds_t)(period_ms * 1000));
  }

  /* restore: wall = start + true elapsed */
  long long elapsed_ms = (mono_ns() - mono0) / 1000000LL;
  if (set_wall_ms(wall0_ms + elapsed_ms) != 0) {
    perror("settimeofday");
    return 1;
  }
  return 0;
}
