/* bump-time: jump the system wall clock by a signed delta, in
 * milliseconds, then print the resulting POSIX time in ms.
 *
 * Usage: bump-time <delta-ms>
 *
 * Compiled with gcc on each DB node by the clock nemesis (same
 * deployment mechanism as the reference's resources/bump-time.c,
 * behavior re-implemented from its interface: one-shot settimeofday
 * jump).  Requires CAP_SYS_TIME (run as root).
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>

int main(int argc, char **argv) {
  struct timeval tv;
  long long delta_ms;
  char *end;

  if (argc != 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 2;
  }

  delta_ms = strtoll(argv[1], &end, 10);
  if (*end != '\0') {
    fprintf(stderr, "bad delta: %s\n", argv[1]);
    return 2;
  }

  if (gettimeofday(&tv, NULL) != 0) {
    perror("gettimeofday");
    return 1;
  }

  /* add delta, normalizing microseconds */
  long long usec = (long long)tv.tv_usec + (delta_ms % 1000) * 1000LL;
  tv.tv_sec += delta_ms / 1000 + usec / 1000000LL;
  usec %= 1000000LL;
  if (usec < 0) {
    usec += 1000000LL;
    tv.tv_sec -= 1;
  }
  tv.tv_usec = (suseconds_t)usec;

  if (settimeofday(&tv, NULL) != 0) {
    perror("settimeofday");
    return 1;
  }

  printf("%lld\n", (long long)tv.tv_sec * 1000LL + tv.tv_usec / 1000);
  return 0;
}
