// repregd — a single-binary replicated linearizable register daemon.
//
// This is the "real database" the localkv suite installs: the harness
// compiles this file ON THE NODE with g++ through the control layer
// (the same deploy mechanism the reference uses for its clock helpers,
// jepsen/src/jepsen/nemesis/time.clj:20-50), runs one replica per node
// under start-stop-daemon, and partitions the peer links mid-workload.
//
// Replication is multi-writer ABD over majority quorums:
//   * every replica persists (ts, tiebreak, value) with fsync;
//   * a write asks a majority for the max timestamp, picks
//     (max_ts+1, node_id), and stores to a majority before acking;
//   * a read asks a majority, takes the max-timestamped value, and
//     writes it back to a majority before returning (read repair).
// Quorum intersection makes the register linearizable under crashes
// and partitions without clocks or leases.
//
// Line protocol, one port for clients and peers:
//   clients:  "R"            -> <value> | ERR-EARLY ...
//             "W <v>"        -> OK | ERR-EARLY ... | ERR-MAYBE ...
//             "STATUS"       -> "<ts> <tb> <value>"
//   peers:    "GET"          -> "<ts> <tb> <value>"
//             "SET <ts> <tb> <v>" -> OK
// ERR-EARLY = no store was attempted (definite failure); ERR-MAYBE =
// stores went out without a majority ack (indeterminate) — the client
// maps these to :fail / :info.
//
// usage: repregd <node_id> <port> <state_path> [peers "2=host:port,..."]

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

static const int kPeerTimeoutMs = 250;

struct Versioned {
  long long ts = 0;
  long long tb = 0;
  long long value = 0;
};

// fsync'd (ts, tiebreak, value) cell with atomic-rename persistence.
class State {
 public:
  explicit State(std::string path) : path_(std::move(path)) {
    FILE* f = std::fopen(path_.c_str(), "r");
    if (f) {
      Versioned v;
      if (std::fscanf(f, "%lld %lld %lld", &v.ts, &v.tb, &v.value) == 3)
        cell_ = v;
      std::fclose(f);
    }
  }

  Versioned read() {
    std::lock_guard<std::mutex> g(mu_);
    return cell_;
  }

  void store_if_newer(const Versioned& v) {
    std::lock_guard<std::mutex> g(mu_);
    if (v.ts > cell_.ts || (v.ts == cell_.ts && v.tb > cell_.tb)) {
      cell_ = v;
      persist();
    }
  }

 private:
  void persist() {
    std::string tmp = path_ + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "w");
    if (!f) return;
    std::fprintf(f, "%lld %lld %lld", cell_.ts, cell_.tb, cell_.value);
    std::fflush(f);
    fsync(fileno(f));
    std::fclose(f);
    rename(tmp.c_str(), path_.c_str());
  }

  std::string path_;
  std::mutex mu_;
  Versioned cell_;
};

struct Peer {
  int id;
  std::string host;
  int port;
};

// One peer call: connect with a poll()-bounded timeout, one request
// line, one reply line.  Returns false on any error.
static bool call_peer(const Peer& p, const std::string& line,
                      std::string* reply) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return false;
  fcntl(fd, F_SETFL, O_NONBLOCK);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(p.port));
  if (inet_pton(AF_INET, p.host.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return false;
  }
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc < 0 && errno == EINPROGRESS) {
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, kPeerTimeoutMs) <= 0) {
      close(fd);
      return false;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      close(fd);
      return false;
    }
  } else if (rc < 0) {
    close(fd);
    return false;
  }
  // blocking IO with timeouts from here on
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) & ~O_NONBLOCK);
  timeval tv{0, kPeerTimeoutMs * 1000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  std::string msg = line + "\n";
  if (send(fd, msg.data(), msg.size(), MSG_NOSIGNAL) !=
      static_cast<ssize_t>(msg.size())) {
    close(fd);
    return false;
  }
  char buf[256];
  std::string out;
  while (out.find('\n') == std::string::npos) {
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n <= 0) {
      close(fd);
      return false;
    }
    out.append(buf, static_cast<size_t>(n));
  }
  close(fd);
  out.erase(out.find('\n'));
  *reply = out;
  return true;
}

class Replica {
 public:
  Replica(int id, std::vector<Peer> peers, State* state)
      : id_(id), peers_(std::move(peers)), state_(state) {
    n_ = static_cast<int>(peers_.size()) + 1;
    majority_ = n_ / 2 + 1;
  }

  std::string handle(const std::vector<std::string>& parts) {
    const std::string& cmd = parts[0];
    if (cmd == "R") return client_read();
    if (cmd == "W" && parts.size() >= 2)
      return client_write(std::stoll(parts[1]));
    if (cmd == "GET") {
      Versioned v = state_->read();
      return fmt(v.ts, v.tb, v.value);
    }
    if (cmd == "SET" && parts.size() >= 4) {
      state_->store_if_newer(
          {std::stoll(parts[1]), std::stoll(parts[2]), std::stoll(parts[3])});
      return "OK";
    }
    if (cmd == "STATUS") {
      Versioned v = state_->read();
      return fmt(v.ts, v.tb, v.value);
    }
    return "ERR";
  }

 private:
  static std::string fmt(long long a, long long b, long long c) {
    std::ostringstream os;
    os << a << " " << b << " " << c;
    return os.str();
  }

  // Ask every peer in parallel; replies land in a shared vector, and
  // the caller waits out the per-call timeout on a condvar so a hung
  // peer cannot stall the quorum op past its budget.
  std::vector<std::string> broadcast(const std::string& line) {
    auto n = peers_.size();
    auto replies = std::make_shared<std::vector<std::string>>(n);
    auto got = std::make_shared<std::vector<bool>>(n, false);
    auto mu = std::make_shared<std::mutex>();
    auto cv = std::make_shared<std::condition_variable>();
    auto done = std::make_shared<size_t>(0);
    for (size_t i = 0; i < n; i++) {
      Peer p = peers_[i];
      std::thread([=] {
        std::string rep;
        bool ok = call_peer(p, line, &rep);
        std::lock_guard<std::mutex> g(*mu);
        if (ok) {
          (*replies)[i] = rep;
          (*got)[i] = true;
        }
        (*done)++;
        cv->notify_all();
      }).detach();
    }
    std::unique_lock<std::mutex> lk(*mu);
    cv->wait_for(lk, std::chrono::milliseconds(2 * kPeerTimeoutMs + 100),
                 [&] { return *done == n; });
    std::vector<std::string> out;
    for (size_t i = 0; i < n; i++)
      out.push_back((*got)[i] ? (*replies)[i] : std::string());
    return out;
  }

  // (ts, tb, value) of the max-timestamped majority reply (counting
  // self), or nullopt-style {found=false}.
  bool quorum_get(Versioned* best) {
    *best = state_->read();
    int got = 1;
    for (const std::string& rep : broadcast("GET")) {
      if (rep.empty()) continue;
      Versioned v;
      if (std::sscanf(rep.c_str(), "%lld %lld %lld", &v.ts, &v.tb,
                      &v.value) != 3)
        continue;
      got++;
      if (v.ts > best->ts || (v.ts == best->ts && v.tb > best->tb)) *best = v;
    }
    return got >= majority_;
  }

  bool quorum_set(const Versioned& v) {
    state_->store_if_newer(v);
    int acks = 1;
    std::string line = "SET " + fmt(v.ts, v.tb, v.value);
    for (const std::string& rep : broadcast(line))
      if (rep == "OK") acks++;
    return acks >= majority_;
  }

  std::string client_read() {
    Versioned best;
    if (!quorum_get(&best)) return "ERR-EARLY no-quorum";
    // read repair: the observed value must reach a majority before the
    // read returns, else a later read could observe an older value
    if (!quorum_set(best)) return "ERR-EARLY no-quorum";
    return std::to_string(best.value);
  }

  std::string client_write(long long v) {
    // concurrent writes coordinated by this replica must serialize, or
    // two could pick the same (max_ts+1, id) for different values
    std::lock_guard<std::mutex> g(write_mu_);
    Versioned best;
    if (!quorum_get(&best)) return "ERR-EARLY no-quorum";
    Versioned next{best.ts + 1, id_, v};
    if (quorum_set(next)) return "OK";
    return "ERR-MAYBE no-quorum";
  }

  int id_;
  std::vector<Peer> peers_;
  State* state_;
  int n_, majority_;
  std::mutex write_mu_;
};

static void serve_conn(int fd, Replica* replica) {
  std::string buf;
  char chunk[512];
  for (;;) {
    size_t nl;
    while ((nl = buf.find('\n')) == std::string::npos) {
      ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        close(fd);
        return;
      }
      buf.append(chunk, static_cast<size_t>(n));
    }
    std::string line = buf.substr(0, nl);
    buf.erase(0, nl + 1);
    std::istringstream is(line);
    std::vector<std::string> parts;
    std::string tok;
    while (is >> tok) parts.push_back(tok);
    std::string out = parts.empty() ? "ERR" : replica->handle(parts);
    out += "\n";
    if (send(fd, out.data(), out.size(), MSG_NOSIGNAL) !=
        static_cast<ssize_t>(out.size())) {
      close(fd);
      return;
    }
  }
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: repregd <node_id> <port> <state_path> [peers]\n");
    return 2;
  }
  int node_id = std::atoi(argv[1]);
  int port = std::atoi(argv[2]);
  State state(argv[3]);
  std::vector<Peer> peers;
  if (argc >= 5 && argv[4][0] != '\0') {
    std::string spec = argv[4];
    std::istringstream is(spec);
    std::string item;
    while (std::getline(is, item, ',')) {
      auto eq = item.find('=');
      auto colon = item.rfind(':');
      if (eq == std::string::npos || colon == std::string::npos) continue;
      peers.push_back({std::atoi(item.substr(0, eq).c_str()),
                       item.substr(eq + 1, colon - eq - 1),
                       std::atoi(item.substr(colon + 1).c_str())});
    }
  }
  Replica replica(node_id, peers, &state);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(srv, 64) != 0) {
    std::perror("bind/listen");
    return 1;
  }
  std::printf("repregd %d listening on %d (%zu peers)\n", node_id, port,
              peers.size());
  std::fflush(stdout);
  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(serve_conn, fd, &replica).detach();
  }
}
