// Block-file writer: the native core of the incremental test store.
//
// The reference implements its store's low-level writer as a Java class
// (jepsen/src/jepsen/store/FileOffsetOutputStream.java:9-40 — an
// offset-pinned, CRC32-tracking stream) under a Clojure format layer
// (jepsen/src/jepsen/store/format.clj:1-200).  Here the equivalent is a
// small C++ library driven from Python via ctypes: it appends
// length/CRC32/type-framed blocks to a file in a single pass, patches
// the root index offset, and verifies frames on read.
//
// File layout (all integers little-endian):
//   magic "JTPU" | u32 version | u64 index-offset | block | block | ...
// Block frame:
//   u64 length (incl. frame) | u32 crc32 | u16 type | data...
// The CRC is computed over data, then the frame with the crc field
// zeroed — so a block can be written in one pass with unknown size.
//
// Build: g++ -O2 -shared -fPIC -o libblockfile.so blockfile.cc

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

// CRC32 (IEEE 802.3, reflected), table-driven.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_update(uint32_t crc, const uint8_t* buf, size_t len) {
  crc = ~crc;
  for (size_t i = 0; i < len; i++)
    crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

constexpr char MAGIC[4] = {'J', 'T', 'P', 'U'};
constexpr uint32_t VERSION = 1;
constexpr size_t HEADER_SIZE = 4 + 4 + 8;
constexpr size_t FRAME_SIZE = 8 + 4 + 2;

struct Writer {
  FILE* f;
  uint64_t offset;  // current end-of-file offset
};

void put_u16(uint8_t* p, uint16_t v) { memcpy(p, &v, 2); }
void put_u32(uint8_t* p, uint32_t v) { memcpy(p, &v, 4); }
void put_u64(uint8_t* p, uint64_t v) { memcpy(p, &v, 8); }
uint16_t get_u16(const uint8_t* p) { uint16_t v; memcpy(&v, p, 2); return v; }
uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
uint64_t get_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

}  // namespace

extern "C" {

// Open (creating or truncating) a block file; writes the header with a
// zero index-offset.  Returns an opaque handle, or null on failure.
void* bf_create(const char* path) {
  crc_init();
  FILE* f = fopen(path, "wb+");
  if (!f) return nullptr;
  uint8_t header[HEADER_SIZE];
  memcpy(header, MAGIC, 4);
  put_u32(header + 4, VERSION);
  put_u64(header + 8, 0);
  if (fwrite(header, 1, HEADER_SIZE, f) != HEADER_SIZE) {
    fclose(f);
    return nullptr;
  }
  Writer* w = new Writer{f, HEADER_SIZE};
  return w;
}

// Re-open an existing block file for appending.  Returns null on
// failure (bad magic/version).
void* bf_open_append(const char* path) {
  crc_init();
  FILE* f = fopen(path, "rb+");
  if (!f) return nullptr;
  uint8_t header[HEADER_SIZE];
  if (fread(header, 1, HEADER_SIZE, f) != HEADER_SIZE ||
      memcmp(header, MAGIC, 4) != 0 || get_u32(header + 4) != VERSION) {
    fclose(f);
    return nullptr;
  }
  fseek(f, 0, SEEK_END);
  long end = ftell(f);
  Writer* w = new Writer{f, (uint64_t)end};
  return w;
}

// Append one block; returns its file offset, or 0 on failure.
uint64_t bf_append_block(void* handle, uint16_t type, const uint8_t* data,
                         uint64_t len) {
  Writer* w = (Writer*)handle;
  uint64_t frame_len = FRAME_SIZE + len;
  uint8_t frame[FRAME_SIZE];
  put_u64(frame, frame_len);
  put_u32(frame + 8, 0);  // crc slot zeroed for computation
  put_u16(frame + 12, type);
  uint32_t crc = crc32_update(0, data, len);
  crc = crc32_update(crc, frame, FRAME_SIZE);
  put_u32(frame + 8, crc);
  uint64_t at = w->offset;
  if (fseek(w->f, (long)at, SEEK_SET) != 0) return 0;
  if (fwrite(frame, 1, FRAME_SIZE, w->f) != FRAME_SIZE) return 0;
  if (len && fwrite(data, 1, len, w->f) != len) return 0;
  w->offset = at + frame_len;
  return at;
}

// Point the header's index-offset at the given block offset (the
// atomic "commit" of a new index).
int bf_set_index_offset(void* handle, uint64_t offset) {
  Writer* w = (Writer*)handle;
  uint8_t buf[8];
  put_u64(buf, offset);
  if (fseek(w->f, 8, SEEK_SET) != 0) return -1;
  if (fwrite(buf, 1, 8, w->f) != 8) return -1;
  fflush(w->f);
  return 0;
}

uint64_t bf_tell(void* handle) { return ((Writer*)handle)->offset; }

int bf_flush(void* handle) { return fflush(((Writer*)handle)->f); }

void bf_close(void* handle) {
  Writer* w = (Writer*)handle;
  fflush(w->f);
  fclose(w->f);
  delete w;
}

// Verify one block frame at `offset`; returns the data length and
// writes the type to *type_out, or -1 on CRC/frame mismatch.
// Reading the data itself is done by Python (mmap/seek) — this check
// exists so corrupted files fail loudly before deserialization.
int64_t bf_check_block(const char* path, uint64_t offset, uint16_t* type_out) {
  crc_init();
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t frame[FRAME_SIZE];
  if (fseek(f, (long)offset, SEEK_SET) != 0 ||
      fread(frame, 1, FRAME_SIZE, f) != FRAME_SIZE) {
    fclose(f);
    return -1;
  }
  uint64_t frame_len = get_u64(frame);
  uint32_t want = get_u32(frame + 8);
  uint16_t type = get_u16(frame + 12);
  if (frame_len < FRAME_SIZE) {
    fclose(f);
    return -1;
  }
  uint64_t len = frame_len - FRAME_SIZE;
  put_u32(frame + 8, 0);
  uint32_t crc = 0;
  const size_t CHUNK = 1 << 20;
  uint8_t* buf = new uint8_t[CHUNK];
  uint64_t remaining = len;
  bool first = true;
  // crc over data...
  uint32_t data_crc = 0;
  while (remaining) {
    size_t n = remaining < CHUNK ? (size_t)remaining : CHUNK;
    if (fread(buf, 1, n, f) != n) {
      delete[] buf;
      fclose(f);
      return -1;
    }
    if (first) {
      data_crc = crc32_update(0, buf, n);
      first = false;
    } else {
      data_crc = crc32_update(data_crc, buf, n);
    }
    remaining -= n;
  }
  delete[] buf;
  fclose(f);
  crc = crc32_update(data_crc, frame, FRAME_SIZE);
  if (len == 0) crc = crc32_update(0, frame, FRAME_SIZE);
  if (crc != want) return -1;
  if (type_out) *type_out = type;
  return (int64_t)len;
}

}  // extern "C"
