/* strobe-time-experiment: experimental strobe variant that toggles the
 * wall clock between its true offset and true+delta every period ms for
 * duration seconds, then restores the clock and prints how many
 * adjustments it made.
 *
 * Usage: strobe-time-experiment <delta-ms> <period-ms> <duration-s>
 *
 * Differs from strobe-time in two ways it inherits from the reference's
 * resources/strobe-time-experiment.c (re-implemented): the oscillation
 * is one-sided (true vs true+delta, not +/-delta around true), and the
 * adjustment count is reported on stdout so callers can confirm the
 * strobe actually ran.  Requires CAP_SYS_TIME.
 */

#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>
#include <sys/time.h>

static long long now_ns(clockid_t clk) {
  struct timespec ts;
  clock_gettime(clk, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

/* wall = monotonic + offset_ns */
static int set_wall_from_mono(long long offset_ns) {
  long long wall = now_ns(CLOCK_MONOTONIC) + offset_ns;
  struct timeval tv;
  tv.tv_sec = wall / 1000000000LL;
  tv.tv_usec = (wall % 1000000000LL) / 1000;
  return settimeofday(&tv, NULL);
}

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <delta-ms> <period-ms> <duration-s>\n"
            "Every period ms, toggles the clock between true time and\n"
            "true+delta, for duration seconds; prints the number of\n"
            "adjustments made.\n",
            argv[0]);
    return 1;
  }
  long long delta_ns = (long long)(atof(argv[1]) * 1e6);
  long long period_ns = (long long)(atof(argv[2]) * 1e6);
  long long duration_ns = (long long)(atof(argv[3]) * 1e9);
  if (period_ns <= 0) {
    fprintf(stderr, "period must be positive\n");
    return 1;
  }

  /* The clock's honest relationship to the monotonic timeline, captured
   * once up front so we can both strobe around it and restore it. */
  long long true_offset = now_ns(CLOCK_REALTIME) - now_ns(CLOCK_MONOTONIC);
  long long end = now_ns(CLOCK_MONOTONIC) + duration_ns;

  struct timespec period = {
    .tv_sec = period_ns / 1000000000LL,
    .tv_nsec = period_ns % 1000000000LL,
  };
  int weird = 0;
  long long count = 0;

  while (now_ns(CLOCK_MONOTONIC) < end) {
    if (0 != set_wall_from_mono(weird ? true_offset
                                      : true_offset + delta_ns)) {
      perror("settimeofday");
      return 2;
    }
    weird = !weird;
    count++;
    if (0 != nanosleep(&period, NULL)) {
      perror("nanosleep");
      return 3;
    }
  }

  if (0 != set_wall_from_mono(true_offset)) {
    perror("settimeofday");
    return 2;
  }
  printf("%lld\n", count);
  return 0;
}
