"""Device-mesh parallelism for the analysis plane.

The framework's data-parallel axis is the *history batch* (the TPU mapping
of the reference's jepsen.independent keyed sub-histories — SURVEY.md
§2.3.3): thousands of independent histories shard across devices over ICI,
each device runs the identical search kernel on its shard, and only the
aggregate verdict/statistics ride collectives.
"""

from .mesh import default_mesh, shard_batch, sharded_check, verdict_stats

__all__ = ["default_mesh", "shard_batch", "sharded_check", "verdict_stats"]
