"""Mesh construction and history-batch sharding.

Replaces the reference's control-plane parallelism (CyclicBarrier +
real-pmap over SSH sessions, jepsen/src/jepsen/core.clj:44-57) on the
*analysis* side with XLA collectives over a jax.sharding.Mesh: histories
are device-data-parallel; a single psum aggregates verdict statistics
(SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HIST_AXIS = "hist"


def default_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices; the history batch
    shards along it."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (HIST_AXIS,))


def resolve_mesh(test: dict) -> Optional[Mesh]:
    """The test's analysis mesh: an explicit ``test["mesh"]``, or the
    lazily-built ``test["mesh-fn"]`` (the CLI's --mesh flag installs
    one so a wedged accelerator tunnel can't hang test STARTUP — the
    backend is only probed once histories exist and analysis begins)."""
    m = test.get("mesh")
    if m is not None:
        return m
    fn = test.get("mesh-fn")
    return fn() if callable(fn) else None


def pad_to_multiple(arr: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to a multiple of `multiple` with `fill`."""
    b = arr.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return arr
    pad = np.full((rem,) + arr.shape[1:], fill, arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def shard_batch(mesh: Mesh, *arrays: np.ndarray):
    """device_put each array with its leading axis sharded over the mesh
    (trailing axes replicated).  Leading dims must be divisible by the
    mesh size (use pad_to_multiple)."""
    sharding = NamedSharding(mesh, P(HIST_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def sharded_check(
    check_fn,
    mesh: Mesh,
    init_state: np.ndarray,
    ev_slot: np.ndarray,
    cand_slot: np.ndarray,
    cand_f: np.ndarray,
    cand_a: np.ndarray,
    cand_b: np.ndarray,
):
    """Run a jitted batched checker with inputs sharded over the mesh.
    The batch is padded to a device multiple — padding rows use
    ev_slot/cand_slot = -1, which the kernel treats as no-op events, so
    they report valid and are sliced off by the caller.  XLA partitions
    the vmapped search across devices; no collectives are needed for the
    per-history verdicts themselves."""
    n = mesh.devices.size
    b = init_state.shape[0]
    arrays = (
        pad_to_multiple(init_state, n, 0),
        pad_to_multiple(ev_slot, n, -1),
        pad_to_multiple(cand_slot, n, -1),
        pad_to_multiple(cand_f, n, 0),
        pad_to_multiple(cand_a, n, 0),
        pad_to_multiple(cand_b, n, 0),
    )
    sharded = shard_batch(mesh, *arrays)
    with mesh:
        ok, failed_at, overflow = check_fn(*sharded)
    return ok[:b], failed_at[:b], overflow[:b]


def verdict_stats(ok: jnp.ndarray, overflow: jnp.ndarray, mesh: Optional[Mesh] = None):
    """Aggregate verdict statistics. On a mesh, this is the one place a
    collective runs (an all-reduce over the history axis)."""
    valid = jnp.sum(ok & ~overflow)
    invalid = jnp.sum(~ok & ~overflow)
    unknown = jnp.sum(overflow)
    return {"valid": valid, "invalid": invalid, "unknown": unknown}
