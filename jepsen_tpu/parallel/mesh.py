"""Mesh construction and history-batch sharding.

Replaces the reference's control-plane parallelism (CyclicBarrier +
real-pmap over SSH sessions, jepsen/src/jepsen/core.clj:44-57) on the
*analysis* side with XLA collectives over a jax.sharding.Mesh: histories
are device-data-parallel; a single psum aggregates verdict statistics
(SURVEY.md §2.4).

Since the slice-native engine work this module is also the dispatch
seam the production pipeline runs through: :func:`shard_fn` wraps a
compiled batched checker in ``shard_map`` (every input and output
split along :data:`HIST_AXIS`, one cached sharded executable per
(fn, mesh)), and :func:`engine_default_mesh` resolves the mesh the
engine adopts when the caller passed none — every attached device
whenever more than one is present (doc/checker-engines.md
"Slice-native dispatch": CLI ``--mesh`` → ``test["mesh"]`` → auto).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HIST_AXIS = "hist"


def default_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices; the history batch
    shards along it."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (HIST_AXIS,))


def engine_default_mesh() -> Optional[Mesh]:
    """The mesh the checker engine adopts when the caller passed none:
    every attached device, whenever more than one is present — the
    slice IS the production dispatch target, not an opt-in.

    ``JEPSEN_TPU_ENGINE_MESH`` tunes the resolution: ``0`` disables
    auto-sharding entirely (single-device dispatch even on a slice),
    ``1`` extends it to virtual host devices (the CPU backend's
    ``--xla_force_host_platform_device_count`` emulation — how
    ``make mesh-smoke`` and the tests force the sharded path without
    hardware).  Unset/``auto``: accelerator platforms only, because on
    the CPU backend the virtual devices share the same cores and
    auto-sharding every host run would tax the common case to exercise
    an emulation.  Returns None (single-device) when the backend is
    unreachable — mesh resolution must never be the thing that hangs a
    checker run."""
    mode = os.environ.get("JEPSEN_TPU_ENGINE_MESH", "auto").strip().lower()
    if mode in ("0", "false", "off", "no"):
        return None
    try:
        # local devices only: on a multi-process slice jax.devices()
        # includes other hosts' chips, which this process cannot
        # device_put to — each host's engine shards its own addressable
        # devices (the history batch is already partitioned upstream)
        devs = jax.local_devices()
    except Exception:  # noqa: BLE001 — unreachable backend = no mesh
        return None
    if len(devs) < 2:
        return None
    if devs[0].platform == "cpu" and mode not in ("1", "on", "true", "yes",
                                                  "force"):
        return None
    return default_mesh(devs)


def resolve_mesh(test: dict) -> Optional[Mesh]:
    """The test's analysis mesh: an explicit ``test["mesh"]``, or the
    lazily-built ``test["mesh-fn"]`` (the CLI's --mesh flag installs
    one so a wedged accelerator tunnel can't hang test STARTUP — the
    backend is only probed once histories exist and analysis begins).
    ``None`` falls through to the engine's own resolution
    (:func:`engine_default_mesh`) at dispatch time."""
    m = test.get("mesh")
    if m is not None:
        return m
    fn = test.get("mesh-fn")
    return fn() if callable(fn) else None


def pad_to_multiple(arr: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to a multiple of `multiple` with `fill`."""
    b = arr.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return arr
    pad = np.full((rem,) + arr.shape[1:], fill, arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def shard_batch(mesh: Mesh, *arrays: np.ndarray):
    """device_put each array with its leading axis sharded over the mesh
    (trailing axes replicated).  Leading dims must be divisible by the
    mesh size (use pad_to_multiple)."""
    sharding = NamedSharding(mesh, P(HIST_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


_shard_lock = threading.Lock()


def _mesh_key(mesh: Mesh) -> tuple:
    """Cache key for a sharded variant: axis names + the exact device
    assignment (two meshes over the same devices share an executable;
    a resized or reordered mesh must not)."""
    return (mesh.axis_names, tuple(d.id for d in mesh.devices.flat))


def shard_fn(check_fn, mesh: Mesh, n_in: int = 6, n_out: int = 3):  # jt: allow[budget-missing-cap] — the per-chip cap rides the BASE kernel; the engine chunks to n_devices x base.safe_dispatch (execution.py "Slice-native dispatch")
    """The ``shard_map``-wrapped, jitted variant of a compiled batched
    kernel: all ``n_in`` input arrays and all ``n_out`` outputs
    partition along :data:`HIST_AXIS` (per-row work is embarrassingly
    parallel — each device runs the unmodified kernel on its row
    shard, no collectives).  The defaults are the history checkers'
    6-in/3-out contract; the Elle cycle screens ride the same wrapper
    at 1-in/2- or 3-out (flags or packed screen planes, plus the
    per-row closure-rounds evidence).  Cached per (fn, mesh, arity) on the fn
    object itself, the same lifetime as the
    ``make_check_fn``/``make_dense_fn`` caches, so repeat dispatches
    at a shape reuse ONE sharded executable — the per-call-site-mesh +
    sharded-compiled-step-fn pattern (SNIPPETS [2]–[3]).  Inputs'
    leading dim must be divisible by the mesh size (callers pad with
    neutral rows; see the engine's shard padding).

    The kernel factories stamp every resolved knob on the fn
    (``fn.closure_impl``/``fn.closure_mode`` from ``ops.cycles``,
    ``fn.union_mode`` from ``ops.dense``, ``fn.compaction`` from
    ``ops.wgl``); all of them ride the cache key — the same fields as
    the factories' own lru keys — so a knob flip mid-process can never
    resolve a sharded executable traced for a different lowering, even
    if a caller ever reuses one fn object across knob states.  The
    ``jaxpr-cache-key`` lint rule pins this correspondence."""
    key = (_mesh_key(mesh), n_in, n_out,
           getattr(check_fn, "closure_impl", ""),
           getattr(check_fn, "closure_mode", ""),
           getattr(check_fn, "union_mode", ""),
           getattr(check_fn, "compaction", ""))
    with _shard_lock:
        cache = getattr(check_fn, "_sharded_variants", None)
        if cache is None:
            try:
                cache = check_fn._sharded_variants = {}
            except AttributeError:
                cache = None
        if cache is not None:
            hit = cache.get(key)
            if hit is not None:
                return hit
    spec = P(HIST_AXIS)
    # check_rep=False: the kernels' closure loops (lax.while_loop) have
    # no replication rule in this jax version, and nothing here claims
    # replication anyway — every output is fully sharded on HIST_AXIS
    wrapped = jax.jit(
        shard_map(
            check_fn, mesh=mesh,
            in_specs=(spec,) * n_in, out_specs=(spec,) * n_out,
            check_rep=False,
        )
    )
    if cache is not None:
        with _shard_lock:
            wrapped = cache.setdefault(key, wrapped)
    return wrapped


def sharded_check(
    check_fn,
    mesh: Mesh,
    init_state: np.ndarray,
    ev_slot: np.ndarray,
    cand_slot: np.ndarray,
    cand_f: np.ndarray,
    cand_a: np.ndarray,
    cand_b: np.ndarray,
):
    """Run a jitted batched checker sharded over the mesh via
    :func:`shard_fn`.  The batch is padded to a device multiple —
    padding rows use ev_slot/cand_slot = -1, which the kernel treats as
    no-op events, so they report valid and are sliced off by the
    caller.  Each device executes the kernel on its own row shard; no
    collectives are needed for the per-history verdicts themselves."""
    n = mesh.devices.size
    b = init_state.shape[0]
    arrays = (
        pad_to_multiple(init_state, n, 0),
        pad_to_multiple(ev_slot, n, -1),
        pad_to_multiple(cand_slot, n, -1),
        pad_to_multiple(cand_f, n, 0),
        pad_to_multiple(cand_a, n, 0),
        pad_to_multiple(cand_b, n, 0),
    )
    sharded = shard_batch(mesh, *arrays)
    ok, failed_at, overflow = shard_fn(check_fn, mesh)(*sharded)  # jt: allow[budget-direct-dispatch] — one-shot helper; callers (wgl.check_batch) own the capped chunk loop
    return ok[:b], failed_at[:b], overflow[:b]


def sharded_elle(fn, mesh: Mesh, rel: np.ndarray, n_out: int):
    """Run an Elle cycle-screen kernel (one ``(B, n, n)`` relation
    input, ``n_out`` tuple outputs — see ``ops.cycles``) sharded over
    the mesh via :func:`shard_fn`.  Padding rows are all-zero
    relation matrices: edge-free, hence acyclic, hence neutral — the
    caller (the engine executor) slices live rows back at settle."""
    n = mesh.devices.size
    b = rel.shape[0]
    rel = pad_to_multiple(np.asarray(rel), n, 0)
    (sharded,) = shard_batch(mesh, rel)
    outs = shard_fn(fn, mesh, n_in=1, n_out=n_out)(sharded)  # jt: allow[budget-direct-dispatch] — one-shot helper; callers (ops.cycles screens) own the capped chunk loop
    return tuple(o[:b] for o in outs)


def verdict_stats(ok: jnp.ndarray, overflow: jnp.ndarray, mesh: Optional[Mesh] = None):
    """Aggregate verdict statistics. On a mesh, this is the one place a
    collective runs (an all-reduce over the history axis)."""
    valid = jnp.sum(ok & ~overflow)
    invalid = jnp.sum(~ok & ~overflow)
    unknown = jnp.sum(overflow)
    return {"valid": valid, "invalid": invalid, "unknown": unknown}
