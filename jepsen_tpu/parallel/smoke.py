"""Mesh smoke check: ``python -m jepsen_tpu.parallel.smoke``.

The slice-native dispatch gate (doc/checker-engines.md "Slice-native
dispatch"): forces the CPU backend into 8 virtual host devices (the
same configuration the test conftest uses — no TPU hardware needed),
runs the mixed-shape engine-smoke corpus through the production
``wgl.check_batch`` path once WITHOUT a mesh and once sharded over the
forced 8-device mesh (``JEPSEN_TPU_ENGINE_MESH``), on both kernel
routes (dense automaton; generic frontier via an explicit closure
cap) plus a tiny-frontier escalation config, and fails loudly on:

- ANY divergence between the sharded and single-device result dicts —
  byte-identical verdicts, engines, kernels, and failure events (the
  acceptance gate: sharding must never move a verdict);
- missing per-device telemetry: the sharded run must record a
  ``jepsen_engine_device_occupancy_ratio`` gauge for every device and
  a nonzero ``jepsen_engine_shard_pad_rows_total`` (the corpus is
  deliberately non-divisible);
- a per-chip budget breach: no compiled fn's peak in-flight per-chip
  rows may exceed its single-chip cap (the executor's
  ``chip_row_accounting`` hook — checked here end-to-end and in
  tests/test_engine.py at the unit level).

Wired into ``make mesh-smoke`` / ``make check`` so a refactor that
skews sharded verdicts (or silently stops sharding) breaks CI, not a
multichip capture window rounds later.

Exit codes: 0 ok, 1 divergence or missing metrics.
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    from jepsen_tpu.platform import force_cpu_platform

    force_cpu_platform(8)

    from jepsen_tpu import models as m
    from jepsen_tpu import obs
    from jepsen_tpu.engine.smoke import _corpus
    from jepsen_tpu.ops import wgl

    hists = _corpus()
    model = m.cas_register(0)

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # both kernel routes + the escalation ladder; max_dispatch=4 forces
    # several chunks per bucket so the window genuinely fills and the
    # per-chip chunk caps actually engage
    configs = {
        "dense": dict(slot_cap=32, max_dispatch=4),
        "frontier": dict(slot_cap=32, max_dispatch=4, max_closure=9),
        "escalation": dict(slot_cap=6, frontier=8, escalation=(4,),
                           max_closure=7),
    }
    for name, kw in configs.items():
        os.environ["JEPSEN_TPU_ENGINE_MESH"] = "0"
        single = wgl.check_batch(model, hists, **kw)

        os.environ["JEPSEN_TPU_ENGINE_MESH"] = "1"
        obs.enable(reset=True)
        sharded = wgl.check_batch(model, hists, **kw)
        check(
            sharded == single,
            f"{name}: sharded result dicts diverge from single-device "
            f"(first mismatch: "
            f"{next(((a, b) for a, b in zip(sharded, single) if a != b), None)})",
        )
        reg = obs.registry()
        occ = [
            reg.value("jepsen_engine_device_occupancy_ratio",
                      device=str(d))
            for d in range(8)
        ]
        check(
            all(v is not None for v in occ),
            f"{name}: missing per-device occupancy gauges (got {occ})",
        )
        pad = reg.value("jepsen_engine_shard_pad_rows_total")
        check(
            (pad or 0) > 0,
            f"{name}: non-divisible corpus recorded no shard pad rows",
        )
        obs.enable(reset=True)

    # per-chip budget end-to-end: drive the executor directly (the
    # daemon composition) so its accounting hook is inspectable
    from jepsen_tpu.engine import execution, planning
    from jepsen_tpu.parallel import mesh as mesh_mod

    os.environ["JEPSEN_TPU_ENGINE_MESH"] = "0"
    mesh = mesh_mod.default_mesh()
    ctx = planning.RunContext(model, hists)
    planner = planning.Planner(
        model, spec=ctx.spec, slot_cap=32, frontier=64, max_closure=9,
        max_dispatch=8, n_devices=mesh.devices.size,
    )
    ex = execution.Executor(4, mesh=mesh, max_dispatch=8)
    for pb in planner.stream(ctx):
        ex.submit(pb)
    ex.drain()
    ctx.drain_oracles()
    check(ex.n_devices == 8, f"executor mesh lost ({ex.n_devices} devices)")
    for acct in ex.chip_row_accounting.values():
        cap = acct["chip_cap"]
        if acct["kernel"] == "dense":
            # multi-in-flight dense dispatch is the measured bench
            # pattern: up to window × the per-chip cap by design
            cap *= ex.window_size
        check(
            acct["peak_chip_rows"] <= cap,
            f"per-chip budget breached: {acct}",
        )
    check(
        any(a["kernel"] == "frontier"
            for a in ex.chip_row_accounting.values()),
        "budget probe never dispatched a frontier chunk",
    )
    os.environ.pop("JEPSEN_TPU_ENGINE_MESH", None)

    if failures:
        for f_ in failures:
            print(f"mesh-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "mesh-smoke: ok (8-device host mesh, dense + frontier + "
        f"escalation routes, {len(hists)} mixed-shape histories, "
        "verdicts byte-identical to single-device, per-chip budgets held)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
