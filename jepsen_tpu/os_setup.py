"""OS provisioning (reference: jepsen/src/jepsen/os.clj:4-16 protocol;
os/debian.clj, os/centos.clj, os/ubuntu.clj, os/smartos.clj).

Sets up hostfiles, installs base packages, disables unattended upgrades —
the pre-DB groundwork each node needs.
"""

from __future__ import annotations

import logging
from typing import Any, Iterable, List, Optional

from . import control
from .control.core import RemoteError, lit
from .control.util import meh

log = logging.getLogger("jepsen_tpu.os")


class OS:
    """(reference: os.clj:4-8)"""

    def setup(self, test: dict, node: Any) -> None:
        pass

    def teardown(self, test: dict, node: Any) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS()


def setup_hostfile(test: dict, node: Any) -> None:
    """Write /etc/hosts entries for every test node.
    (reference: os/debian.clj:13-26 setup-hostfile!)"""
    lines = ["127.0.0.1 localhost"]
    for n in test["nodes"]:
        try:
            from .net import node_ip

            ip = node_ip(n)
        except Exception:
            ip = str(n)
        lines.append(f"{ip} {n}")
    content = "\n".join(lines) + "\n"
    with control.su():
        from .control.util import write_file

        write_file(content, "/etc/hosts")


class Debian(OS):
    """(reference: os/debian.clj)"""

    def __init__(self, extra_packages: Iterable[str] = ()):
        self.extra_packages = list(extra_packages)

    base_packages = [
        "curl",
        "faketime",
        "iptables",
        "iputils-ping",
        "logrotate",
        "man-db",
        "net-tools",
        "ntpdate",
        "psmisc",
        "rsyslog",
        "sudo",
        "tar",
        "unzip",
        "wget",
    ]

    def setup(self, test, node):
        setup_hostfile(test, node)
        with control.su():
            # stop unattended upgrades from holding the dpkg lock
            meh(lambda: control.execute("systemctl", "stop", "unattended-upgrades", check=False))
            control.execute(
                "env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
                "-y", "--no-install-recommends",
                *(self.base_packages + self.extra_packages),
            )

    def install(self, packages: Iterable[str]) -> None:
        """(reference: os/debian.clj install)"""
        with control.su():
            control.execute(
                "env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
                "-y", "--no-install-recommends", *packages,
            )

    def installed_version(self, package: str) -> str:
        return control.execute(
            "dpkg-query", "-W", "-f", "${Version}", package
        )


debian = Debian()


class CentOS(OS):
    """(reference: os/centos.clj)"""

    base_packages = [
        "curl",
        "iptables",
        "iputils",
        "logrotate",
        "man-db",
        "net-tools",
        "ntpdate",
        "psmisc",
        "rsyslog",
        "sudo",
        "tar",
        "unzip",
        "wget",
    ]

    def setup(self, test, node):
        setup_hostfile(test, node)
        with control.su():
            control.execute("yum", "install", "-y", *self.base_packages)

    def install(self, packages: Iterable[str]) -> None:
        with control.su():
            control.execute("yum", "install", "-y", *packages)


centos = CentOS()


class Ubuntu(Debian):
    """Ubuntu = Debian + snapd/cloud-init quirks handled.
    (reference: os/ubuntu.clj:14-46)"""

    def setup(self, test, node):
        with control.su():
            meh(lambda: control.execute("systemctl", "stop", "snapd", check=False))
        super().setup(test, node)


ubuntu = Ubuntu()


class SmartOS(OS):
    """SmartOS (illumos) boxes: pkgin package management, svcadm-managed
    ipfilter, and a /etc/hosts loopback entry for the local hostname.
    (reference: os/smartos.clj)"""

    base_packages = [
        "wget",
        "curl",
        "vim",
        "unzip",
        "rsyslog",
        "logrotate",
    ]

    #: re-run `pkgin update` when the package DB is older than a day
    update_interval_s = 86_400

    def _setup_hostfile(self) -> None:
        """Append the local hostname to the 127.0.0.1 line if missing.
        (reference: smartos.clj:12-25 setup-hostfile!)"""
        import re as _re

        name = control.execute("hostname")
        hosts = control.execute("cat", "/etc/hosts")
        out = []
        for line in hosts.splitlines():
            # whole-token comparison: a hostname that happens to be a
            # substring of an alias must still be appended
            if _re.match(r"^127\.0\.0\.1\s", line) and name not in line.split():
                line = f"{line} {name}"
            out.append(line)
        with control.su():
            from .control.util import write_file

            write_file("\n".join(out) + "\n", "/etc/hosts")

    def _maybe_update(self) -> None:
        """pkgin update unless done recently.
        (reference: smartos.clj:27-43)"""
        try:
            now = int(control.execute("date", "+%s"))
            last = int(
                control.execute("stat", "-c", "%Y", "/var/db/pkgin/sql.log")
            )
            stale = self.update_interval_s < now - last
        except Exception:
            stale = True
        if stale:
            with control.su():
                control.execute("pkgin", "update")

    def installed(self, packages: Iterable[str]) -> set:
        """Subset of ``packages`` already installed, by pkgin list.
        (reference: smartos.clj:45-56 installed)"""
        return {str(p) for p in packages} & set(self._versions())

    def _versions(self) -> dict:
        """{package: installed version} from one pkgin list fetch."""
        out = {}
        for line in control.execute("pkgin", "-p", "list").splitlines():
            pkg = line.split(";", 1)[0]
            if "-" not in pkg:
                continue
            name, version = pkg.rsplit("-", 1)
            out[name] = version
        return out

    def installed_version(self, package: str) -> Optional[str]:
        """(reference: smartos.clj:72-84)"""
        return self._versions().get(str(package))

    def install(self, packages) -> None:
        """Install a collection of packages, or a {package: version}
        map.  (reference: smartos.clj:86-107)"""
        if isinstance(packages, dict):
            # one pkgin list fetch for all the version comparisons, not
            # one remote round-trip per package
            versions = self._versions()
            todo = [
                f"{pkg}-{version}"
                for pkg, version in packages.items()
                if versions.get(str(pkg)) != version
            ]
            if todo:
                with control.su():
                    control.execute("pkgin", "-y", "install", *todo)
            return
        missing = {str(p) for p in packages} - self.installed(packages)
        if missing:
            with control.su():
                control.execute("pkgin", "-y", "install", *sorted(missing))

    def uninstall(self, packages) -> None:
        """(reference: smartos.clj:58-63)"""
        pkgs = packages if isinstance(packages, (list, tuple, set)) else [packages]
        present = self.installed(pkgs)
        if present:
            with control.su():
                control.execute("pkgin", "-y", "remove", *sorted(present))

    def setup(self, test, node):
        self._setup_hostfile()
        self._maybe_update()
        self.install(self.base_packages)
        with control.su():
            control.execute("svcadm", "enable", "-r", "ipfilter")
        if test.get("net") is not None:
            meh(lambda: test["net"].heal(test))

    def teardown(self, test, node):
        pass


smartos = SmartOS()
