"""OS provisioning (reference: jepsen/src/jepsen/os.clj:4-16 protocol;
os/debian.clj, os/centos.clj, os/ubuntu.clj, os/smartos.clj).

Sets up hostfiles, installs base packages, disables unattended upgrades —
the pre-DB groundwork each node needs.
"""

from __future__ import annotations

import logging
from typing import Any, Iterable, List

from . import control
from .control.core import RemoteError, lit
from .control.util import meh

log = logging.getLogger("jepsen_tpu.os")


class OS:
    """(reference: os.clj:4-8)"""

    def setup(self, test: dict, node: Any) -> None:
        pass

    def teardown(self, test: dict, node: Any) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS()


def setup_hostfile(test: dict, node: Any) -> None:
    """Write /etc/hosts entries for every test node.
    (reference: os/debian.clj:13-26 setup-hostfile!)"""
    lines = ["127.0.0.1 localhost"]
    for n in test["nodes"]:
        try:
            from .net import node_ip

            ip = node_ip(n)
        except Exception:
            ip = str(n)
        lines.append(f"{ip} {n}")
    content = "\n".join(lines) + "\n"
    with control.su():
        from .control.util import write_file

        write_file(content, "/etc/hosts")


class Debian(OS):
    """(reference: os/debian.clj)"""

    def __init__(self, extra_packages: Iterable[str] = ()):
        self.extra_packages = list(extra_packages)

    base_packages = [
        "curl",
        "faketime",
        "iptables",
        "iputils-ping",
        "logrotate",
        "man-db",
        "net-tools",
        "ntpdate",
        "psmisc",
        "rsyslog",
        "sudo",
        "tar",
        "unzip",
        "wget",
    ]

    def setup(self, test, node):
        setup_hostfile(test, node)
        with control.su():
            # stop unattended upgrades from holding the dpkg lock
            meh(lambda: control.execute("systemctl", "stop", "unattended-upgrades", check=False))
            control.execute(
                "env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
                "-y", "--no-install-recommends",
                *(self.base_packages + self.extra_packages),
            )

    def install(self, packages: Iterable[str]) -> None:
        """(reference: os/debian.clj install)"""
        with control.su():
            control.execute(
                "env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
                "-y", "--no-install-recommends", *packages,
            )

    def installed_version(self, package: str) -> str:
        return control.execute(
            "dpkg-query", "-W", "-f", "${Version}", package
        )


debian = Debian()


class CentOS(OS):
    """(reference: os/centos.clj)"""

    base_packages = [
        "curl",
        "iptables",
        "iputils",
        "logrotate",
        "man-db",
        "net-tools",
        "ntpdate",
        "psmisc",
        "rsyslog",
        "sudo",
        "tar",
        "unzip",
        "wget",
    ]

    def setup(self, test, node):
        setup_hostfile(test, node)
        with control.su():
            control.execute("yum", "install", "-y", *self.base_packages)

    def install(self, packages: Iterable[str]) -> None:
        with control.su():
            control.execute("yum", "install", "-y", *packages)


centos = CentOS()


class Ubuntu(Debian):
    """Ubuntu = Debian + snapd/cloud-init quirks handled.
    (reference: os/ubuntu.clj:14-46)"""

    def setup(self, test, node):
        with control.su():
            meh(lambda: control.execute("systemctl", "stop", "snapd", check=False))
        super().setup(test, node)


ubuntu = Ubuntu()
