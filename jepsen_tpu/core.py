"""Test lifecycle orchestration (reference: jepsen/src/jepsen/core.clj).

``run(test)`` drives the full lifecycle: prepare, OS/DB setup (when a
remote control plane is configured), client+nemesis setup, the
interpreter, analysis, and persistence:

    run! (core.clj:327) → prepare-test:311 → with-os/with-db:93-181
    → run-case!:214 (client+nemesis setup/teardown:183-212 around
      generator.interpreter/run!) → analyze!:221 → log-results:239

In-process tests use a dummy remote + fake clients and skip OS/DB setup,
exactly like the reference's ``:ssh {:dummy? true}`` mode
(control.clj:40, core_test.clj:55-120).
"""

from __future__ import annotations

import datetime
import logging
import threading
from typing import Any, Optional

from . import checker as checker_mod
from . import client as client_mod
from . import interpreter
from . import nemesis as nemesis_mod
from . import obs
from .history import History
from .util import real_pmap, with_relative_time

log = logging.getLogger("jepsen_tpu.core")


class Synchronizer:
    """A reusable barrier for :conn-barrier style cross-node sync during
    DB setup (reference: core.clj:44-57 synchronize)."""

    def __init__(self, parties: int):
        self.barrier = threading.Barrier(parties)

    def synchronize(self, timeout: Optional[float] = None):
        self.barrier.wait(timeout)


def prepare_test(test: dict) -> dict:
    """Fill in start-time, barrier, default keys.
    (reference: core.clj:311-325)"""
    test = dict(test)
    test.setdefault("start-time", datetime.datetime.now().strftime("%Y%m%dT%H%M%S.%f")[:-3])
    test.setdefault("nodes", ["n1", "n2", "n3", "n4", "n5"])
    test.setdefault("concurrency", len(test["nodes"]))
    test.setdefault("barrier", Synchronizer(len(test["nodes"])))
    test.setdefault("checker", checker_mod.unbridled_optimism())
    test.setdefault("nemesis", nemesis_mod.noop())
    test.setdefault("client", client_mod.noop())
    return test


def run_case(test: dict) -> History:
    """Set up nemesis + per-node clients, run the interpreter, tear down.
    (reference: core.clj:183-218)"""
    client = test["client"]
    nemesis = nemesis_mod.validate(test["nemesis"])

    nemesis = nemesis.setup(test)
    test = {**test, "nemesis": nemesis}

    # Track successfully-opened clients even if a later node's open
    # raises, so teardown ALWAYS covers what was opened (reference
    # guarantees teardown of both nemesis and clients, core.clj:183-212).
    clients: list = []
    clients_lock = threading.Lock()

    def open_and_setup(node):
        c = client.open(test, node)
        with clients_lock:
            clients.append((c, node))
        c.setup(test)
        return c

    try:
        with obs.span("setup", cat="phase"):
            real_pmap(open_and_setup, test["nodes"])
        with obs.span("generator", cat="phase"):
            return interpreter.run(test)
    finally:
        with obs.span("teardown", cat="phase"):
            try:
                nemesis.teardown(test)
            finally:

                def teardown_and_close(cn):
                    c, _node = cn
                    try:
                        c.teardown(test)
                    finally:
                        c.close(test)

                with clients_lock:
                    opened = list(clients)
                real_pmap(teardown_and_close, opened)


_snarf_lock = threading.Lock()


def snarf_logs(test: dict) -> None:
    """Download every DB log file (db.LogFiles) from every node into the
    test's store directory under ``<store>/<node>/<short-path>``, where
    short paths drop the nodes' common directory prefix.  Worker crashes
    and missing files are tolerated per-file so one broken node can't
    lose the others' logs.  (reference: core.clj:102-135 snarf-logs!)"""
    from . import control
    from . import db as db_mod
    from . import store as store_mod

    db = test.get("db")
    if not isinstance(db, db_mod.LogFiles) or not test.get("store?", True):
        return

    with _snarf_lock, obs.span("snarf-logs", cat="phase"):
        log.info("Snarfing log files")

        def snarf_node(test, node):
            try:
                full_paths = [str(p) for p in db.log_files(test, node)]
            except Exception:
                log.exception("couldn't list log files on %s", node)
                return
            if not full_paths:
                return
            from .util import drop_common_proper_prefix

            shorts = [
                "/".join(parts)
                for parts in drop_common_proper_prefix(
                    [p.split("/") for p in full_paths]
                )
            ]
            import subprocess

            from .control import RemoteError

            transfer_errors = (
                FileNotFoundError,
                RemoteError,
                # docker/k8s remotes raise CalledProcessError when cp
                # fails; the ssh transports wrap scp failures in
                # RuntimeError
                subprocess.CalledProcessError,
                RuntimeError,
            )
            for remote, short in zip(full_paths, shorts):
                dest = store_mod.path_(
                    test, str(node), short.lstrip("/")
                )
                try:
                    control.download(remote, dest)
                except transfer_errors as e:
                    # tolerate vanished remote files / broken transfers
                    # (reference tolerates pipe-closed and not-yet-created
                    # files, core.clj:119-134); local store errors like a
                    # full disk still propagate
                    log.info("couldn't download %s from %s: %s", remote, node, e)

        control.on_nodes(test, snarf_node)
        # an aborted run never reaches save_1, so refresh the symlinks
        # here too (reference: core.clj:135 update-symlinks!)
        store_mod.update_symlinks(test)


def maybe_snarf_logs(test: dict) -> None:
    """snarf_logs, swallowing everything — used on the abort path where
    a snarf error must not supersede the root cause.
    (reference: core.clj:137-148 maybe-snarf-logs!)"""
    try:
        snarf_logs(test)
    except Exception:
        log.exception("Error snarfing logs")


def analyze(test: dict) -> dict:
    """Index the history, run checkers, attach results.
    (reference: core.clj:221-237)"""
    with obs.span("analyze", cat="phase"):
        history = test["history"]
        if isinstance(history, History):
            history.index_ops()
        results = checker_mod.check_safe(
            test["checker"], test, history, {}
        )
    return {**test, "results": results}


def log_results(test: dict) -> dict:
    """(reference: core.clj:239-253)"""
    r = test.get("results", {})
    verdict = r.get("valid?")
    if verdict is False:
        log.warning("Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")
    elif verdict == "unknown":
        log.warning("Errors occurred during analysis, but no anomalies found. ಠ~ಠ")
    else:
        log.info("Everything looks good! ヽ(‘ー`)ノ")
    return test


def run(test: dict) -> dict:
    """Full lifecycle; returns the test with :history and :results.
    Persistence is 3-phase (save_0 at start, save_1 once the history is
    durable, save_2 after analysis) unless ``store?`` is False.
    (reference: core.clj:327-406 + store.clj:413-456)"""
    from contextlib import nullcontext

    from . import store as store_mod

    test = prepare_test(test)
    storing = test.get("store?", True)

    # observability (jepsen_tpu.obs): default on, per-test opt-out via
    # obs? (the CLI's --no-obs / JEPSEN_TPU_OBS=0).  Each run resets
    # the process-global tracer+registry so a prior in-process run's
    # spans can't leak into this run's exports.
    observing = bool(test.get("obs?", obs.default_enabled()))
    if observing:
        obs.enable(reset=True)
    else:
        obs.disable()

    # span tracing turns on for the run — not at test-build time, so
    # building several test maps can't cross-wire each other's
    # exporters through the process-global tracer — and off again
    # after it, so later runs in the same process don't inherit a
    # stale exporter (trace.wire stores the endpoint; the reference
    # configures its tracer once per run, dgraph/core.clj:118)
    tracing_endpoint = test.get("tracing")
    if tracing_endpoint:
        from . import trace

        trace.tracing(tracing_endpoint)

    if storing:
        store_mod.start_logging(test, test.get("logging-json?", False))
    try:
        writer_ctx = (
            store_mod.with_writer(test) if storing else nullcontext(test)
        )
        with writer_ctx as test:
            if storing:
                test = store_mod.save_0(test)
            try:
                test = _run_body(test)
            except BaseException:
                # abort path: the spans recorded up to the crash are the
                # flight recorder's whole point — export them best-effort
                # (like maybe_snarf_logs) without superseding the cause
                if observing:
                    try:
                        _finish_obs(test, storing)
                    except Exception:
                        log.exception("obs export failed on abort")
                raise
            if observing:
                test = _finish_obs(test, storing)
            if storing:
                test = store_mod.save_2(test)
            return log_results(test)
    finally:
        if tracing_endpoint:
            trace.tracing()
        if storing:
            store_mod.stop_logging(test)


def _finish_obs(test: dict, storing: bool) -> dict:
    """Distill the run's spans+metrics: summary dict into
    ``results["obs"]`` (durable via save_2) and ``test["obs-summary"]``
    (for the CLI breakdown table), artifact files (Chrome trace,
    span JSONL, Prometheus dump) into the store directory."""
    from . import store as store_mod

    summary = obs.summary()
    results = test.get("results")
    if isinstance(results, dict):
        test = {**test, "results": {**results, "obs": summary}}
    test = {**test, "obs-summary": summary}
    if storing:
        try:
            paths = obs.export_all(store_mod.test_dir(test))
            log.info("Wrote trace artifacts: %s", sorted(paths.values()))
        except Exception:
            # telemetry must never fail a run that already has results
            # (any export error — full disk, a serialization surprise —
            # would otherwise abort before save_2 writes results.json)
            log.exception("obs export failed")
    return test


def _run_body(test: dict) -> dict:
    """OS/DB setup, the run itself, history save, analysis."""
    from . import db as db_mod
    from . import store as store_mod

    storing = test.get("store?", True)
    db = test.get("db")
    os_ = test.get("os")
    control_ctx = _control_context(test)
    with control_ctx:
        if os_ is not None:
            with obs.span("os-setup", cat="phase"):
                _on_nodes(test, lambda node: os_.setup(test, node))
        if db is not None:
            with obs.span("db-start", cat="phase"):
                db_mod.cycle(test)
        try:
            try:
                with with_relative_time():
                    # anchor span timestamps to the history's t=0 so
                    # exports/overlays can align them with op times
                    obs.set_run_anchor()
                    history = run_case(test)
                test = {**test, "history": history}
                if storing:
                    with obs.span("save-history", cat="phase"):
                        test = store_mod.save_1(test)
                result = analyze(test)
            except BaseException:
                # abort path, before DB teardown deletes the logs; must
                # not supersede the root cause (reference: core.clj:150-170
                # with-log-snarfing)
                maybe_snarf_logs(test)
                raise
            # success path: snarf errors (e.g. unwritable store) propagate
            # rather than silently losing all DB logs — but outside the
            # except above, so they can't trigger a second snarf
            snarf_logs(test)
            return result
        finally:
            if db is not None and not test.get("leave-db-running?"):
                with obs.span("db-teardown", cat="phase"):
                    _on_nodes(test, lambda node: db.teardown(test, node))


def _control_context(test: dict):
    """The remote-session context for this test (dummy by default)."""
    from . import control

    remote = test.get("remote")
    if remote is None:
        return control.dummy_session(test)
    return control.with_session(test, remote)


def _on_nodes(test: dict, fn):
    """Run fn on every node concurrently.
    (reference: control.clj:295-311 on-nodes)"""
    from . import control

    return dict(
        zip(
            test["nodes"],
            real_pmap(
                lambda node: control.with_node(node, lambda: fn(node)), test["nodes"]
            ),
        )
    )
