"""libfaketime wrappers: run DB processes on skewed or scaled clocks.

(reference: jepsen/src/jepsen/faketime.clj — builds libfaketime on the
node with make :8-22, script :24-35, wrap! rebinds a binary to run under
faketime :36-55, rand-factor :57-65.)
"""

from __future__ import annotations

from typing import Optional

from . import control
from .control.core import lit
from .control.util import write_file

LIBFAKETIME_URL = (
    "https://github.com/wolfcw/libfaketime/archive/refs/tags/v0.9.10.tar.gz"
)
BUILD_DIR = "/opt/jepsen/faketime"


def install() -> None:
    """Fetch + build libfaketime on the current node (the reference
    builds its fork the same way, faketime.clj:8-22); falls back to a
    distro package if the build fails."""
    from .control.core import RemoteError
    from .control.util import cached_wget, install_archive

    with control.su():
        try:
            install_archive(LIBFAKETIME_URL, BUILD_DIR)
            with control.cd(BUILD_DIR):
                control.execute("make")
                control.execute("make", "install")
        except RemoteError:
            control.execute("apt-get", "install", "-y", "faketime")


def script(offset_s: float = 0.0, rate: Optional[float] = None) -> str:
    """A shell preamble exporting LD_PRELOAD + FAKETIME for child
    processes.  (reference: faketime.clj:24-35)"""
    spec = f"{offset_s:+f}s"
    if rate is not None:
        spec += f" x{rate}"
    return (
        'export LD_PRELOAD="${LD_PRELOAD:+$LD_PRELOAD:}'
        'libfaketime.so.1"\n'
        f'export FAKETIME="{spec}"\n'
        'export FAKETIME_NO_CACHE=1\n'
    )


def wrap(bin_path: str, offset_s: float = 0.0, rate: Optional[float] = None) -> None:
    """Replace a binary with a faketime-launching wrapper script; the
    original moves to <bin>.real.  (reference: faketime.clj:36-55)"""
    real = f"{bin_path}.real"
    with control.su():
        out = control.execute(
            lit(f"test -f {real} && echo yes || echo no")
        )
        if out.strip() != "yes":
            control.execute("mv", bin_path, real)
        wrapper = "#!/bin/bash\n" + script(offset_s, rate) + f'exec "{real}" "$@"\n'
        write_file(wrapper, bin_path)
        control.execute("chmod", "+x", bin_path)


def unwrap(bin_path: str) -> None:
    """Restore the original binary."""
    real = f"{bin_path}.real"
    with control.su():
        control.execute(
            lit(f"test -f {real} && mv {real} {bin_path} || true")
        )


def rand_factor(rng=None) -> float:
    """A random clock rate in [1/5, 5], log-uniform.
    (reference: faketime.clj:57-65)"""
    import math
    import random as _random

    rng = rng or _random
    return math.exp(rng.uniform(math.log(0.2), math.log(5.0)))
