"""Backend/platform selection guards.

The environment's TPU plugin (a sitecustomize hook) forces
``JAX_PLATFORMS`` to its own platform regardless of env vars, so a plain
environment override cannot select the CPU backend.  The working recipe,
shared by the test conftest, the driver entrypoints, and the benchmark's
fallback path, is:

1. set ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` *before*
   the first backend use (required for virtual CPU devices to apply), and
2. override ``jax_platforms`` via ``jax.config`` *after* importing jax.
"""

from __future__ import annotations

import os
import re
from typing import Optional


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Force jax onto the CPU backend, optionally with ``n_devices``
    virtual host devices.  Must run before the first jax backend use
    (device queries, array ops); importing jax beforehand is fine."""
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # rewrite a stale value (e.g. =1 inherited from another harness)
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")


#: the probe EXECUTES a computation, not just a device query: a wedged
#: tunnel can initialize its backend fine and then hang at remote
#: compile, so listing devices reports healthy while every dispatch
#: blocks forever
_PROBE_SRC = (
    "import jax, jax.numpy as jnp, sys; "
    "ds = jax.devices(); "
    "accel = any(d.platform not in ('cpu',) for d in ds); "
    "jax.jit(lambda x: x + 1)(jnp.ones((8, 8))).block_until_ready(); "
    "sys.exit(0 if accel else 3)"
)

import threading as _threading

#: memoized accelerator probe verdict (None = not probed yet)
_accelerator_ok: Optional[bool] = None
_accelerator_error: Optional[str] = None
_probe_lock = _threading.Lock()


def forget_probe() -> None:
    """Drop the memoized probe verdict so the next probe_accelerator
    call re-probes.  Long-lived watchers need this: the memoization
    exists so one *test run* shares a verdict, but a process polling
    for the TPU tunnel to come back must ask fresh every time."""
    global _accelerator_ok, _accelerator_error
    with _probe_lock:
        _accelerator_ok, _accelerator_error = None, None


def probe_accelerator(
    retries: Optional[int] = None,
    timeout_s: Optional[float] = None,
    backoff_s: float = 5.0,
):
    """Probe (in subprocesses, so a hung backend can't wedge us) whether
    a non-CPU jax backend initializes AND executes.  Returns
    ``(ok, error_message)``; the verdict is memoized process-wide and
    thread-safe — concurrent callers share one probe.

    Crashes and hangs retry with backoff (the environment's device
    plugin can flake once at init); a clean "no accelerator present"
    answer (exit 3) is deterministic and returns immediately."""
    global _accelerator_ok, _accelerator_error
    # double-checked memo: the unlocked fast path reads a pair that is
    # only ever written once, under _probe_lock, before any reader can
    # observe _accelerator_ok non-None
    if _accelerator_ok is not None:  # jt: allow[concurrency-guard-drift] — double-checked fast path (see above)
        return _accelerator_ok, _accelerator_error  # jt: allow[concurrency-guard-drift] — double-checked fast path
    with _probe_lock:
        if _accelerator_ok is not None:
            return _accelerator_ok, _accelerator_error
        import subprocess
        import sys
        import time

        if retries is None:
            retries = int(os.environ.get("JEPSEN_TPU_PROBE_RETRIES", 3))
        if timeout_s is None:
            timeout_s = float(os.environ.get("JEPSEN_TPU_PROBE_TIMEOUT", 90))
        err = None
        for attempt in range(retries):
            t0 = time.time()
            try:
                r = subprocess.run(
                    [sys.executable, "-c", _PROBE_SRC],
                    timeout=timeout_s,
                    capture_output=True,
                    text=True,
                )
                if r.returncode == 0:
                    _trail(attempt, "ok", time.time() - t0)
                    _accelerator_ok, _accelerator_error = True, None
                    return True, None
                if r.returncode == 3:
                    _trail(attempt, "no-accelerator", time.time() - t0)
                    _accelerator_ok = False
                    _accelerator_error = "no accelerator device present"
                    return False, _accelerator_error
                tail = (r.stderr or "").strip().splitlines()
                err = tail[-1][:300] if tail else f"probe exit {r.returncode}"
            except subprocess.TimeoutExpired:
                err = f"backend init timed out after {timeout_s:g}s"
            except Exception as e:  # noqa: BLE001 — must never raise
                err = repr(e)[:300]
            _trail(attempt, err, time.time() - t0)
            if attempt < retries - 1:
                time.sleep(backoff_s * (attempt + 1))
        _accelerator_ok, _accelerator_error = False, err or "probe never ran"
        return False, _accelerator_error


def _trail(attempt: int, outcome: str, elapsed_s: float) -> None:
    """Append one probe-attempt record to the JSONL diagnostics trail
    (JEPSEN_TPU_PROBE_TRAIL=path to enable).  The bench points this at
    a per-round file so a wedged-tunnel round leaves evidence of every
    attempt, not one terse error string."""
    path = os.environ.get("JEPSEN_TPU_PROBE_TRAIL")
    if not path:
        return
    try:
        import datetime
        import json

        with open(path, "a") as f:
            f.write(
                json.dumps(
                    {
                        "ts": datetime.datetime.now(
                            datetime.timezone.utc
                        ).isoformat(timespec="seconds"),
                        "attempt": attempt,
                        "outcome": str(outcome)[:300],
                        "elapsed_s": round(elapsed_s, 1),
                        "pid": os.getpid(),
                    }
                )
                + "\n"
            )
    except OSError:
        pass


def accelerator_usable(timeout_s: Optional[float] = None) -> bool:
    """Boolean view of :func:`probe_accelerator`."""
    return probe_accelerator(timeout_s=timeout_s)[0]


def ensure_usable_backend() -> None:
    """Force the CPU platform when no usable accelerator is present.
    Safe to call repeatedly; a no-op when the platform is already
    pinned to CPU (no probe cost) or the backend is initialized with a
    live accelerator."""
    try:
        import jax

        if jax.config.jax_platforms == "cpu":
            return  # already pinned (e.g. by the test conftest)
    except Exception:
        pass
    ok, err = probe_accelerator()
    if not ok:
        import logging

        logging.getLogger(__name__).warning(
            "accelerator unusable (%s); analysis plane pinned to CPU", err
        )
        try:
            force_cpu_platform()
        except Exception:
            pass  # backend already initialized; nothing to rescue
