"""Backend/platform selection guards.

The environment's TPU plugin (a sitecustomize hook) forces
``JAX_PLATFORMS`` to its own platform regardless of env vars, so a plain
environment override cannot select the CPU backend.  The working recipe,
shared by the test conftest, the driver entrypoints, and the benchmark's
fallback path, is:

1. set ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` *before*
   the first backend use (required for virtual CPU devices to apply), and
2. override ``jax_platforms`` via ``jax.config`` *after* importing jax.
"""

from __future__ import annotations

import os
import re
from typing import Optional


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Force jax onto the CPU backend, optionally with ``n_devices``
    virtual host devices.  Must run before the first jax backend use
    (device queries, array ops); importing jax beforehand is fine."""
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # rewrite a stale value (e.g. =1 inherited from another harness)
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
