"""Value serialization for client payloads.

(reference: jepsen/src/jepsen/codec.clj:9-29 — edn↔bytes; here
JSON-with-tuples, the Python-native equivalent.)
"""

from __future__ import annotations

import json
from typing import Any


def _encode_value(v: Any) -> Any:
    if isinstance(v, tuple):
        return {"__tuple__": [_encode_value(x) for x in v]}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _encode_value(x) for k, x in v.items()}
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if set(v.keys()) == {"__tuple__"}:
            return tuple(_decode_value(x) for x in v["__tuple__"])
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def encode(value: Any) -> bytes:
    """(reference: codec.clj:9-16)"""
    if value is None:
        return b""
    return json.dumps(_encode_value(value)).encode()


def decode(data: bytes) -> Any:
    """(reference: codec.clj:17-29)"""
    if not data:
        return None
    return _decode_value(json.loads(data.decode()))
