"""Thread-safe auto-reopening connection wrapper.

(reference: jepsen/src/jepsen/reconnect.clj — wrapper :16-54, open!
:55-77, reopen! :78-90, with-conn retry semantics :90-146.)  Used by DB
clients whose connections break mid-test.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class Wrapper:
    def __init__(
        self,
        open_fn: Callable[[], Any],
        close_fn: Callable[[Any], None] = lambda conn: None,
        name: str = "conn",
        log: bool = True,
    ):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.name = name
        self.lock = threading.RLock()
        self.conn: Optional[Any] = None

    def open(self) -> "Wrapper":
        with self.lock:
            if self.conn is None:
                self.conn = self.open_fn()
        return self

    def close(self) -> None:
        with self.lock:
            if self.conn is not None:
                try:
                    self.close_fn(self.conn)
                finally:
                    self.conn = None

    def reopen(self) -> None:
        """(reference: reconnect.clj:78-90)"""
        with self.lock:
            self.close()
            self.conn = self.open_fn()

    def with_conn(self, fn: Callable[[Any], Any], retries: int = 1) -> Any:
        """Run fn(conn); on failure reopen and retry up to `retries`
        times before re-raising."""
        attempt = 0
        while True:
            with self.lock:
                if self.conn is None:
                    self.conn = self.open_fn()
                conn = self.conn
            try:
                return fn(conn)
            except Exception:
                attempt += 1
                try:
                    self.reopen()
                except Exception:
                    pass
                if attempt > retries:
                    raise


def wrapper(open_fn, close_fn=lambda c: None, **kw) -> Wrapper:
    return Wrapper(open_fn, close_fn, **kw)
