"""The interpreter: executes a generator against real worker threads,
building the history.

(reference: jepsen/src/jepsen/generator/interpreter.clj — Worker protocol
:19-31, ClientWorker re-open logic :33-67, worker thread loop :99-164,
scheduler loop :181-292, crash-to-:info conversion :142-157,
max-pending-interval :166-170.)

One thread per worker (concurrency clients + 1 nemesis) with a
size-1 in-queue each and a shared completion queue; a single scheduler
thread drives the generator, dispatches invocations, applies completions,
and retires crashed processes.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Dict, List, Optional

from . import client as client_mod
from . import generator as gen
from . import obs
from .history import History, NEMESIS, Op
from .util import relative_time_nanos

#: Max micros to wait before re-polling a pending generator
#: (reference: interpreter.clj:166-170)
MAX_PENDING_INTERVAL_US = 1000

#: Live shipper: bounded buffer between the scheduler and the shipper
#: thread; a full buffer drops (and counts) rather than blocking the
#: workload (doc/checker-service.md "Online checking")
LIVE_BUFFER_OPS = 4096
#: Live shipper: max events shipped in one ``/feed`` append
LIVE_BATCH_OPS = 64


def live_enabled() -> bool:
    """``JEPSEN_TPU_LIVE=1`` opts the interpreter into shipping history
    events to the resident checker daemon as they land, so ``/watch``
    subscribers see verdicts while the workload is still running."""
    import os

    return os.environ.get("JEPSEN_TPU_LIVE", "") == "1"


class _LiveShipper:
    """Ships history events to a daemon feed session as they land.

    Contract with the workload: **never block, never fail.**
    :meth:`offer` is a ``put_nowait`` off the scheduler loop — a full
    buffer or a dead daemon drops events (counted as
    ``jepsen_feed_drops_total``) instead of applying backpressure to op
    timing, and every daemon error is swallowed after counting.  The
    post-hoc checker stays the authority on the verdict either way;
    the feed only buys earlier detection.

    Ships BOTH invocations and completions, in history-append order:
    the daemon's incremental probe needs the real concurrency
    structure, and serializing inv/comp pairs would narrow
    linearization windows into false violations.
    """

    #: consecutive append failures before the shipper gives up for the
    #: rest of the run (the resilient client already retried each one)
    MAX_STRIKES = 3

    def __init__(self, model):
        from .serve import client as serve_client

        self._serve_client = serve_client
        self._q = queue.Queue(maxsize=LIVE_BUFFER_OPS)
        self._closing = threading.Event()
        self._client = serve_client.ServiceClient(timeout=5.0)
        self._model = model
        self._session = None
        self._dead = threading.Event()
        self.final_results: Optional[list] = None
        self._thread = threading.Thread(
            target=self._run, name="jepsen-live-shipper", daemon=True
        )
        self._thread.start()

    def offer(self, op: dict) -> None:
        """Enqueue one history event (scheduler thread; never blocks)."""
        if self._dead.is_set() or not isinstance(op.get("process"), int):
            return  # nemesis/system events aren't model operations
        import time as _time

        try:
            self._q.put_nowait((_time.time(), op))
        except queue.Full:
            obs.count("jepsen_feed_drops_total")

    def close(self, wait_s: float = 10.0) -> None:
        """Flush the buffer and close the feed session, bounded in time
        — teardown must not hang on a wedged daemon."""
        self._closing.set()
        self._thread.join(timeout=wait_s)

    # ── shipper thread ────────────────────────────────────────────

    def _drain(self, max_n: int):
        batch = []
        while len(batch) < max_n:
            try:
                batch.append(self._q.get_nowait())
            except queue.Empty:
                break
        return batch

    def _run(self):
        import logging
        import time as _time

        log = logging.getLogger("jepsen_tpu.live")
        try:
            self._session = self._client.open_feed(self._model)
        except Exception as e:
            log.info("live feed disabled (no daemon session): %s", e)
            self._dead.set()
            return
        strikes = 0
        while True:
            batch = self._drain(LIVE_BATCH_OPS)
            if not batch:
                if self._closing.is_set():
                    break
                _time.sleep(0.05)
                continue
            t_inv = min(t for t, _ in batch)
            ops = [op for _, op in batch]
            try:
                self._session.append(ops=ops, t_inv=t_inv)
                strikes = 0
            except Exception:
                # the resilient client already retried; this delta is
                # lost to the feed (the post-hoc check still sees it)
                obs.count("jepsen_feed_drops_total", len(ops))
                strikes += 1
                if strikes >= self.MAX_STRIKES:
                    log.info(
                        "live feed gave up after %d failed deltas",
                        strikes)
                    self._dead.set()
                    return
        try:
            self.final_results = self._session.close()
            if self.final_results:
                log.info(
                    "live feed closed: online verdict valid?=%s",
                    self.final_results[-1].get("valid?"))
        except Exception as e:
            log.info("live feed close failed: %s", e)
        finally:
            self._dead.set()


def _live_model(test: dict):
    """The model the live feed probes against: an explicit
    ``test["model"]`` wins, else the checker's (the linearizable
    checker carries one).  None → live shipping stays off."""
    model = test.get("model")
    if model is None:
        model = getattr(test.get("checker"), "model", None)
    return model


def _make_shipper(test: dict) -> Optional[_LiveShipper]:
    if not live_enabled():
        return None
    model = _live_model(test)
    if model is None:
        return None
    try:
        from .serve import protocol

        protocol.model_to_wire(model)  # no wire form → nothing to feed
    except Exception:
        return None
    return _LiveShipper(model)


class ClientWorker:
    """Wraps a client, reopening it when its process changes (unless the
    client is reusable).  (reference: interpreter.clj:33-67)"""

    def __init__(self, node):
        self.node = node
        self.process = None
        self.client: Optional[client_mod.Client] = None

    def invoke(self, test: dict, op: dict) -> dict:
        while True:
            if self.process != op["process"] and not client_mod.is_reusable(
                self.client, test
            ):
                self.close(test)
                try:
                    self.client = client_mod.validate(test["client"]).open(
                        test, self.node
                    )
                    self.process = op["process"]
                except Exception as e:
                    self.client = None
                    return {
                        **op,
                        "type": "fail",
                        "error": ["no-client", str(e)],
                    }
                continue
            return self.client.invoke(test, op)

    def close(self, test: dict) -> None:
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker:
    """(reference: interpreter.clj:69-76)"""

    def invoke(self, test: dict, op: dict) -> dict:
        return test["nemesis"].invoke(test, op)

    def close(self, test: dict) -> None:
        pass


def make_worker(test: dict, worker_id: Any):
    """client for integer ids (round-robin over nodes), nemesis
    otherwise.  (reference: interpreter.clj:80-97)"""
    if isinstance(worker_id, int):
        nodes = test.get("nodes") or [None]
        return ClientWorker(nodes[worker_id % len(nodes)])
    return NemesisWorker()


class _WorkerThread:
    """Thread + queues for one worker.  (reference: interpreter.clj:99-164)"""

    def __init__(self, test: dict, out: "queue.Queue", worker, worker_id):
        self.id = worker_id
        self.inq: "queue.Queue" = queue.Queue(maxsize=1)
        self.test = test
        self.out = out
        self.worker = worker
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-worker-{worker_id}", daemon=True
        )
        self.thread.start()

    def _run(self):
        test, out, worker = self.test, self.out, self.worker
        # captured once per worker thread: with tracing disabled the
        # hot loop below pays exactly this one pre-paid branch per op
        # (no span objects, no counter lookups — tests/test_obs.py
        # asserts zero records allocated)
        tracing = obs.enabled()
        try:
            while True:
                op = self.inq.get()
                try:
                    t = op.get("type")
                    if t == "exit":
                        return
                    elif t == "sleep":
                        import time as _t

                        _t.sleep(op["value"])
                        out.put(op)
                    elif t == "log":
                        import logging

                        logging.getLogger("jepsen_tpu").info(op.get("value"))
                        out.put(op)
                    elif tracing:
                        with obs.span(
                            f"op/{op.get('f')}", cat="op"
                        ) as sp:
                            res = worker.invoke(test, op)
                            sp.set("worker", self.id)
                            # guard non-dict results: telemetry must
                            # not change how a buggy client fails
                            t_res = (
                                res.get("type")
                                if isinstance(res, dict) else "?"
                            )
                            sp.set("type", t_res)
                        obs.count_op(t_res)
                        out.put(res)
                    else:
                        out.put(worker.invoke(test, op))
                except Exception as e:
                    # worker crash ⇒ indeterminate op
                    # (reference: interpreter.clj:142-157)
                    if tracing:
                        obs.count_op("info")
                    out.put(
                        {
                            **op,
                            "type": "info",
                            "exception": traceback.format_exc(),
                            "exception_class": type(e).__name__,
                            "error": f"indeterminate: {e}",
                        }
                    )
        finally:
            try:
                worker.close(test)
            except Exception:
                pass


def goes_in_history(op: dict) -> bool:
    """:sleep and :log ops are not journaled.
    (reference: interpreter.clj:172-179)"""
    return op.get("type") not in ("sleep", "log")


def run(test: dict) -> History:
    """Evaluate all ops from test["generator"] against workers driving
    test["client"] / test["nemesis"]; returns the History.
    (reference: interpreter.clj:181-292)"""
    ctx = gen.context(test)
    worker_ids = gen.all_threads(ctx)
    completions: "queue.Queue" = queue.Queue(maxsize=len(worker_ids))
    workers = [
        _WorkerThread(test, completions, make_worker(test, wid), wid)
        for wid in worker_ids
    ]
    invocations: Dict[Any, "queue.Queue"] = {w.id: w.inq for w in workers}
    g = gen.validate(gen.friendly_exceptions(test.get("generator")))

    outstanding = 0
    poll_timeout_us = 0
    history: List[dict] = []
    # online checking: opt-in shipper feeding the checker daemon a
    # live copy of the history (JEPSEN_TPU_LIVE=1); never blocks the
    # scheduler, never fails the run
    shipper = _make_shipper(test)

    try:
        while True:
            op_done = None
            if poll_timeout_us > 0:
                try:
                    op_done = completions.get(timeout=poll_timeout_us / 1e6)
                except queue.Empty:
                    op_done = None
            else:
                try:
                    op_done = completions.get_nowait()
                except queue.Empty:
                    op_done = None

            if op_done is not None:
                # completion-first: latency sensitive
                # (reference: interpreter.clj:212-241)
                thread = gen.process_to_thread(ctx, op_done.get("process"))
                now = relative_time_nanos()
                op_done = {**op_done, "time": now}
                ctx = {
                    **ctx,
                    "time": now,
                    "free_threads": tuple(ctx["free_threads"]) + (thread,),
                }
                g = gen.update(g, test, ctx, op_done)
                if thread != NEMESIS and op_done.get("type") == "info":
                    workers_map = dict(ctx["workers"])
                    workers_map[thread] = gen.next_process(ctx, thread)
                    ctx = {**ctx, "workers": workers_map}
                if goes_in_history(op_done):
                    history.append(op_done)
                    if shipper is not None:
                        shipper.offer(op_done)
                outstanding -= 1
                poll_timeout_us = 0
                continue

            now = relative_time_nanos()
            ctx = {**ctx, "time": now}
            res = gen.op(g, test, ctx)

            if res is None:
                if outstanding > 0:
                    poll_timeout_us = MAX_PENDING_INTERVAL_US
                    continue
                for q in invocations.values():
                    q.put({"type": "exit"})
                for w in workers:
                    w.thread.join(timeout=10)
                if shipper is not None:
                    shipper.close()
                return _to_history(history)

            op, g2 = res
            if op == gen.PENDING:
                poll_timeout_us = MAX_PENDING_INTERVAL_US
                continue

            if now < op["time"]:
                # not time yet; sleep until then (or a completion)
                poll_timeout_us = max(1, int((op["time"] - now) / 1000))
                continue

            thread = gen.process_to_thread(ctx, op["process"])
            invocations[thread].put(op)
            ctx = {
                **ctx,
                "time": op["time"],
                "free_threads": tuple(
                    t for t in ctx["free_threads"] if t != thread
                ),
            }
            g2 = gen.update(g2, test, ctx, op)
            if goes_in_history(op):
                history.append(op)
                if shipper is not None:
                    shipper.offer(op)
            g = g2
            outstanding += 1
            poll_timeout_us = 0
    except BaseException:
        # abnormal exit: keep offering exit until each worker drains its
        # in-flight op and accepts it, bounded in time (reference keeps
        # offering through the queue, interpreter.clj:294-309; workers
        # are daemon threads as a last resort)
        import time as _time

        if shipper is not None:
            # bounded; the abort cause below must not wait on a daemon
            shipper.close(wait_s=2.0)
        deadline = _time.monotonic() + 10.0
        pending = list(workers)
        while pending and _time.monotonic() < deadline:
            still = []
            for w in pending:
                if not w.thread.is_alive():
                    continue
                try:
                    w.inq.put_nowait({"type": "exit"})
                except queue.Full:
                    still.append(w)
            pending = still
            if pending:
                _time.sleep(0.01)
        raise


def _to_history(ops: List[dict]) -> History:
    h = History(Op.from_dict(d) for d in ops)
    return h.index_ops()
