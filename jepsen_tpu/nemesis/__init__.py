"""Nemesis protocol — fault injection into the system under test.

(reference: jepsen/src/jepsen/nemesis.clj:11-90 for the protocol and
validation; partitioners, grudges, and composition live in this package's
submodules.)
"""

from __future__ import annotations

from typing import Any, Iterable, Optional


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    #: Optional reflection: the set of :f values this nemesis handles
    #: (reference: nemesis.clj:18-47 Reflection/fs)
    def fs(self) -> Iterable[Any]:
        return ()


class NoopNemesis(Nemesis):
    """(reference: nemesis.clj noop)"""

    def invoke(self, test, op):
        return {**op, "type": "info"}


def noop() -> Nemesis:
    return NoopNemesis()


class ValidationError(Exception):
    pass


class Validate(Nemesis):
    """(reference: nemesis.clj:49-90)"""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        inner = self.nemesis.setup(test)
        if inner is None:
            raise ValidationError(
                f"Expected nemesis setup to return a nemesis, got None from "
                f"{self.nemesis!r}"
            )
        return Validate(inner)

    def invoke(self, test, op):
        # every nemesis in a run passes through validate (core.run_case),
        # so this one seam gives fault start/stop spans to all of them
        from .. import obs

        with obs.span(f"nemesis/{op.get('f')}", cat="nemesis") as sp:
            res = self.nemesis.invoke(test, op)
            sp.set("type", res.get("type") if isinstance(res, dict) else "?")
        if not isinstance(res, dict):
            raise ValidationError(
                f"Nemesis {self.nemesis!r} returned {res!r} for {op!r}"
            )
        # counted only for valid completions — an invalid result raises
        # above and must not inflate the completed-fault count
        obs.count("jepsen_nemesis_ops_total", f=str(op.get("f")))
        return res

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def validate(nemesis: Nemesis) -> Nemesis:
    return Validate(nemesis)


class Timeout(Nemesis):
    """Bound a flaky nemesis's ops; timed-out ops get :value "timeout".
    (reference: nemesis.clj:92-106)"""

    def __init__(self, timeout_ms: float, nemesis: Nemesis):
        self.timeout_ms = timeout_ms
        self.nemesis = nemesis

    def setup(self, test):
        return Timeout(self.timeout_ms, self.nemesis.setup(test))

    def invoke(self, test, op):
        from ..util import timeout as timeout_fn

        return timeout_fn(
            self.timeout_ms,
            lambda: self.nemesis.invoke(test, op),
            default={**op, "value": "timeout"},
        )

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def timeout(timeout_ms: float, nemesis: Nemesis) -> Nemesis:
    return Timeout(timeout_ms, nemesis)


# ---------------------------------------------------------------------------
# Grudges: maps of node → set of nodes to drop traffic from
# (reference: nemesis.clj:108-281)
# ---------------------------------------------------------------------------


def _rng():
    from .. import generator as gen

    return gen.rng


def bisect(coll):
    """Cut a sequence in half; smaller half first.
    (reference: nemesis.clj:108-111)"""
    coll = list(coll)
    mid = len(coll) // 2
    return [coll[:mid], coll[mid:]]


def split_one(coll, loner=None):
    """Split one node off from the rest.  (reference: nemesis.clj:113-118)"""
    coll = list(coll)
    if loner is None:
        loner = _rng().choice(coll)
    return [[loner], [x for x in coll if x != loner]]


def complete_grudge(components):
    """No node may talk to nodes outside its component.
    (reference: nemesis.clj:120-132)"""
    components = [set(c) for c in components]
    universe = set().union(*components) if components else set()
    grudge = {}
    for component in components:
        for node in component:
            grudge[node] = universe - component
    return grudge


def invert_grudge(nodes, conns):
    """From a connectivity map to a drop map.
    (reference: nemesis.clj:134-142)"""
    universe = set(nodes)
    return {a: universe - set(conns.get(a, set())) for a in sorted(universe, key=str)}


def bridge(nodes):
    """Cut the network in half but keep one bridge node connected to
    both sides.  (reference: nemesis.clj:144-155)"""
    components = bisect(nodes)
    bridge_node = components[1][0]
    grudge = complete_grudge(components)
    grudge.pop(bridge_node, None)
    return {node: others - {bridge_node} for node, others in grudge.items()}


def majorities_ring_perfect(nodes):
    """Exact ring for ≤5-node clusters.  (reference: nemesis.clj:202-216)"""
    from ..util import majority

    nodes = list(nodes)
    universe = set(nodes)
    n = len(nodes)
    m = majority(n)
    shuffled = list(nodes)
    _rng().shuffle(shuffled)
    ring = shuffled * 2  # cycle
    grudge = {}
    for i in range(n):
        maj = ring[i : i + m]
        center = maj[len(maj) // 2]
        grudge[center] = universe - set(maj)
    return grudge


def majorities_ring_stochastic(nodes):
    """Greedy construction for larger clusters.
    (reference: nemesis.clj:218-258)"""
    from ..util import majority

    nodes = list(nodes)
    m = majority(len(nodes))
    conns = {a: {a} for a in nodes}
    while True:
        by_degree = sorted(
            nodes, key=lambda a: (len(conns[a]), _rng().random())
        )
        a = by_degree[0]
        if len(conns[a]) >= m:
            return invert_grudge(nodes, conns)
        candidates = [b for b in by_degree if b != a and b not in conns[a]]
        if not candidates:
            return invert_grudge(nodes, conns)
        b = candidates[0]
        conns[a].add(b)
        conns[b].add(a)


def majorities_ring(nodes):
    """Every node sees a majority, but no two nodes see the same one.
    (reference: nemesis.clj:260-275)"""
    nodes = list(nodes)
    if len(nodes) <= 5:
        return majorities_ring_perfect(nodes)
    return majorities_ring_stochastic(nodes)


# ---------------------------------------------------------------------------
# Partitioners (reference: nemesis.clj:157-281)
# ---------------------------------------------------------------------------


class Partitioner(Nemesis):
    """:start cuts links per (grudge nodes); :stop heals.
    (reference: nemesis.clj:157-183)"""

    def __init__(self, grudge_fn=None):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        from .. import net

        net.heal(test)
        return self

    def invoke(self, test, op):
        from .. import net

        f = op.get("f")
        if f == "start":
            grudge = op.get("value")
            if grudge is None:
                if self.grudge_fn is None:
                    raise ValueError(
                        f"Expected op {op!r} to have a grudge for a value"
                    )
                grudge = self.grudge_fn(test["nodes"])
            net.drop_all(test, grudge)
            return {
                **op,
                "type": "info",
                "value": ["isolated", {str(k): sorted(map(str, v)) for k, v in grudge.items()}],
            }
        elif f == "stop":
            net.heal(test)
            return {**op, "type": "info", "value": "network-healed"}
        raise ValueError(f"partitioner cannot handle f={f!r}")

    def teardown(self, test):
        from .. import net

        net.heal(test)

    def fs(self):
        return {"start", "stop"}


def partitioner(grudge_fn=None) -> Nemesis:
    return Partitioner(grudge_fn)


def partition_halves() -> Nemesis:
    """(reference: nemesis.clj:185-190)"""
    return partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Nemesis:
    """(reference: nemesis.clj:192-195)"""

    def grudge(nodes):
        nodes = list(nodes)
        _rng().shuffle(nodes)
        return complete_grudge(bisect(nodes))

    return partitioner(grudge)


def partition_random_node() -> Nemesis:
    """(reference: nemesis.clj:197-200)"""
    return partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Nemesis:
    """(reference: nemesis.clj:277-281)"""
    return partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Composition (reference: nemesis.clj:285-428)
# ---------------------------------------------------------------------------


class FMap(Nemesis):
    """Remap the :f values a nemesis accepts.
    (reference: nemesis.clj:285-327)"""

    def __init__(self, lift, unlift, nemesis):
        self.lift = lift
        self.unlift = unlift
        self.nemesis = nemesis

    def setup(self, test):
        return f_map(self.lift, self.nemesis.setup(test))

    def invoke(self, test, op):
        inner = {**op, "f": self.unlift[op.get("f")]}
        res = self.nemesis.invoke(test, inner)
        return {**res, "f": self.lift(res.get("f"))}

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return {self.lift(f) for f in self.nemesis.fs()}


def f_map(lift, nemesis: Nemesis) -> Nemesis:
    fs = set(nemesis.fs())
    unlift = {lift(f): f for f in fs}
    return FMap(lift, unlift, nemesis)


class ReflCompose(Nemesis):
    """Compose nemeses, routing by their declared fs.
    (reference: nemesis.clj:334-351)"""

    def __init__(self, fmap, nemeses):
        self.fmap = fmap  # f -> index
        self.nemeses = list(nemeses)

    def setup(self, test):
        return compose([n.setup(test) for n in self.nemeses])

    def invoke(self, test, op):
        i = self.fmap.get(op.get("f"))
        if i is None:
            raise ValueError(
                f"No nemesis can handle f={op.get('f')!r} "
                f"(expected one of {sorted(map(str, self.fmap))})"
            )
        return self.nemeses[i].invoke(test, op)

    def teardown(self, test):
        for n in self.nemeses:
            n.teardown(test)

    def fs(self):
        out = set()
        for n in self.nemeses:
            out |= set(n.fs())
        return out


class MapCompose(Nemesis):
    """Compose with explicit {f-mapping: nemesis} routing; an f-mapping
    is a dict (rewrites f) or set (passes f through).
    (reference: nemesis.clj:354-382)"""

    def __init__(self, nemeses: dict):
        self.nemeses = dict(nemeses)

    @staticmethod
    def _route(fmapping, f):
        if isinstance(fmapping, dict):
            return fmapping.get(f)
        if isinstance(fmapping, (set, frozenset)):
            return f if f in fmapping else None
        raise TypeError(f"bad f mapping: {fmapping!r}")

    def setup(self, test):
        return MapCompose(
            {fm: n.setup(test) for fm, n in self.nemeses.items()}
        )

    def invoke(self, test, op):
        f = op.get("f")
        for fmapping, nemesis in self.nemeses.items():
            f2 = self._route(fmapping, f)
            if f2 is not None:
                res = nemesis.invoke(test, {**op, "f": f2})
                return {**res, "f": f}
        raise ValueError(f"no nemesis can handle {f!r}")

    def teardown(self, test):
        for n in self.nemeses.values():
            n.teardown(test)

    def fs(self):
        out = set()
        for fmapping in self.nemeses:
            if isinstance(fmapping, dict):
                out |= set(fmapping.keys())
            elif isinstance(fmapping, (set, frozenset)):
                out |= set(fmapping)
            else:
                raise TypeError(
                    "can only infer fs from dict/set mappings"
                )
        return out


def compose(nemeses) -> Nemesis:
    """Compose nemeses.  Accepts: a dict of f-mappings→nemeses (f-mapping
    = a set passing fs through, or — via the pair-list form, since dicts
    aren't hashable keys — a dict rewriting fs); a list of
    (f-mapping, nemesis) pairs; or a collection of Reflection-supporting
    nemeses routed by their declared fs.  (reference: nemesis.clj:384-428)"""
    if isinstance(nemeses, dict):
        nemeses = list(nemeses.items())
    nemeses = list(nemeses)
    if nemeses and isinstance(nemeses[0], tuple) and len(nemeses[0]) == 2 and isinstance(nemeses[0][0], (dict, set, frozenset)):
        frozen = {}
        for fmapping, n in nemeses:
            if isinstance(fmapping, (set, frozenset)):
                frozen[frozenset(fmapping)] = n
            elif isinstance(fmapping, dict):
                frozen[_FrozenDict(fmapping)] = n
            else:
                raise TypeError(f"bad f mapping: {fmapping!r}")
        return MapCompose(frozen)
    fmap = {}
    for i, n in enumerate(nemeses):
        for f in n.fs():
            if f in fmap:
                raise ValueError(
                    f"Nemeses {n!r} and {nemeses[fmap[f]]!r} are mutually "
                    f"incompatible; both use f {f!r}"
                )
            fmap[f] = i
    return ReflCompose(fmap, nemeses)


class _FrozenDict(dict):
    def __hash__(self):
        return hash(frozenset(self.items()))

    def get(self, k, default=None):  # routing uses .get
        return dict.get(self, k, default)


# ---------------------------------------------------------------------------
# Clock + process + file faults (reference: nemesis.clj:430-539)
# ---------------------------------------------------------------------------


def set_time(t: float) -> None:
    """Set the node's wall clock (POSIX seconds).
    (reference: nemesis.clj:430-433)"""
    from .. import control

    with control.su():
        control.execute("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomize node clocks within ±dt seconds.
    (reference: nemesis.clj:435-450)"""

    def __init__(self, dt: float):
        self.dt = dt

    def invoke(self, test, op):
        import time as _time

        from .. import control

        def thunk():
            dt = int(self.dt)
            offset = _rng().randint(-dt, dt)
            set_time(_time.time() + offset)
            return offset

        value = control.with_test_nodes(test, thunk)
        return {**op, "type": "info", "value": value}

    def teardown(self, test):
        import time as _time

        from .. import control

        control.with_test_nodes(test, lambda: set_time(_time.time()))

    def fs(self):
        return {"scramble-clock"}


def clock_scrambler(dt: float) -> Nemesis:
    return ClockScrambler(dt)


class NodeStartStopper(Nemesis):
    """:start runs start_fn on targeted nodes; :stop undoes it.
    (reference: nemesis.clj:452-495)"""

    def __init__(self, targeter, start_fn, stop_fn):
        import threading

        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.nodes = None
        self.lock = threading.Lock()

    @staticmethod
    def _target(targeter, test, nodes):
        """Call (targeter test nodes) or (targeter nodes) based on its
        actual arity — not exception probing, which would mask real
        TypeErrors inside the targeter."""
        import inspect

        try:
            sig = inspect.signature(targeter)
            required = [
                p
                for p in sig.parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty
            ]
            two_arg = len(required) >= 2
        except (ValueError, TypeError):
            two_arg = False
        return targeter(test, nodes) if two_arg else targeter(nodes)

    def invoke(self, test, op):
        from .. import control

        with self.lock:
            f = op.get("f")
            if f == "start":
                ns = self._target(self.targeter, test, test["nodes"])
                if ns is None:
                    value = "no-target"
                elif self.nodes is not None:
                    value = f"nemesis already disrupting {self.nodes!r}"
                else:
                    ns = list(ns) if isinstance(ns, (list, tuple, set)) else [ns]
                    self.nodes = ns
                    value = control.on_many(
                        ns,
                        lambda: self.start_fn(test, control.current_node()),
                    )
            elif f == "stop":
                if self.nodes is None:
                    value = "not-started"
                else:
                    value = control.on_many(
                        self.nodes,
                        lambda: self.stop_fn(test, control.current_node()),
                    )
                    self.nodes = None
            else:
                raise ValueError(f"unknown f {f!r}")
            return {**op, "type": "info", "value": value}

    def fs(self):
        return {"start", "stop"}


def node_start_stopper(targeter, start_fn, stop_fn) -> Nemesis:
    return NodeStartStopper(targeter, start_fn, stop_fn)


def hammer_time(process: str, targeter=None) -> Nemesis:
    """SIGSTOP/SIGCONT a process on targeted nodes.
    (reference: nemesis.clj:497-511)"""
    from .. import control

    if targeter is None:
        targeter = lambda nodes: _rng().choice(list(nodes))  # noqa: E731

    def start(test, node):
        with control.su():
            control.execute("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with control.su():
            control.execute("killall", "-s", "CONT", process)
        return ["resumed", process]

    return node_start_stopper(targeter, start, stop)


class TruncateFile(Nemesis):
    """Drop the last :drop bytes of files on nodes.
    (reference: nemesis.clj:513-539)"""

    def invoke(self, test, op):
        from .. import control

        assert op.get("f") == "truncate"
        plan = op.get("value") or {}

        def doit(test_, node):
            spec = plan[node]
            path, drop = spec["file"], spec["drop"]
            assert isinstance(path, str) and isinstance(drop, int)
            with control.su():
                control.execute("truncate", "-c", "-s", f"-{drop}", path)

        control.on_nodes(test, list(plan.keys()), doit)
        return {**op, "type": "info"}

    def fs(self):
        return {"truncate"}


def truncate_file() -> Nemesis:
    return TruncateFile()
