"""Nemesis protocol — fault injection into the system under test.

(reference: jepsen/src/jepsen/nemesis.clj:11-90 for the protocol and
validation; partitioners, grudges, and composition live in this package's
submodules.)
"""

from __future__ import annotations

from typing import Any, Iterable, Optional


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    #: Optional reflection: the set of :f values this nemesis handles
    #: (reference: nemesis.clj:18-47 Reflection/fs)
    def fs(self) -> Iterable[Any]:
        return ()


class NoopNemesis(Nemesis):
    """(reference: nemesis.clj noop)"""

    def invoke(self, test, op):
        return {**op, "type": "info"}


def noop() -> Nemesis:
    return NoopNemesis()


class ValidationError(Exception):
    pass


class Validate(Nemesis):
    """(reference: nemesis.clj:49-90)"""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        inner = self.nemesis.setup(test)
        if inner is None:
            raise ValidationError(
                f"Expected nemesis setup to return a nemesis, got None from "
                f"{self.nemesis!r}"
            )
        return Validate(inner)

    def invoke(self, test, op):
        res = self.nemesis.invoke(test, op)
        if not isinstance(res, dict):
            raise ValidationError(
                f"Nemesis {self.nemesis!r} returned {res!r} for {op!r}"
            )
        return res

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def validate(nemesis: Nemesis) -> Nemesis:
    return Validate(nemesis)
