"""Clock-fault nemesis: reset / bump / strobe node clocks.

(reference: jepsen/src/jepsen/nemesis/time.clj — compile! uploads C
sources and gccs them on each DB node :20-50, install! :52-84,
bump-time! :86-91, strobe-time! :92-97, clock-nemesis :98-146, and the
generators reset-gen/bump-gen/strobe-gen :148-205 with bump magnitudes
±2²…2¹⁸ ms and strobe periods 1–1024 ms for ≤32 s :170-192.)

The C sources live in this repo's native/ directory (fresh
implementations) and are shipped + compiled on the nodes, exactly the
reference's deployment mechanism.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import control
from ..control.core import RemoteError, lit
from ..control.util import meh, write_file
from . import Nemesis

BIN_DIR = "/opt/jepsen"
NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)


def _source(name: str) -> str:
    with open(os.path.join(NATIVE_DIR, name)) as f:
        return f.read()


def compile_tool(c_file: str, bin_name: str) -> None:
    """Upload a C source and compile it on the node (reference:
    nemesis/time.clj:20-50 compiles with gcc on the DB node)."""
    with control.su():
        control.execute("mkdir", "-p", BIN_DIR)
        src_path = f"{BIN_DIR}/{bin_name}.c"
        write_file(_source(c_file), src_path)
        control.execute("gcc", "-O2", "-o", f"{BIN_DIR}/{bin_name}", src_path)


def install() -> None:
    """Ensure clock tools exist on the current node.
    (reference: nemesis/time.clj:52-84)"""
    compile_tool("bump-time.c", "bump-time")
    compile_tool("strobe-time.c", "strobe-time")


def bump_time(delta_ms: float) -> str:
    """Jump this node's clock by delta ms.
    (reference: nemesis/time.clj:86-91)"""
    with control.su():
        return control.execute(f"{BIN_DIR}/bump-time", str(int(delta_ms)))


def strobe_time(delta_ms: float, period_ms: float, duration_s: float) -> str:
    """Oscillate this node's clock.  (reference: nemesis/time.clj:92-97)"""
    with control.su():
        return control.execute(
            f"{BIN_DIR}/strobe-time",
            str(int(delta_ms)),
            str(int(period_ms)),
            str(int(duration_s)),
        )


def strobe_time_experiment(
    delta_ms: float, period_ms: float, duration_s: float
) -> str:
    """The experimental one-sided strobe (true vs true+delta) that
    reports its adjustment count; compiled on first use — it's not part
    of the standard clock-nemesis toolkit.  (reference:
    jepsen/resources/strobe-time-experiment.c, shipped but unwired
    there too; native/strobe-time-experiment.c here)"""
    with control.su():
        compile_tool("strobe-time-experiment.c", "strobe-time-experiment")
        return control.execute(
            f"{BIN_DIR}/strobe-time-experiment",
            str(int(delta_ms)),
            str(int(period_ms)),
            str(int(duration_s)),
        )


def reset_time() -> None:
    """Reset via ntpdate, falling back to date -s from the control
    host's clock.  (reference: nemesis/time.clj reset-time!)"""
    import time as _time

    with control.su():
        try:
            control.execute("ntpdate", "-p", "1", "-b", "pool.ntp.org")
        except RemoteError:
            control.execute("date", "+%s", "-s", f"@{int(_time.time())}")


class ClockNemesis(Nemesis):
    """Handles ops: {"f": "reset"|"bump"|"strobe", "value": ...}.
    value for bump: {node: delta-ms}; for strobe:
    {node: {"delta": ms, "period": ms, "duration": s}}.
    (reference: nemesis/time.clj:98-146)"""

    def setup(self, test):
        def init(test_, node):
            install()
            # stop ntp daemons so they don't fight us
            meh(lambda: control.execute("service", "ntp", "stop", check=False))
            meh(lambda: control.execute("service", "ntpd", "stop", check=False))
            meh(lambda: control.execute(
                "systemctl", "stop", "systemd-timesyncd", check=False
            ))

        control.on_nodes(test, init)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        value = op.get("value")
        if f == "reset":
            nodes = value or test["nodes"]
            res = control.on_many(nodes, reset_time)
        elif f == "bump":
            res = control.on_nodes(
                test,
                list(value.keys()),
                lambda t, node: bump_time(value[node]),
            )
        elif f == "strobe":
            res = control.on_nodes(
                test,
                list(value.keys()),
                lambda t, node: strobe_time(
                    value[node]["delta"],
                    value[node]["period"],
                    value[node]["duration"],
                ),
            )
        elif f == "check-offsets":
            # observation-only op: the shared post-op sweep below IS the
            # value (reference: nemesis/time.clj:108,126-130)
            res = None
        else:
            raise ValueError(f"clock nemesis cannot handle f={f!r}")
        clock_offsets = control.on_nodes(test, lambda t, n: current_offset())
        if f == "check-offsets":
            res = clock_offsets
        return {**op, "type": "info", "value": res, "clock-offsets": clock_offsets}

    def teardown(self, test):
        control.on_nodes(test, lambda t, n: reset_time())

    def fs(self):
        return {"reset", "bump", "strobe", "check-offsets"}


def current_offset() -> Optional[float]:
    """This node's clock offset from the control host, seconds."""
    import time as _time

    try:
        remote = float(control.execute("date", "+%s.%N"))
        return remote - _time.time()
    except Exception:
        return None


def clock_nemesis() -> Nemesis:
    return ClockNemesis()


# ---------------------------------------------------------------------------
# Generators (reference: nemesis/time.clj:148-205)
# ---------------------------------------------------------------------------


def _rng():
    from .. import generator as gen

    return gen.rng


def reset_gen(test, ctx):
    """Reset a random subset of nodes' clocks."""
    from ..util import random_nonempty_subset

    nodes = test.get("nodes", [])
    return {"f": "reset", "value": random_nonempty_subset(nodes, _rng())}


def bump_gen(test, ctx):
    """Bump a random subset by ±2²–2¹⁸ ms.
    (reference: nemesis/time.clj:170-173)"""
    from ..util import random_nonempty_subset

    rng = _rng()
    nodes = random_nonempty_subset(test.get("nodes", []), rng)
    return {
        "f": "bump",
        "value": {
            node: (2 ** rng.randint(2, 18)) * rng.choice([-1, 1])
            for node in nodes
        },
    }


def strobe_gen(test, ctx):
    """Strobe a random subset: delta ≤2¹⁸ ms, period 1–1024 ms,
    duration ≤32 s.  (reference: nemesis/time.clj:178-192)"""
    from ..util import random_nonempty_subset

    rng = _rng()
    nodes = random_nonempty_subset(test.get("nodes", []), rng)
    return {
        "f": "strobe",
        "value": {
            node: {
                "delta": 2 ** rng.randint(2, 18),
                "period": 2 ** rng.randint(0, 10),
                "duration": rng.randint(1, 32),
            }
            for node in nodes
        },
    }


def check_offsets_gen(test, ctx):
    """(reference: nemesis/time.clj:204)"""
    return {"type": "info", "f": "check-offsets", "value": None}


def clock_gen():
    """Mix of reset/bump/strobe/check-offsets ops.
    (reference: nemesis/time.clj:194-205)"""
    from .. import generator as gen

    return gen.mix([reset_gen, bump_gen, strobe_gen, check_offsets_gen])
