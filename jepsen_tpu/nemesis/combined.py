"""Nemesis "packages": composable {nemesis, generator, final_generator,
perf} bundles for partitions, clock skew, and process kill/pause.

(reference: jepsen/src/jepsen/nemesis/combined.clj — default-interval
:27-29, db-nodes node specs :38-61, db-nemesis :70-98, db-package
:141-160, grudge partition specs :162-188, partition-nemesis :196-224,
partition-package :226-246, clock-package :248-280, f-map :294-303,
compose-packages :305-316, nemesis-package :328-374.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Set

from .. import control
from .. import db as db_mod
from .. import generator as gen
from ..util import majority, random_nonempty_subset
from . import (
    Nemesis,
    bisect,
    complete_grudge,
    compose,
    majorities_ring,
    noop as noop_nemesis,
    partitioner,
    split_one,
)
from . import f_map as nemesis_f_map
from . import time as nt

#: Seconds between nemesis operations (reference: combined.clj:27-29)
DEFAULT_INTERVAL = 10

NOOP_PACKAGE = {
    "generator": None,
    "final_generator": None,
    "nemesis": noop_nemesis(),
    "perf": set(),
}


def _rng():
    return gen.rng


def minority_third(n: int) -> int:
    """Up to, but not including, 1/3rd of nodes (reference:
    util.clj minority-third)."""
    return max(0, (n - 1) // 3)


def db_nodes(test: dict, db, node_spec) -> List[Any]:
    """Resolve a node spec to actual nodes.
    (reference: combined.clj:38-61)"""
    nodes = list(test["nodes"])
    rng = _rng()
    if node_spec is None:
        return random_nonempty_subset(nodes, rng)
    if node_spec == "one":
        return [rng.choice(nodes)]
    if node_spec == "minority":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return shuffled[: majority(len(nodes)) - 1]
    if node_spec == "majority":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return shuffled[: majority(len(nodes))]
    if node_spec == "minority-third":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return shuffled[: minority_third(len(nodes))]
    if node_spec == "primaries":
        return random_nonempty_subset(db.primaries(test), rng)
    if node_spec == "all":
        return nodes
    return list(node_spec)


def node_specs(db) -> List[Any]:
    """(reference: combined.clj:63-68)"""
    specs: List[Any] = [None, "one", "minority-third", "minority", "majority", "all"]
    if isinstance(db, db_mod.Primary):
        specs.append("primaries")
    return specs


class DBNemesis(Nemesis):
    """start/kill/pause/resume a DB's processes on spec'd nodes.
    (reference: combined.clj:70-98)"""

    def __init__(self, db):
        self.db = db

    def invoke(self, test, op):
        f = op.get("f")
        fn = {
            "start": lambda t, n: self.db.start(t, n),
            "kill": lambda t, n: self.db.kill(t, n),
            "pause": lambda t, n: self.db.pause(t, n),
            "resume": lambda t, n: self.db.resume(t, n),
        }.get(f)
        if fn is None:
            raise ValueError(f"db nemesis cannot handle f={f!r}")
        nodes = db_nodes(test, self.db, op.get("value"))
        res = control.on_nodes(test, nodes, fn)
        return {**op, "type": "info", "value": {str(k): str(v) for k, v in res.items()}}

    def fs(self):
        return {"start", "kill", "pause", "resume"}


def db_package(opts: dict) -> dict:
    """(reference: combined.clj:100-160)"""
    db = opts["db"]
    faults = set(opts.get("faults", ()))
    kill = isinstance(db, db_mod.Process) and "kill" in faults
    pause = isinstance(db, db_mod.Pause) and "pause" in faults
    needed = kill or pause

    kill_targets = opts.get("kill", {}).get("targets", node_specs(db))
    pause_targets = opts.get("pause", {}).get("targets", node_specs(db))

    start = {"type": "info", "f": "start", "value": "all"}
    resume = {"type": "info", "f": "resume", "value": "all"}

    def kill_op(test, ctx):
        return {"type": "info", "f": "kill", "value": _rng().choice(kill_targets)}

    def pause_op(test, ctx):
        return {"type": "info", "f": "pause", "value": _rng().choice(pause_targets)}

    modes = []
    final = []
    if pause:
        modes.append(gen.flip_flop(pause_op, gen.repeat(resume)))
        final.append(resume)
    if kill:
        modes.append(gen.flip_flop(kill_op, gen.repeat(start)))
        final.append(start)

    generator = gen.stagger(
        opts.get("interval", DEFAULT_INTERVAL), gen.mix(modes)
    ) if modes else None
    return {
        "generator": generator if needed else None,
        "final_generator": final if needed else None,
        "nemesis": DBNemesis(db),
        "perf": {
            ("kill", frozenset({"kill"}), frozenset({"start"}), "#E9A4A0"),
            ("pause", frozenset({"pause"}), frozenset({"resume"}), "#A0B1E9"),
        },
    }


def grudge(test: dict, db, part_spec) -> Dict[Any, Set[Any]]:
    """Compute a grudge from a partition spec.
    (reference: combined.clj:162-188)"""
    nodes = list(test["nodes"])
    rng = _rng()
    if part_spec == "one":
        return complete_grudge(split_one(nodes))
    if part_spec == "majority":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        return complete_grudge(bisect(shuffled))
    if part_spec == "majorities-ring":
        return majorities_ring(nodes)
    if part_spec == "minority-third":
        shuffled = list(nodes)
        rng.shuffle(shuffled)
        k = minority_third(len(nodes))
        return complete_grudge([shuffled[:k], shuffled[k:]])
    if part_spec == "primaries":
        primaries = random_nonempty_subset(db.primaries(test), rng)
        components = [[n for n in nodes if n not in set(primaries)]] + [
            [p] for p in primaries
        ]
        return complete_grudge(components)
    return part_spec  # already a grudge


def partition_specs(db) -> List[Any]:
    """(reference: combined.clj:190-194)"""
    specs: List[Any] = ["one", "minority-third", "majority", "majorities-ring"]
    if isinstance(db, db_mod.Primary):
        specs.append("primaries")
    return specs


class PartitionNemesis(Nemesis):
    """start-partition/stop-partition with spec values.
    (reference: combined.clj:196-224)"""

    def __init__(self, db, p=None):
        self.db = db
        self.p = p or partitioner()

    def setup(self, test):
        return PartitionNemesis(self.db, self.p.setup(test))

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start-partition":
            g = grudge(test, self.db, op.get("value"))
            res = self.p.invoke(test, {**op, "f": "start", "value": g})
        elif f == "stop-partition":
            res = self.p.invoke(test, {**op, "f": "stop", "value": None})
        else:
            raise ValueError(f"partition nemesis cannot handle f={f!r}")
        return {**res, "f": f}

    def teardown(self, test):
        self.p.teardown(test)

    def fs(self):
        return {"start-partition", "stop-partition"}


def partition_package(opts: dict) -> dict:
    """(reference: combined.clj:226-246)"""
    needed = "partition" in set(opts.get("faults", ()))
    db = opts["db"]
    targets = opts.get("partition", {}).get("targets", partition_specs(db))

    def start(test, ctx):
        return {
            "type": "info",
            "f": "start-partition",
            "value": _rng().choice(targets),
        }

    stop = {"type": "info", "f": "stop-partition", "value": None}
    g = gen.stagger(
        opts.get("interval", DEFAULT_INTERVAL),
        gen.flip_flop(start, gen.repeat(stop)),
    )
    return {
        "generator": g if needed else None,
        "final_generator": stop if needed else None,
        "nemesis": PartitionNemesis(db),
        "perf": {
            (
                "partition",
                frozenset({"start-partition"}),
                frozenset({"stop-partition"}),
                "#E9DCA0",
            )
        },
    }


def clock_package(opts: dict) -> dict:
    """(reference: combined.clj:248-280)"""
    needed = "clock" in set(opts.get("faults", ()))
    nemesis = compose(
        [
            (
                {
                    "reset-clock": "reset",
                    "strobe-clock": "strobe",
                    "bump-clock": "bump",
                },
                nt.clock_nemesis(),
            )
        ]
    )
    clock_gen = gen.f_map(
        {"reset": "reset-clock", "strobe": "strobe-clock", "bump": "bump-clock"},
        gen.mix([nt.reset_gen, nt.bump_gen, nt.strobe_gen]),
    )
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL), clock_gen)
    return {
        "generator": g if needed else None,
        "final_generator": {"type": "info", "f": "reset-clock"} if needed else None,
        "nemesis": nemesis,
        "perf": {
            (
                "clock",
                frozenset({"bump-clock"}),
                frozenset({"reset-clock"}),
                "#A0E9E3",
            )
        },
    }


def f_map(lift: Callable[[Any], Any], pkg: dict) -> dict:
    """Lift a whole package's fs.  (reference: combined.clj:294-303)"""
    return {
        **pkg,
        "generator": gen.map(
            lambda op: {**op, "f": lift(op.get("f"))}, pkg["generator"]
        )
        if pkg.get("generator") is not None
        else None,
        "final_generator": gen.map(
            lambda op: {**op, "f": lift(op.get("f"))}, pkg["final_generator"]
        )
        if pkg.get("final_generator") is not None
        else None,
        "nemesis": nemesis_f_map(lift, pkg["nemesis"]),
        "perf": {
            (lift(name), frozenset(map(lift, start)), frozenset(map(lift, stop)), color)
            for (name, start, stop, color) in pkg.get("perf", set())
        },
    }


def compose_packages(packages: Iterable[dict]) -> dict:
    """any() over generators, sequence of final generators, composed
    nemeses, union of perf specs.  (reference: combined.clj:305-316)"""
    packages = list(packages)
    if not packages:
        return dict(NOOP_PACKAGE)
    if len(packages) == 1:
        return packages[0]
    perf: Set = set()
    for p in packages:
        perf |= set(p.get("perf", set()))
    return {
        "generator": gen.any(
            *[p["generator"] for p in packages if p.get("generator") is not None]
        ),
        "final_generator": [
            p["final_generator"]
            for p in packages
            if p.get("final_generator") is not None
        ],
        "nemesis": compose([p["nemesis"] for p in packages]),
        "perf": perf,
    }


def disk_package(opts: dict) -> dict:
    """Disk faults via the faultfs FUSE filesystem: probabilistic
    breakage flip-flopped with heals, everything healed at the end.
    (no reference analogue in combined.clj — charybdefs is wired
    manually there; here "disk" is a first-class fault name)"""
    faults = set(opts.get("faults", ()))
    if "disk" not in faults:
        return dict(NOOP_PACKAGE)
    from .. import faultfs

    targets = opts.get("disk", {}).get("targets")

    def break_op(test, ctx):
        nodes = targets or random_nonempty_subset(test["nodes"], _rng())
        return {"type": "info", "f": "break-disk-slow", "value": list(nodes)}

    heal = {"type": "info", "f": "heal-disk", "value": None}
    return {
        "generator": gen.stagger(
            opts.get("interval", DEFAULT_INTERVAL),
            gen.flip_flop(break_op, gen.repeat(heal)),
        ),
        "final_generator": [heal],
        "nemesis": faultfs.FaultFsNemesis(),
        "perf": {
            ("disk", frozenset({"break-disk", "break-disk-slow"}),
             frozenset({"heal-disk"}), "#E9D3A0"),
        },
    }


def nemesis_packages(opts: dict) -> List[dict]:
    """(reference: combined.clj:318-326)"""
    faults = set(opts.get("faults", ["partition", "kill", "pause", "clock"]))
    opts = {**opts, "faults": faults}
    return [
        partition_package(opts),
        clock_package(opts),
        db_package(opts),
        disk_package(opts),
    ]


def nemesis_package(opts: dict, only_active: bool = False) -> dict:
    """The standard broad-spectrum fault package.  With ``only_active``,
    drop sub-packages whose faults weren't requested (their generators
    are None) — needed when composing with a suite's own fault menu,
    whose op names would otherwise collide with the idle sub-nemeses.
    (reference: combined.clj:328-374)"""
    pkgs = nemesis_packages(opts)
    if only_active:
        pkgs = [p for p in pkgs if p.get("generator") is not None]
    return compose_packages(pkgs)
