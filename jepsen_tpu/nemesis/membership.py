"""Cluster-membership nemesis: join/leave churn as a state machine with
per-node views and pending-op resolution.

(reference: jepsen/src/jepsen/nemesis/membership.clj — node-view-interval
:59-61, initial-state :68-77, resolve/resolve-ops :79-107,
update-node-view! :109-142, node-view-future :143-157, the Nemesis record
:159-225, the Generator :227-237, package :239-270 — plus
membership/state.clj:21-58 for the State protocol.)
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import control
from .. import generator as gen
from . import Nemesis

log = logging.getLogger("jepsen_tpu.nemesis.membership")

#: Seconds between node-view refreshes (reference: membership.clj:59-61)
NODE_VIEW_INTERVAL = 5


class State:
    """Membership state machine protocol.  Implementations carry three
    special fields maintained by the nemesis: ``node_views`` (node →
    view), ``view`` (merged view), ``pending`` (list of (op, op') dict
    pairs, matching the reference's contract).
    (reference: membership/state.clj:21-58)"""

    node_views: Dict[Any, Any]
    view: Any
    pending: List[Tuple[dict, dict]]

    def setup(self, test: dict) -> "State":
        return self

    def node_view(self, test: dict, node: Any) -> Any:
        """The cluster as seen from `node`; None = unknown."""
        return None

    def merge_views(self, test: dict) -> Any:
        """Derive the authoritative view from node_views."""
        return self.view

    def fs(self) -> Set[Any]:
        return set()

    def op(self, test: dict):
        """Next membership op to perform, or "pending" if none."""
        return "pending"

    def invoke(self, test: dict, op: dict):
        """Apply an op. Returns op' or (op', state')."""
        raise NotImplementedError

    def resolve(self, test: dict) -> "State":
        """Evolve toward a fixed point."""
        return self

    def resolve_op(self, test: dict, op_pair: Tuple) -> Optional["State"]:
        """If op_pair has resolved, the new state; else None."""
        return None

    def teardown(self, test: dict) -> None:
        pass


def _init_special_fields(state: State) -> State:
    if not hasattr(state, "node_views") or state.node_views is None:
        state.node_views = {}
    if not hasattr(state, "view"):
        state.view = None
    # pending holds REAL (op, op') dict pairs, as the State contract
    # documents; stored as a list (dicts aren't hashable) with
    # identity-based removal
    if not hasattr(state, "pending") or state.pending is None:
        state.pending = []
    elif isinstance(state.pending, set):
        state.pending = list(state.pending)
    return state


def _resolve(state: State, test: dict) -> State:
    """resolve + resolve-ops to fixed point.
    (reference: membership.clj:79-107)"""
    for _ in range(100):
        before = (state.view, len(state.pending))
        state = state.resolve(test) or state
        remaining = []
        for pair in list(state.pending):
            s2 = state.resolve_op(test, pair)
            if s2 is not None:
                state = s2
            else:
                remaining.append(pair)
        state.pending = remaining
        if (state.view, len(state.pending)) == before:
            return state
    return state


class MembershipNemesis(Nemesis):
    """(reference: membership.clj:159-225)"""

    def __init__(self, state: State, opts: Optional[dict] = None):
        self.lock = threading.RLock()
        self.state = _init_special_fields(state)
        self.opts = opts or {}
        self.running = False
        self.threads: List[threading.Thread] = []

    def setup(self, test):
        with self.lock:
            self.state = _init_special_fields(self.state.setup(test) or self.state)
        # lock-free start/stop flag: the store is atomic under the
        # GIL and the view loops tolerate one stale NODE_VIEW_INTERVAL
        self.running = True  # jt: allow[concurrency-unguarded-shared] — lock-free stop flag (see above)
        for node in test["nodes"]:
            t = threading.Thread(
                target=self._view_loop,
                args=(test, node),
                name=f"membership-view-{node}",
                daemon=True,
            )
            t.start()
            self.threads.append(t)
        return self

    def _view_loop(self, test, node):
        """(reference: membership.clj:109-157)"""
        import time as _time

        while self.running:
            try:
                control.with_node(node, lambda: self._update_node_view(test, node))
            except Exception:
                log.exception("node view updater for %s failed; will retry", node)
            _time.sleep(NODE_VIEW_INTERVAL)

    def _update_node_view(self, test, node):
        with self.lock:
            state = self.state
        nv = state.node_view(test, node)
        if nv is None:
            return
        with self.lock:
            self.state.node_views = {**self.state.node_views, node: nv}
            self.state.view = self.state.merge_views(test)
            self.state = _resolve(self.state, test)

    def invoke(self, test, op):
        with self.lock:
            res = self.state.invoke(test, op)
            if isinstance(res, tuple):
                op2, state2 = res
                self.state = _init_special_fields(state2)
            else:
                op2 = res
            self.state.pending = list(self.state.pending) + [(op, op2)]
            self.state = _resolve(self.state, test)
            return op2

    def teardown(self, test):
        # lock-free stop flag (see setup); loops exit within one interval
        self.running = False  # jt: allow[concurrency-unguarded-shared] — lock-free stop flag (see setup)
        with self.lock:
            self.state.teardown(test)

    def fs(self):
        with self.lock:
            return self.state.fs()


class MembershipGenerator(gen.Generator):
    """Ask the state machine for its next op.
    (reference: membership.clj:227-237)"""

    def __init__(self, nemesis: MembershipNemesis):
        self.nemesis = nemesis

    def op(self, test, ctx):
        with self.nemesis.lock:
            o = self.nemesis.state.op(test)
        if o is None:
            return None
        if o == "pending":
            return (gen.PENDING, self)
        return (gen.fill_in_op(dict(o), ctx), self)

    def update(self, test, ctx, event):
        return self


def package(opts: dict) -> Optional[dict]:
    """{state, nemesis, generator} package, or None if membership faults
    aren't enabled.  (reference: membership.clj:239-270)"""
    if "membership" not in set(opts.get("faults", ())):
        return None
    mopts = opts.get("membership", {})
    nem = MembershipNemesis(mopts["state"], mopts)
    g = gen.stagger(opts.get("interval", 10), MembershipGenerator(nem))
    return {"state": nem, "nemesis": nem, "generator": g}
