"""``python -m jepsen_tpu`` → the CLI.  (reference: project.clj:34
``:main jepsen.cli``)"""

from .cli import main

main()
