"""List-append transactional anomaly analysis.

Transactions are lists of micro-ops ``["append", k, v]`` /
``["r", k, [v1 v2 …]]`` with globally unique appended values per key.
Reads observe the full list, so every read of a key is a *version*: the
prefix relation over observed lists recovers the version order exactly,
and write-write / write-read / read-write dependencies follow without
guesswork.  That soundness argument is the reason the reference's Elle
treats list-append as its strongest mode (consumed at
jepsen/src/jepsen/tests/cycle/append.clj:12-21).

Anomalies detected: internal, G1a (aborted read), G1b (intermediate
read), dirty-update, duplicate-elements, incompatible-order, plus the
cycle anomalies G0 / G1c / G-single / G2-item (with -realtime /
-process variants when those graphs are enabled).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Set, Tuple

from ..history import History
from ..txn import APPEND, R
from . import core
from .core import Txn
from .graph import Graph, WW, WR, RW, PROCESS, REALTIME
from . import cycles as cycles_mod


def mops(t: Txn):
    return t.value or []


def internal_cases(txns: List[Txn]) -> List[dict]:
    """Reads inconsistent with the txn's *own* prior reads/appends: after
    reading k as L then appending x, a later read of k must be exactly
    L+[x…]; after appending without a prior read, a later read must end
    with the appended suffix."""
    cases = []
    for t in txns:
        if not t.ok:
            continue
        # key -> ("exact", list) after a read, ("suffix", list) append-only
        state: Dict[Any, Tuple[str, List[Any]]] = {}
        for f, k, v in mops(t):
            if f == APPEND:
                kind, lst = state.get(k, ("suffix", []))
                state[k] = (kind, lst + [v])
            elif f == R:
                v = list(v or [])
                if k in state:
                    kind, lst = state[k]
                    bad = (
                        v != lst
                        if kind == "exact"
                        else (len(v) < len(lst) or v[len(v) - len(lst) :] != lst)
                    )
                    if bad:
                        cases.append(
                            {
                                "op": t.complete.to_dict(),
                                "mop": [f, k, v],
                                "expected": {"kind": kind, "value": lst},
                            }
                        )
                state[k] = ("exact", v)
    return cases


def g1a_cases(txns: List[Txn]) -> List[dict]:
    """Reads of values appended by failed txns."""
    failed: Set[Tuple[Any, Any]] = {
        (k, v)
        for t in txns
        if t.failed
        for f, k, v in mops(t)
        if f == APPEND
    }
    cases = []
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f != R:
                continue
            for element in v or []:
                if (k, element) in failed:
                    cases.append(
                        {
                            "op": t.complete.to_dict(),
                            "mop": [f, k, list(v)],
                            "element": element,
                        }
                    )
    return cases


def g1b_cases(
    txns: List[Txn], appends_by_txn: Dict[Txn, Dict[Any, List[Any]]]
) -> List[dict]:
    """Reads observing an *intermediate* state of some txn: the read's
    list ends inside a txn's appends to that key (sees some but not the
    final one)."""
    # (k, element) -> (txn, position among txn's appends to k, total)
    pos: Dict[Tuple[Any, Any], Tuple[Txn, int, int]] = {}
    for t, per_key in appends_by_txn.items():
        for k, els in per_key.items():
            for i, el in enumerate(els):
                pos[(k, el)] = (t, i, len(els))
    cases = []
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f != R or not v:
                continue
            last = v[-1]
            hit = pos.get((k, last))
            if hit is not None:
                writer, i, total = hit
                if i < total - 1 and writer is not t:
                    cases.append(
                        {
                            "op": t.complete.to_dict(),
                            "mop": [f, k, list(v)],
                            "element": last,
                        }
                    )
    return cases


def duplicate_cases(txns: List[Txn]) -> List[dict]:
    """A read observing the same element twice."""
    cases = []
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f != R or not v:
                continue
            seen: Set[Any] = set()
            dups = []
            for el in v:
                if el in seen:
                    dups.append(el)
                seen.add(el)
            if dups:
                cases.append(
                    {"op": t.complete.to_dict(), "mop": [f, k, list(v)],
                     "duplicates": dups}
                )
    return cases


def version_orders(
    txns: List[Txn],
) -> Tuple[Dict[Any, List[Any]], List[dict]]:
    """Per-key total order of elements from read prefixes.

    All reads of a key must be prefix-comparable; the longest read is the
    order.  Returns (orders, incompatible-order cases)."""
    longest: Dict[Any, List[Any]] = {}
    incompatible: List[dict] = []
    seen_reads: Dict[Any, List[Tuple[Txn, List[Any]]]] = defaultdict(list)
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f != R or v is None:
                continue
            v = list(v)
            seen_reads[k].append((t, v))
            cur = longest.get(k)
            if cur is None or len(v) > len(cur):
                longest[k] = v
    for k, reads in seen_reads.items():
        order = longest.get(k) or []
        for t, v in reads:
            if v != order[: len(v)]:
                incompatible.append(
                    {"key": k, "read": v, "longest": order,
                     "op": t.complete.to_dict()}
                )
    return longest, incompatible


def graph_and_anomalies(
    history: History,
    extra_graphs: Tuple[str, ...] = (),
) -> Tuple[Graph, List[Txn], Dict[str, list]]:
    """Build the dependency graph and collect non-cycle anomalies."""
    txns = core.transactions(history)
    anomalies: Dict[str, list] = {}

    appends_by_txn: Dict[Txn, Dict[Any, List[Any]]] = {}
    writer_of: Dict[Tuple[Any, Any], Txn] = {}
    for t in txns:
        if t.failed:
            continue  # failed appends never took effect (except G1a checks)
        per_key: Dict[Any, List[Any]] = defaultdict(list)
        for f, k, v in mops(t):
            if f == APPEND:
                per_key[k].append(v)
                writer_of[(k, v)] = t
        if per_key:
            appends_by_txn[t] = dict(per_key)

    internal = internal_cases(txns)
    if internal:
        anomalies["internal"] = internal
    g1a = g1a_cases(txns)
    if g1a:
        anomalies["G1a"] = g1a
    g1b = g1b_cases(txns, appends_by_txn)
    if g1b:
        anomalies["G1b"] = g1b
    dups = duplicate_cases(txns)
    if dups:
        anomalies["duplicate-elements"] = dups

    orders, incompatible = version_orders(txns)
    if incompatible:
        anomalies["incompatible-order"] = incompatible

    g = Graph()
    for t in txns:
        if t.ok:
            g.add_vertex(t)

    # Elements appended but never observed extend the version order only
    # when a single txn appended them (order within a txn is known).
    for k, order in orders.items():
        # ww: consecutive elements in the version order
        for a, b in zip(order, order[1:]):
            wa, wb = writer_of.get((k, a)), writer_of.get((k, b))
            if wa is not None and wb is not None and wa.ok and wb.ok:
                g.add_edge(wa, wb, WW)

    for t in txns:
        if not t.ok:
            continue
        own = appends_by_txn.get(t, {})
        for f, k, v in mops(t):
            if f != R:
                continue
            v = list(v or [])
            # strip our own appended suffix: deps are external
            own_els = own.get(k, [])
            while v and own_els and v[-1] in own_els:
                v.pop()
            if v:
                w = writer_of.get((k, v[-1]))
                if w is not None and w.ok and w is not t:
                    g.add_edge(w, t, WR)  # we read w's final visible append
            # rw: we did not observe the next element in the order
            order = orders.get(k, [])
            nxt_idx = len(v)  # we saw order[:len(v)]
            if v == order[: len(v)] and nxt_idx < len(order):
                w2 = writer_of.get((k, order[nxt_idx]))
                if w2 is not None and w2.ok and w2 is not t:
                    g.add_edge(t, w2, RW)

    # dirty-update: a failed append that lands in the version order ahead
    # of committed ones (observed in some read)
    dirty = []
    for k, order in orders.items():
        for el in order:
            w = writer_of.get((k, el))
            if w is None:
                # element read but not appended by any ok/info txn
                failed_writers = [
                    t
                    for t in txns
                    if t.failed
                    and any(
                        f == APPEND and kk == k and vv == el
                        for f, kk, vv in mops(t)
                    )
                ]
                if failed_writers:
                    dirty.append({"key": k, "element": el})
    if dirty:
        anomalies["dirty-update"] = dirty

    if PROCESS in extra_graphs:
        g = g.union(core.process_graph(txns))
    if REALTIME in extra_graphs:
        g = g.union(core.realtime_graph(txns))

    return g, txns, anomalies


def cycle_anomalies(g: Graph) -> Dict[str, list]:
    """Classify cycles in the dependency graph by edge profile."""
    return cycles_mod.classify(g)


def prepare(history: History, opts: Optional[dict] = None):
    """The host half of a check, ahead of cycle classification: parse
    opts, build the dependency graph, and collect the non-cycle
    anomalies.  Returns ``(g, txns, anomalies, wanted)`` — the batch
    entry (``elle.check_batch``) prepares every history first so all
    the graphs screen in ONE engine pass."""
    from . import consistency

    opts = opts or {}
    wanted = consistency.proscribed(opts)
    extra: Tuple[str, ...] = ()
    if any(a.endswith("-realtime") for a in wanted):
        extra += (REALTIME,)
    if any(a.endswith("-process") for a in wanted):
        extra += (PROCESS,)

    g, txns, anomalies = graph_and_anomalies(history, extra_graphs=extra)
    return g, txns, anomalies, wanted


def finish(prep, cyc_anomalies: Dict[str, list]) -> dict:
    """Fold classified cycle anomalies into a prepared analysis."""
    from . import consistency

    g, txns, anomalies, wanted = prep
    anomalies.update(cyc_anomalies)
    return consistency.result(anomalies, wanted, txn_count=len(txns))


def check(history: History, opts: Optional[dict] = None) -> dict:
    """Full list-append analysis.  opts: consistency-models (list of
    model names, default ["strict-serializable"]), or anomalies (explicit
    list to look for); ``screen-route`` forces the cycle screens'
    device/cpu routing (default: self-calibrating auto)."""
    prep = prepare(history, opts)
    cyc = cycles_mod.classify_graphs(
        [prep[0]], route=(opts or {}).get("screen-route")
    )[0]
    return finish(prep, cyc)
