"""Elle transactional-screen smoke check: ``python -m
jepsen_tpu.elle.smoke``.

The engine-routed transactional checking gate (doc/checker-engines.md
"Transactional screens"): a mixed corpus of list-append and
rw-register transaction histories — mixed sizes (graphs landing in
different vertex buckets), cyclic and acyclic, valid and anomalous,
plain and realtime-suffixed consistency models (the lifted
nonadjacent-rw kernels and the process/realtime filter masks) — runs
through the production ``elle.check_batch`` path with the device
screens forced ON and forced OFF, and fails loudly on:

- ANY divergence between screened and pure-CPU result dicts
  (byte-identical verdicts, anomaly types, witness cycles);
- the boolean has-cycle route (dense closure) disagreeing with the
  host reference on mixed-size adjacency batches;
- missing screen evidence: the device route counter and the
  graphs-per-dispatch histogram must record;
- a budget-accounting breach: with a deliberately tiny dispatch cap
  the engine executor must chunk the screen buckets, and no kernel's
  peak in-flight per-chip rows may exceed its cap (the same
  ``chip_row_accounting`` hook the mesh/tune gates assert on).

Run plain for the single-device gate and with
``JEPSEN_TPU_ENGINE_MESH=1`` for the 8-virtual-device sharded gate
(the Makefile's ``elle-smoke`` target runs both).

Exit codes: 0 ok, 1 divergence or missing evidence.
"""

from __future__ import annotations

import json
import os
import sys


def _corpus(mode: str):
    """Seeded mixed-size transaction histories: workload-generator
    traffic against the serializable in-memory store, with a
    handcrafted committed wr-dependency cycle injected into every
    third history (G1c in either workload mode)."""
    from jepsen_tpu import fake
    from jepsen_tpu import generator as g
    from jepsen_tpu.generator import sim
    from jepsen_tpu.history import History, Op
    from jepsen_tpu.workloads.cycle import TxnGenerator

    hists = []
    sizes = [8] * 8 + [20] * 6 + [40] * 4  # buckets 16 / 32 / 64
    for h_i, n_txns in enumerate(sizes):
        client = fake.TxnAtomClient()

        def complete(ctx, inv):
            return {**client.invoke(None, inv), "time": inv["time"] + 10}

        txn_gen = TxnGenerator(
            mode,
            {"key-count": 6, "min-txn-length": 1, "max-txn-length": 4,
             "max-writes-per-key": 8},
        )
        dicts = sim.simulate(g.limit(n_txns, txn_gen), complete)
        if h_i % 3 == 0:
            t0 = max((d.get("time") or 0) for d in dicts) + 100
            kx, ky = "__bx", "__by"
            if mode == "append":
                t1 = [["append", kx, 1], ["r", ky, [2]]]
                t2 = [["append", ky, 2], ["r", kx, [1]]]
            else:
                t1 = [["w", kx, 1], ["r", ky, 2]]
                t2 = [["w", ky, 2], ["r", kx, 1]]
            for p, txn, dt in ((91, t1, 0), (92, t2, 1)):
                dicts.append({"process": p, "type": "invoke",
                              "f": "txn", "value": txn, "time": t0 + dt})
                dicts.append({"process": p, "type": "ok", "f": "txn",
                              "value": txn, "time": t0 + 10 + dt})
        hists.append(History([Op.from_dict(d) for d in dicts]).index_ops())
    return hists


def _dumps(results) -> str:
    return json.dumps(results, sort_keys=True, default=repr)


def main(argv=None) -> int:
    from jepsen_tpu.platform import force_cpu_platform

    force_cpu_platform(8)

    import numpy as np

    from jepsen_tpu import elle, obs
    from jepsen_tpu.elle import encode as elle_encode
    from jepsen_tpu.engine import execution
    from jepsen_tpu.ops import cycles as ops_cycles

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # -- screened vs CPU byte-equality, both workloads × both model
    # families (serializable: plain masks; strict-serializable:
    # realtime graphs → suffixed masks + the second lifted kernel)
    for mode, workload in (("wr", "rw-register"), ("append", "list-append")):
        hists = _corpus(mode)
        for models in (["serializable"], ["strict-serializable"]):
            opts = {"workload": workload, "consistency-models": models}
            cpu = elle.check_batch({**opts, "screen-route": "cpu"}, hists)
            obs.enable(reset=True)
            dev = elle.check_batch({**opts, "screen-route": "device"}, hists)
            reg = obs.registry()
            label = f"{workload}/{models[0]}"
            check(
                _dumps(cpu) == _dumps(dev),
                f"{label}: screened results diverge from CPU",
            )
            verdicts = {r["valid?"] for r in cpu}
            check(
                verdicts == {True, False},
                f"{label}: corpus should mix verdicts, got {verdicts}",
            )
            check(
                (reg.value("jepsen_elle_screen_route_total",
                           route="device") or 0) > 0,
                f"{label}: no device-routed screens recorded",
            )
            check(
                (reg.value("jepsen_elle_witness_fallback_total") or 0) > 0,
                f"{label}: no witness-search fallbacks recorded "
                "(the corpus injects cycles)",
            )
            obs.enable(reset=True)

    # -- the boolean has-cycle route on mixed-size adjacency batches
    rng = np.random.default_rng(45100)
    mats = []
    for n in (5, 12, 24, 40, 70):
        m = rng.random((n, n)) < 0.12
        np.fill_diagonal(m, False)
        mats.append(m)
        mats.append(np.triu(m))  # acyclic twin
    got = ops_cycles.has_cycle_batch(mats)
    want = [ops_cycles._np_has_cycle(np.asarray(m, bool)) for m in mats]
    check(list(got) == want, "has_cycle_batch diverges from host closure")
    check(True in want and False in want, "has-cycle batch should mix")

    # -- budget accounting through an explicit resident executor: a
    # tiny dispatch cap must chunk the buckets, and no kernel's peak
    # in-flight per-chip rows may exceed its cap
    preps = [elle.rw_register.prepare(h, {"workload": "rw-register"})
             for h in _corpus("wr")]
    encs = [elle_encode.encode_graph(p[0]) for p in preps]
    ex = execution.Executor(4)
    base = ops_cycles.screen_graphs(encs)
    capped = ops_cycles.screen_graphs(encs, executor=ex, max_dispatch=4)
    for a, b in zip(base, capped):
        same = (a is None) == (b is None) and (
            a is None or (
                all(np.array_equal(a.members[k], b.members[k])
                    for k in a.members)
                and all(np.array_equal(a.walks[k], b.walks[k])
                        for k in a.walks)
            )
        )
        check(same, "capped screen masks diverge from uncapped")
        if not same:
            break
    if ex.n_devices == 1:
        # chunk caps scale ×n_devices on a mesh (per-chip budget ×
        # slice width), so only the single-device gate pins chunking
        check(ex.submitted >= len(encs) // 4,
              f"cap=4 should chunk dispatches, submitted={ex.submitted}")
    check(ex.submitted > 0, "no screen dispatches reached the executor")
    for acct in ex.chip_row_accounting.values():
        cap = acct["chip_cap"]
        if acct["kernel"] == "dense":
            cap *= ex.window_size
        check(
            acct["peak_chip_rows"] <= cap,
            f"per-chip budget breach: {acct}",
        )
    mesh_mode = os.environ.get("JEPSEN_TPU_ENGINE_MESH", "").strip()
    if mesh_mode in ("1", "on", "true", "yes", "force"):
        check(ex.n_devices == 8,
              f"mesh gate expected 8 devices, got {ex.n_devices}")

    if failures:
        for f_ in failures:
            print(f"elle-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "elle-smoke: ok (screened ≡ CPU on list-append + rw-register × "
        "plain/realtime models; has-cycle route; budget accounting at "
        f"cap 4 over {ex.n_devices} device(s))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
