"""Elle-equivalent transactional anomaly checker.

Black-box transactional safety analysis: histories of micro-op
transactions are reduced to typed dependency graphs (ww/wr/rw +
process/realtime), and Adya anomalies are cycles with particular edge
profiles.  Two inference modes:

- :mod:`list_append` — appends + list reads; version order is recovered
  exactly from read prefixes (the strongest mode)
- :mod:`rw_register` — writes + point reads; version order is inferred
  from sound sources only

The reference consumes the external Elle 0.1.3 library for this
(jepsen/project.clj:11, jepsen/src/jepsen/tests/cycle.clj:5-16); here
it is native, with the bulk cycle screening offloadable to the
accelerator (jepsen_tpu.ops.cycles — batched boolean matrix closure on
the MXU).
"""

from __future__ import annotations

from typing import Optional

from ..history import History
from . import consistency, core, cycles, graph, list_append, rw_register


def _workload_module(opts: dict):
    workload = opts.get("workload", "list-append")
    if workload == "list-append":
        return list_append
    if workload == "rw-register":
        return rw_register
    raise KeyError(f"unknown elle workload {workload!r}")


def check(opts: Optional[dict], history: History) -> dict:
    """Elle-style entry point: opts include ``workload`` ("list-append"
    or "rw-register"), plus ``consistency-models`` / ``anomalies``."""
    opts = opts or {}
    return _workload_module(opts).check(history, opts)


def check_batch(opts: Optional[dict], histories) -> list:
    """Batched Elle analysis — the engine-routed production shape: all
    histories' dependency graphs are built first, then screened
    together through :func:`jepsen_tpu.elle.cycles.classify_graphs` —
    graphs from many histories (and, via the checker service, many
    concurrent runs) stack into shared ``(B, n, n)`` device
    dispatches through the engine Executor, and only graphs (and
    ladder rungs) the screens proved cyclic pay the CPU Tarjan +
    witness search.  ``opts["screen-route"]`` forces
    ``"device"``/``"cpu"`` routing (default: self-calibrating auto).
    Per-history results are byte-identical to :func:`check`."""
    opts = opts or {}
    mod = _workload_module(opts)
    preps = [mod.prepare(h, opts) for h in histories]
    cyc = cycles.classify_graphs(
        [p[0] for p in preps], route=opts.get("screen-route")
    )
    return [mod.finish(p, c) for p, c in zip(preps, cyc)]
