"""Elle-equivalent transactional anomaly checker.

Black-box transactional safety analysis: histories of micro-op
transactions are reduced to typed dependency graphs (ww/wr/rw +
process/realtime), and Adya anomalies are cycles with particular edge
profiles.  Two inference modes:

- :mod:`list_append` — appends + list reads; version order is recovered
  exactly from read prefixes (the strongest mode)
- :mod:`rw_register` — writes + point reads; version order is inferred
  from sound sources only

The reference consumes the external Elle 0.1.3 library for this
(jepsen/project.clj:11, jepsen/src/jepsen/tests/cycle.clj:5-16); here
it is native, with the bulk cycle screening offloadable to the
accelerator (jepsen_tpu.ops.cycles — batched boolean matrix closure on
the MXU).
"""

from __future__ import annotations

from typing import Optional

from ..history import History
from . import consistency, core, cycles, graph, list_append, rw_register


def check(opts: Optional[dict], history: History) -> dict:
    """Elle-style entry point: opts include ``workload`` ("list-append"
    or "rw-register"), plus ``consistency-models`` / ``anomalies``."""
    opts = opts or {}
    workload = opts.get("workload", "list-append")
    if workload == "list-append":
        return list_append.check(history, opts)
    if workload == "rw-register":
        return rw_register.check(history, opts)
    raise KeyError(f"unknown elle workload {workload!r}")
