"""Batched graph encoding for the TPU transactional screens.

The Elle side of the engine speaks graphs, not histories: a dependency
(or per-key version) graph becomes a dense **relation-bit matrix** —
``rel[i, j]`` is the OR of :data:`REL_BITS` for every dependency type
edge ``i → j`` carries — padded to a power-of-two vertex bucket, and
graphs from many keys, histories, and concurrent runs stack into
shared ``(B, n, n)`` dispatches exactly the way history encodes stack
into per-(E, C) buckets in :mod:`jepsen_tpu.engine.planning`.  The
device kernels (:mod:`jepsen_tpu.ops.cycles`) then answer, for every
graph and every relation filter of the classify ladder at once: which
vertices sit on a cycle (forward×backward closure intersection → SCC
membership masks), and which sit on a nonadjacent-rw closed walk (the
snapshot-isolation cycle test's lifted product graph).

Filter masks are **canonicalized per graph** to the relation bits the
graph actually contains (``25 & present``): a graph with no
process/realtime edges screens its suffixed ladder rungs through the
identical plain-relation closure instead of paying extra ones, and
graphs sharing a (bucket, filter-profile) key share one compiled
kernel and one dispatch row budget.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .graph import Graph, PROCESS, REALTIME, RW, WR, WW

#: relation-type → bit in the encoded adjacency entries.  The device
#: kernels AND these against static filter masks, so the assignment is
#: part of the kernel cache key contract — append, never renumber.
REL_BITS: Dict[str, int] = {WW: 1, WR: 2, RW: 4, PROCESS: 8, REALTIME: 16}

WW_BIT = REL_BITS[WW]
WR_BIT = REL_BITS[WR]
RW_BIT = REL_BITS[RW]
PR_MASK = REL_BITS[PROCESS] | REL_BITS[REALTIME]
ALL_MASK = WW_BIT | WR_BIT | RW_BIT | PR_MASK

#: the classify ladder's relation filters, pre-canonicalization: the
#: G0 / G1c / G2-item rungs and their process/realtime-suffixed
#: variants (elle.cycles.classify walks exactly these subgraphs)
LADDER_MASKS = (
    WW_BIT,
    WW_BIT | WR_BIT,
    WW_BIT | WR_BIT | RW_BIT,
    WW_BIT | PR_MASK,
    WW_BIT | WR_BIT | PR_MASK,
    ALL_MASK,
)

#: the nonadjacent-rw walk tests (want, rest): plain and suffixed —
#: the snapshot-isolation cycle characterization's screening question
NONADJ_MASKS = (
    (RW_BIT, WW_BIT | WR_BIT),
    (RW_BIT, WW_BIT | WR_BIT | PR_MASK),
)

#: smallest vertex bucket — matches ops.cycles._bucket so the screen
#: kernels and the boolean has-cycle closure share shape discipline
GRAPH_BUCKET_MIN = 16

#: boolean lanes per packed uint32 word — the word floor
#: :func:`graph_bucket` pads vertex counts to, so the ``packed32``
#: closure never sees ragged word lanes (mirrors
#: ``jepsen_tpu.ops.dense.WORD_LANES``; kept literal here to avoid an
#: elle → ops import for one constant)
WORD_LANES = 32

#: packed-plane weight of one lifted nonadjacent walk query: its
#: 2n×2n product graph carries four n×n planes' worth of closure
#: state, vs one plane per membership filter mask
LIFTED_PLANE_WEIGHT = 4


def plane_weight(masks: Sequence[int],
                 nonadj: Sequence[Tuple[int, int]],
                 impl: str = "uint8") -> int:
    """Packed closure planes (n×n-equivalents) one profile expands
    into on the batch axis — the ``F`` coordinate of a profile's
    ``(kernel="cycles", E, C, F)`` cost-table key since the
    plane-packing work: one plane per membership mask,
    :data:`LIFTED_PLANE_WEIGHT` per lifted walk query.  Floors at 1 so
    an edge-free profile (no masks, no queries) still ranks.

    ``impl="packed32"`` prices the whole profile at W/n ≈ 1/32 of its
    uint8 footprint (``⌈planes/32⌉``): a word-packed plane moves one
    uint32 word per 32 vertex lanes, so the cost-table coordinate, the
    analytic ``rows·E²·frontier`` proxy, and the scheduler's
    largest-first ordering all see the denser closure as ~32×
    cheaper — the pricing half of the word-packing contract
    (``ops.cycles.cycles_max_dispatch`` is the footprint half)."""
    base = max(1, len(masks) + LIFTED_PLANE_WEIGHT * len(nonadj))
    if impl == "packed32":
        return max(1, -(-base // WORD_LANES))
    return base


def rel_mask(rels) -> int:
    """OR of :data:`REL_BITS` over an edge's relation set."""
    m = 0
    for r in rels:
        m |= REL_BITS.get(r, 0)
    return m


def graph_bucket(n: int) -> int:
    """Pad vertex counts to powers of two (min
    :data:`GRAPH_BUCKET_MIN`) so compiled screen kernels are shared
    across graphs of nearby size — the same recompile-bounding
    discipline as ``ops.cycles._bucket`` and the engine's (E, C)
    buckets.

    Vertex counts first round up to a multiple of :data:`WORD_LANES`
    (the **word floor**) so the ``packed32`` closure's uint32 words
    never carry ragged lanes: every bucket a screen can see is a
    multiple of 32, making W = n/32 exact.  The effective minimum
    bucket is therefore 32.  Padding is provably inert — padded
    rows/columns carry no relation bits (:func:`stack_rel` zero-fills),
    an edge-free vertex is acyclic and unreachable, and the closure
    recurrence ``r ← r ∪ r·r`` never sets a bit no path witnesses —
    so a graph screened at bucket 32 answers byte-identically to the
    same graph at the pre-word-floor bucket 16."""
    n = -(-max(1, int(n)) // WORD_LANES) * WORD_LANES
    return max(GRAPH_BUCKET_MIN, 1 << (n - 1).bit_length())


class EncodedGraph:
    """One graph, host-encoded for the screens: the deterministic
    vertex ``order`` (the same sort ``Graph.adjacency`` uses, so
    device masks and CPU searches can never disagree about which row
    is which vertex), the ``(n, n)`` uint8 relation-bit matrix, the
    union of bits actually ``present``, and the canonicalized filter
    profile (``masks``, ``nonadj``) this graph needs screened."""

    __slots__ = ("order", "rel", "present", "masks", "nonadj")

    def __init__(self, order, rel, present, masks, nonadj):
        self.order = order
        self.rel = rel
        self.present = present
        self.masks = masks
        self.nonadj = nonadj

    @property
    def n(self) -> int:
        return len(self.order)


def encode_graph(g: Graph) -> EncodedGraph:
    """Encode one dependency graph into its relation-bit matrix and
    canonical screen profile."""
    order = sorted(g.vertices, key=str)
    index = {v: i for i, v in enumerate(order)}
    n = len(order)
    rel = np.zeros((n, n), dtype=np.uint8)
    present = 0
    for a, nbrs in g.out.items():
        ia = index[a]
        for b, rels in nbrs.items():
            m = rel_mask(rels)
            rel[ia, index[b]] = m
            present |= m
    masks = tuple(sorted({m & present for m in LADDER_MASKS} - {0}))
    if present & RW_BIT:
        nonadj = tuple(sorted(
            {(RW_BIT, rest & present) for _w, rest in NONADJ_MASKS}
        ))
    else:
        # no rw edge anywhere: every nonadjacent-rw question is a
        # definitive no without a kernel
        nonadj = ()
    return EncodedGraph(order, rel, present, masks, nonadj)


def bucket_key(enc: EncodedGraph) -> Tuple[int, tuple, tuple]:
    """The shared-dispatch key: vertex bucket + canonical filter
    profile.  Graphs from different keys/histories/runs with the same
    key stack into one ``(B, n, n)`` dispatch and one compiled
    kernel."""
    return (graph_bucket(enc.n), enc.masks, enc.nonadj)


def bucket_graphs(
    encs: Sequence[EncodedGraph],
) -> Tuple[Dict[tuple, List[int]], List[tuple]]:
    """Group encoded graphs by :func:`bucket_key`; returns
    ``(buckets, order)`` with ``buckets[key] = [enc index, ...]`` in
    first-seen key order — the same bucket-stream shape
    ``Planner.encode_buckets`` produces for histories."""
    buckets: Dict[tuple, List[int]] = {}
    order: List[tuple] = []
    for i, enc in enumerate(encs):
        key = bucket_key(enc)
        acc = buckets.get(key)
        if acc is None:
            acc = buckets[key] = []
            order.append(key)
        acc.append(i)
    return buckets, order


def stack_rel(encs: Sequence[EncodedGraph], n: int) -> np.ndarray:
    """Stack encoded graphs into one padded ``(B, n, n)`` uint8 batch;
    padding rows/cols carry no edges, so they are acyclic by
    construction and never perturb a screen."""
    batch = np.zeros((len(encs), n, n), dtype=np.uint8)
    for row, enc in enumerate(encs):
        k = enc.n
        batch[row, :k, :k] = enc.rel
    return batch
