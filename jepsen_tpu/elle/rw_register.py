"""Read-write register transactional anomaly analysis.

Transactions are lists of ``["w", k, v]`` / ``["r", k, v]`` micro-ops
with distinct written values per key.  Unlike list-append, reads reveal
only a point version, so the per-key version order must be *inferred*
from sound sources:

- initial: ``None`` precedes every written value of the key
- intra-txn: two writes of one key in one txn are ordered
- read→write: a txn reading u then writing v orders u before v
- realtime/process (optional, per the consistency model sought):
  a committed write of u completing before a write of v begins orders
  u before v

The union forms a per-key version DAG; a cycle there is reported as
``cyclic-versions`` (verdict unknown, like Elle).  Dependencies follow:
wr (writer → reader of the same version), ww (writer u → writer v for
u < v), rw (reader of u → writer of any v > u).
(reference consumer: jepsen/src/jepsen/tests/cycle/wr.clj)
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Set, Tuple

from ..history import History
from ..txn import R, W
from . import core
from .core import Txn
from .graph import Graph, WW, WR, RW, PROCESS, REALTIME
from . import cycles as cycles_mod

INIT = ("init",)  # sentinel for the unwritten initial version


def mops(t: Txn):
    return t.value or []


def internal_cases(txns: List[Txn]) -> List[dict]:
    """A read must agree with the txn's own latest prior write/read of
    that key."""
    cases = []
    for t in txns:
        if not t.ok:
            continue
        state: Dict[Any, Any] = {}
        for f, k, v in mops(t):
            if f == W:
                state[k] = v
            else:
                if k in state and state[k] != v:
                    cases.append(
                        {"op": t.complete.to_dict(), "mop": [f, k, v],
                         "expected": state[k]}
                    )
                state[k] = v
    return cases


def g1a_cases(txns: List[Txn]) -> List[dict]:
    """Reads of values written by failed txns."""
    failed = {
        (k, v): t
        for t in txns
        if t.failed
        for f, k, v in mops(t)
        if f == W
    }
    cases = []
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f == R and v is not None and (k, v) in failed:
                cases.append({"op": t.complete.to_dict(), "mop": [f, k, v]})
    return cases


def g1b_cases(txns: List[Txn]) -> List[dict]:
    """Reads of a txn's non-final (intermediate) write of a key."""
    intermediate: Dict[Tuple[Any, Any], Txn] = {}
    for t in txns:
        if not t.ok:
            continue
        last_write: Dict[Any, Any] = {}
        writes_in_order: Dict[Any, List[Any]] = defaultdict(list)
        for f, k, v in mops(t):
            if f == W:
                writes_in_order[k].append(v)
                last_write[k] = v
        for k, vs in writes_in_order.items():
            for v in vs[:-1]:
                intermediate[(k, v)] = t
    cases = []
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f == R and (k, v) in intermediate and intermediate[(k, v)] is not t:
                cases.append({"op": t.complete.to_dict(), "mop": [f, k, v]})
    return cases


def lost_update_cases(txns: List[Txn]) -> List[dict]:
    """Two (or more) committed txns that both externally read version v
    of key k and both externally wrote k: only one of those updates can
    have seen the other, so an update was lost.  (Elle's
    elle.txn/lost-update-cases; proscribed from cursor stability /
    snapshot isolation upward.)"""
    groups: Dict[Tuple[Any, Any], List[Txn]] = defaultdict(list)
    for t in txns:
        if not t.ok:
            continue
        written = {k for f, k, _v in mops(t) if f == W}
        seen: Set[Any] = set()
        for f, k, v in mops(t):
            if f == W:
                seen.add(k)
            elif f == R and k not in seen:
                seen.add(k)
                if k in written:  # external read + external write of k
                    groups[(k, v)].append(t)
    return [
        {
            "key": k,
            "value": v,
            "txns": [t.complete.to_dict() for t in ts],
        }
        for (k, v), ts in sorted(groups.items(), key=lambda kv: str(kv[0]))
        if len(ts) > 1
    ]


def _ext_write(t: Txn, k: Any) -> Optional[Any]:
    """The txn's final (externally visible) write of k, or None."""
    out = None
    for f, kk, v in mops(t):
        if f == W and kk == k:
            out = v
    return out


def version_graphs(
    txns: List[Txn], extra: Tuple[str, ...] = ()
) -> Tuple[Dict[Any, Graph], List[dict]]:
    """Per-key version DAGs from the sound order sources.  Returns
    (key → graph over values, cyclic-versions cases)."""
    graphs: Dict[Any, Graph] = defaultdict(Graph)

    writers: Dict[Tuple[Any, Any], Txn] = {}
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f == W:
                writers[(k, v)] = t
                graphs[k].add_edge(INIT, v, "version")

    for t in txns:
        if not t.ok:
            continue
        last_seen: Dict[Any, Any] = {}
        for f, k, v in mops(t):
            if f == W:
                prev = last_seen.get(k)
                if prev is not None and prev != v:
                    graphs[k].add_edge(prev, v, "version")
                last_seen[k] = v
            elif f == R:
                vv = v if v is not None else INIT
                prev = last_seen.get(k)
                if prev is None:
                    last_seen[k] = vv

    if REALTIME in extra or PROCESS in extra:
        # committed write of u completes before write of v begins
        writes: List[Tuple[Txn, Any, Any]] = []
        for t in txns:
            if not t.ok:
                continue
            for k in {kk for f, kk, _ in mops(t) if f == W}:
                writes.append((t, k, _ext_write(t, k)))
        for t1, k1, u in writes:
            for t2, k2, v in writes:
                if k1 != k2 or u == v:
                    continue
                if REALTIME in extra and t1.complete.time < t2.invoke.time:
                    graphs[k1].add_edge(u, v, "version")
                elif (
                    PROCESS in extra
                    and t1.process == t2.process
                    and t1.complete.time <= t2.invoke.time
                ):
                    graphs[k1].add_edge(u, v, "version")

    # Batched cycle screen over every per-key version graph at once —
    # the device closure kernel (or per-graph SCC, whichever the
    # self-calibrating router picks for this backend and size); only
    # keys the screen flags pay the detailed SCC extraction (cyclic
    # keys are anomalies, so the double pass is the rare case).  This
    # is the Elle-on-TPU seam from SURVEY.md §7 step 8 running inside
    # the production pipeline, not just the benchmark.  Batches the
    # screen can't win (few graphs, or any graph past the device
    # vertex cap) keep the direct per-graph SCC pass — routing through
    # the mask there would compute SCCs and throw them away.
    cyclic = []
    items = list(graphs.items())
    use_screen = len(items) >= 16 and all(
        len(g.vertices) <= cycles_mod.DEVICE_SCREEN_MAX_VERTICES
        for _k, g in items
    )
    if use_screen:
        mask = cycles_mod.cyclic_graph_mask([g for _k, g in items])
    else:
        mask = [
            bool(cycles_mod.strongly_connected_components(g))
            for _k, g in items
        ]
    for (k, g), has_cycle in zip(items, mask):
        if has_cycle:
            sccs = cycles_mod.strongly_connected_components(g)
            cyclic.append(
                {"key": k, "sccs": [[repr(v) for v in c] for c in sccs]}
            )
    return graphs, cyclic


def _closure(g: Graph) -> Dict[Any, Set[Any]]:
    """value → set of values strictly after it.  Iterative post-order
    DFS (version chains can be thousands deep; recursion would blow the
    stack); back-edges (cycles) contribute nothing here and are reported
    separately as cyclic-versions."""
    memo: Dict[Any, Set[Any]] = {}
    visiting: Set[Any] = set()
    for root in g.vertices:
        if root in memo:
            continue
        stack: List[Tuple[Any, bool]] = [(root, False)]
        while stack:
            v, expanded = stack.pop()
            if expanded:
                out: Set[Any] = set()
                for w in g.successors(v):
                    out.add(w)
                    out |= memo.get(w, set())
                memo[v] = out
                visiting.discard(v)
                continue
            if v in memo or v in visiting:
                continue
            visiting.add(v)
            stack.append((v, True))
            for w in g.successors(v):
                if w not in memo and w not in visiting:
                    stack.append((w, False))
    return memo


def graph_and_anomalies(
    history: History, extra_graphs: Tuple[str, ...] = ()
) -> Tuple[Graph, List[Txn], Dict[str, list]]:
    txns = core.transactions(history)
    anomalies: Dict[str, list] = {}

    internal = internal_cases(txns)
    if internal:
        anomalies["internal"] = internal
    g1a = g1a_cases(txns)
    if g1a:
        anomalies["G1a"] = g1a
    g1b = g1b_cases(txns)
    if g1b:
        anomalies["G1b"] = g1b
    lost = lost_update_cases(txns)
    if lost:
        anomalies["lost-update"] = lost

    vgraphs, cyclic = version_graphs(txns, extra_graphs)
    if cyclic:
        anomalies["cyclic-versions"] = cyclic

    writers: Dict[Tuple[Any, Any], Txn] = {}
    for t in txns:
        if not t.ok:
            continue
        for f, k, v in mops(t):
            if f == W:
                writers[(k, v)] = t

    g = Graph()
    for t in txns:
        if t.ok:
            g.add_vertex(t)

    closures = {k: _closure(vg) for k, vg in vgraphs.items()}

    for k, vg in vgraphs.items():
        after = closures[k]
        # ww: writer of u → writer of each later version v
        for u, vs in after.items():
            wu = writers.get((k, u))
            if u is not INIT and wu is None:
                continue
            for v in vs:
                wv = writers.get((k, v))
                if wu is not None and wv is not None and wu is not wv:
                    g.add_edge(wu, wv, WW)

    for t in txns:
        if not t.ok:
            continue
        # external reads: first read of k before any write in this txn
        written: Set[Any] = set()
        seen_keys: Set[Any] = set()
        for f, k, v in mops(t):
            if f == W:
                written.add(k)
            elif f == R and k not in written and k not in seen_keys:
                seen_keys.add(k)
                vv = v if v is not None else INIT
                w = writers.get((k, vv))
                if w is not None and w is not t:
                    g.add_edge(w, t, WR)
                # rw: t read vv; any later version's writer overwrote it
                for v2 in closures.get(k, {}).get(vv, ()):
                    w2 = writers.get((k, v2))
                    if w2 is not None and w2 is not t:
                        g.add_edge(t, w2, RW)

    if PROCESS in extra_graphs:
        g = g.union(core.process_graph(txns))
    if REALTIME in extra_graphs:
        g = g.union(core.realtime_graph(txns))

    return g, txns, anomalies


def prepare(history: History, opts: Optional[dict] = None):
    """The host half of a check, ahead of cycle classification (see
    ``list_append.prepare``).  Returns ``(g, txns, anomalies,
    wanted)``."""
    from . import consistency

    opts = opts or {}
    wanted = consistency.proscribed(opts)
    extra: Tuple[str, ...] = ()
    if any(a.endswith("-realtime") for a in wanted):
        extra += (REALTIME,)
    if any(a.endswith("-process") for a in wanted):
        extra += (PROCESS,)

    g, txns, anomalies = graph_and_anomalies(history, extra_graphs=extra)
    return g, txns, anomalies, wanted


def finish(prep, cyc_anomalies) -> dict:
    """Fold classified cycle anomalies into a prepared analysis."""
    from . import consistency

    g, txns, anomalies, wanted = prep
    anomalies.update(cyc_anomalies)
    out = consistency.result(anomalies, wanted, txn_count=len(txns))
    # A cyclic version order makes a clean verdict unreachable — but never
    # masks a definite anomaly already found.
    if "cyclic-versions" in anomalies and out["valid?"] is True:
        out["valid?"] = "unknown"
    return out


def check(history: History, opts: Optional[dict] = None) -> dict:
    """Full rw-register analysis; same opts as list_append.check."""
    prep = prepare(history, opts)
    cyc = cycles_mod.classify_graphs(
        [prep[0]], route=(opts or {}).get("screen-route")
    )[0]
    return finish(prep, cyc)
