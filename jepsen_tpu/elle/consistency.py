"""Consistency models → proscribed anomalies, and verdict shaping.

A small lattice in the spirit of Elle's elle.consistency-model
(consumed transitively by the reference at
jepsen/src/jepsen/tests/cycle/wr.clj:33-47, whose docstring enumerates
these same anomaly names).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

#: Anomalies each model proscribes.  Weaker models inherit into stronger
#: ones below.
_BASE: Dict[str, Set[str]] = {
    "read-uncommitted": {"G0", "dirty-update", "duplicate-elements",
                         "incompatible-order"},
    "read-committed": {"G1a", "G1b", "G1c", "internal"},
    "repeatable-read": {"G2-item", "lost-update"},
    "snapshot-isolation": {"G-single", "G-nonadjacent", "lost-update"},
    "serializable": {"G-single", "G-nonadjacent", "G2-item"},
    "strict-serializable": {
        "G0-realtime", "G1c-realtime", "G-single-realtime",
        "G-nonadjacent-realtime", "G2-item-realtime",
    },
    "sequential": {
        "G0-process", "G1c-process", "G-single-process",
        "G-nonadjacent-process", "G2-item-process",
    },
}

#: What each model implies (transitively expanded at lookup).
_IMPLIES: Dict[str, Sequence[str]] = {
    "read-committed": ("read-uncommitted",),
    "repeatable-read": ("read-committed",),
    "snapshot-isolation": ("read-committed",),
    "serializable": ("repeatable-read", "snapshot-isolation"),
    "sequential": ("serializable",),
    "strict-serializable": ("serializable", "sequential"),
}

KNOWN_MODELS = sorted(_BASE)

#: Cycle anomalies implied by others (a G0 is also a G1c profile etc.) —
#: used only for reporting, not detection.
SEVERITY = [
    "G0", "G1c", "G-single", "G-nonadjacent", "G2-item",
    "G0-process", "G1c-process", "G-single-process",
    "G-nonadjacent-process", "G2-item-process",
    "G0-realtime", "G1c-realtime", "G-single-realtime",
    "G-nonadjacent-realtime", "G2-item-realtime",
    "G1a", "G1b", "lost-update", "dirty-update", "internal",
    "duplicate-elements", "incompatible-order",
]


def proscribed_for_model(model: str) -> Set[str]:
    if model not in _BASE:
        raise KeyError(f"unknown consistency model {model!r}; known: {KNOWN_MODELS}")
    out = set(_BASE[model])
    for dep in _IMPLIES.get(model, ()):
        out |= proscribed_for_model(dep)
    return out


def proscribed(opts: dict) -> Set[str]:
    """The set of anomaly names that invalidate this test, from opts:
    either explicit ``anomalies`` or ``consistency-models`` (default
    strict-serializable)."""
    out: Set[str] = set()
    for a in opts.get("anomalies", ()):
        if a == "G1":
            out |= {"G1a", "G1b", "G1c"}
        elif a == "G2":
            out |= {"G-single", "G-nonadjacent", "G2-item"}
        else:
            out.add(a)
    for m in opts.get("consistency-models") or (
        [] if opts.get("anomalies") else ["strict-serializable"]
    ):
        out |= proscribed_for_model(m)
    return out


#: classify() names each cycle by its most-specific profile, but a
#: specific profile is still an *instance* of the general ones — a
#: single-rw cycle is also a nonadjacent-rw cycle and an item
#: anti-dependency cycle.  A model proscribing the general name must
#: therefore reject the specific finding too (Elle's implied-anomalies).
_INSTANCE_OF: Dict[str, Sequence[str]] = {
    "G-single": ("G-nonadjacent", "G2-item"),
    "G-nonadjacent": ("G2-item",),
    "G-single-process": ("G-nonadjacent-process", "G2-item-process"),
    "G-nonadjacent-process": ("G2-item-process",),
    "G-single-realtime": ("G-nonadjacent-realtime", "G2-item-realtime"),
    "G-nonadjacent-realtime": ("G2-item-realtime",),
    "G0": ("G1c",),
    "G0-process": ("G1c-process",),
    "G0-realtime": ("G1c-realtime",),
}


def _proscribed_name(name: str, wanted: Set[str]) -> bool:
    return name in wanted or any(
        g in wanted for g in _INSTANCE_OF.get(name, ())
    )


def result(
    anomalies: Dict[str, list], wanted: Set[str], txn_count: int = 0
) -> dict:
    """Shape the final verdict: valid iff no *proscribed* anomaly was
    found; unproscribed findings are reported under also-anomalies."""
    bad = {k: v for k, v in anomalies.items() if _proscribed_name(k, wanted)}
    also = {k: v for k, v in anomalies.items() if k not in bad}
    out: dict = {
        "valid?": not bad,
        "txn-count": txn_count,
        "anomaly-types": sorted(bad, key=_severity_key),
        "anomalies": bad,
    }
    if also:
        out["also-anomaly-types"] = sorted(also, key=_severity_key)
        out["also-anomalies"] = also
    if out["valid?"] is True:
        # "-indeterminate" markers mean a bounded search gave up before
        # confirming or refuting the base anomaly (e.g. G-nonadjacent's
        # simple-cycle budget).  If the model proscribes that anomaly —
        # by exact name or any suffixed variant — a clean pass is not
        # provable: report unknown, never a false valid.
        for k in anomalies:
            if not k.endswith("-indeterminate"):
                continue
            base = k[: -len("-indeterminate")]
            if _proscribed_name(base, wanted) or any(
                w.startswith(base) for w in wanted
            ):
                out["valid?"] = "unknown"
                break
    return out


def _severity_key(name: str) -> int:
    try:
        return SEVERITY.index(name)
    except ValueError:
        return len(SEVERITY)
