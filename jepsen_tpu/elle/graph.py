"""Typed dependency graphs and cycle search.

The transactional checker reduces a history to a directed graph whose
vertices are transactions and whose edges carry dependency types
(``ww``/``wr``/``rw``, plus ``process``/``realtime``).  Anomalies are
cycles with particular edge-type profiles, found via strongly-connected
components (Tarjan, iterative) and per-SCC BFS.

The reference consumes the external Elle library for this
(jepsen/project.clj:11; jepsen/src/jepsen/tests/cycle.clj:5-16).  The
hot screening step — does any cycle exist over thousands of per-key
graphs — can run on TPU via jepsen_tpu.ops.cycles (batched boolean
matrix closure); this module is the exact CPU path and witness extractor.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

#: Dependency edge types.
WW = "ww"
WR = "wr"
RW = "rw"
PROCESS = "process"
REALTIME = "realtime"


class Graph:
    """A directed multigraph: edges carry a set of dependency types."""

    def __init__(self):
        self.vertices: Set[Any] = set()
        self.out: Dict[Any, Dict[Any, Set[str]]] = defaultdict(dict)

    def add_vertex(self, v: Any) -> None:
        self.vertices.add(v)

    def add_edge(self, a: Any, b: Any, rel: str) -> None:
        if a == b:
            return  # self-deps are intra-txn; never cycle material
        self.vertices.add(a)
        self.vertices.add(b)
        rels = self.out[a].get(b)
        if rels is None:
            self.out[a][b] = {rel}
        else:
            rels.add(rel)

    def edge_rels(self, a: Any, b: Any) -> Set[str]:
        return self.out.get(a, {}).get(b, set())

    def successors(self, v: Any) -> Iterable[Any]:
        return self.out.get(v, {}).keys()

    def union(self, other: "Graph") -> "Graph":
        g = Graph()
        for v in self.vertices | other.vertices:
            g.add_vertex(v)
        for src in (self, other):
            for a, nbrs in src.out.items():
                for b, rels in nbrs.items():
                    for r in rels:
                        g.add_edge(a, b, r)
        return g

    def filtered(self, pred: Callable[[Set[str]], bool]) -> "Graph":
        """Subgraph keeping only edges whose rel-set satisfies pred."""
        g = Graph()
        for v in self.vertices:
            g.add_vertex(v)
        for a, nbrs in self.out.items():
            for b, rels in nbrs.items():
                if pred(rels):
                    for r in rels:
                        g.add_edge(a, b, r)
        return g

    def edge_count(self) -> int:
        return sum(len(nbrs) for nbrs in self.out.values())

    def adjacency(self, order: Optional[List[Any]] = None):
        """(order, dense bool numpy adjacency) — feed for the TPU kernel."""
        import numpy as np

        order = order or sorted(self.vertices, key=str)
        index = {v: i for i, v in enumerate(order)}
        n = len(order)
        m = np.zeros((n, n), dtype=bool)
        for a, nbrs in self.out.items():
            for b in nbrs:
                m[index[a], index[b]] = True
        return order, m


def strongly_connected_components(g: Graph) -> List[List[Any]]:
    """Tarjan's SCC, iterative (histories can be deep).  Only components
    with ≥2 vertices or a self-loop can hold cycles; we return all and
    let callers filter."""
    index: Dict[Any, int] = {}
    low: Dict[Any, int] = {}
    on_stack: Set[Any] = set()
    stack: List[Any] = []
    sccs: List[List[Any]] = []
    counter = [0]

    for root in g.vertices:
        if root in index:
            continue
        work: List[Tuple[Any, Optional[Iterable]]] = [(root, None)]
        while work:
            v, it = work.pop()
            if it is None:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
                it = iter(list(g.successors(v)))
            advanced = False
            for w in it:
                if w not in index:
                    work.append((v, it))
                    work.append((w, None))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return [c for c in sccs if len(c) > 1]


def find_cycle(g: Graph, scc: List[Any]) -> Optional[List[Any]]:
    """A shortest cycle within an SCC: BFS from each vertex back to
    itself through SCC-internal edges.  Returns [v1 v2 … v1] or None."""
    members = set(scc)
    for start in scc:
        parent: Dict[Any, Any] = {}
        q = deque([start])
        seen = {start}
        while q:
            v = q.popleft()
            for w in g.successors(v):
                if w not in members:
                    continue
                if w == start:
                    path = [v]
                    while path[-1] != start:
                        path.append(parent[path[-1]])
                    path.reverse()
                    path.append(start)
                    return path
                if w not in seen:
                    seen.add(w)
                    parent[w] = v
                    q.append(w)
    return None


def find_cycle_with(
    g: Graph,
    scc: List[Any],
    want: Callable[[Set[str]], bool],
    rest: Callable[[Set[str]], bool],
    want_count: int = 1,
) -> Optional[List[Any]]:
    """Find a cycle containing exactly ``want_count`` edges satisfying
    ``want``, all other edges satisfying ``rest``.  Used for G-single
    (exactly one rw, rest ww/wr).  BFS over a layered product graph:
    state = (vertex, #want-edges-used)."""
    members = set(scc)
    for start in scc:
        # state: (v, k) = reached v using k want-edges
        parent: Dict[Tuple[Any, int], Tuple[Any, int]] = {}
        q = deque([(start, 0)])
        seen = {(start, 0)}
        while q:
            v, k = q.popleft()
            for w in g.successors(v):
                if w not in members:
                    continue
                rels = g.edge_rels(v, w)
                steps = []
                if want(rels) and k < want_count:
                    steps.append(k + 1)
                if rest(rels):
                    steps.append(k)
                for k2 in steps:
                    if w == start and k2 == want_count:
                        path = [v]
                        vv, kk = v, k
                        while (vv, kk) != (start, 0):
                            vv, kk = parent[(vv, kk)]
                            path.append(vv)
                        path.reverse()
                        path.append(start)
                        return path
                    if (w, k2) not in seen and w != start:
                        seen.add((w, k2))
                        parent[(w, k2)] = (v, k)
                        q.append((w, k2))
    return None


def cycle_rels(g: Graph, cycle: List[Any]) -> List[Set[str]]:
    """The rel-sets along a cycle path [v1 v2 … v1]."""
    return [g.edge_rels(a, b) for a, b in zip(cycle, cycle[1:])]


#: Sentinel returned by :func:`find_nonadjacent_cycle` when the bounded
#: simple-cycle search ran out of budget before reaching a verdict: a
#: nonadjacent witness *walk* exists but no simple witness was confirmed
#: or refuted.  Callers must not treat this as "no cycle" — under
#: snapshot isolation that would be a silent false negative.
INDETERMINATE = object()

#: Default expansion budget for the bounded simple-cycle search (DFS
#: node expansions across the whole SCC).  Simple-cycle enumeration is
#: exponential in the worst case; the budget keeps classify() bounded
#: while letting it answer definitively on real-world SCC sizes.  The
#: DFS prunes to vertices that can still reach the cycle's start
#: (Johnson-style), so realistic per-key dependency graphs resolve in
#: far fewer steps than this — the bound is a backstop, not a ceiling
#: histories routinely hit.
NONADJ_BUDGET = 2_000_000


def find_nonadjacent_cycle(
    g: Graph,
    scc: List[Any],
    want: Callable[[Set[str]], bool],
    rest: Callable[[Set[str]], bool],
    budget: Optional[int] = None,
):
    """Find a *simple* cycle containing ≥1 ``want`` edges, no two of
    them adjacent (cyclically — the wrap-around pair counts), every
    other edge satisfying ``rest``.  Used for G-nonadjacent: under
    snapshot isolation every dependency cycle must contain two
    *adjacent* rw anti-dependency edges, so a cycle whose rw edges are
    all isolated is a genuine SI violation (Adya G-SI / Cerone's SI
    characterization).

    Any qualifying cycle can be rotated to start with a want edge, so
    trying every start vertex with a forced want first edge is complete.
    Fast path: BFS over the product graph state
    (vertex, last-edge-was-want); a want edge is only traversable when
    the previous edge was not, and the closing edge back to start must
    be non-want (it precedes the first, want, edge in the rotation).
    The BFS decides *walk* existence exactly, so a no-walk answer is a
    sound "no cycle".  A walk witness can be non-simple, though, and a
    non-simple walk is not a sound nonadjacent witness (its simple
    decomposition may contain only adjacent-rw cycles) — in that case a
    budgeted DFS enumerates simple cycles directly.

    Returns the cycle path ``[v1 v2 … v1]``, ``None`` (definitely no
    qualifying simple cycle), or :data:`INDETERMINATE` when the DFS
    budget ran out first — callers must surface that as an unknown
    verdict, not a pass."""
    members = set(scc)

    def bfs(start: Any) -> Optional[List[Any]]:
        parent: Dict[Tuple[Any, bool], Tuple[Any, bool]] = {}
        q: deque = deque()
        seen: Set[Tuple[Any, bool]] = set()
        # seed: the forced want first edge out of start
        for w in g.successors(start):
            if w not in members or w == start:
                continue
            if want(g.edge_rels(start, w)):
                st = (w, True)
                if st not in seen:
                    seen.add(st)
                    q.append(st)
        while q:
            v, last = q.popleft()
            for w in g.successors(v):
                if w not in members:
                    continue
                rels = g.edge_rels(v, w)
                if w == start:
                    # closing edge must be non-want (wrap adjacency)
                    if rest(rels):
                        back = []
                        cur: Optional[Tuple[Any, bool]] = (v, last)
                        while cur is not None:
                            back.append(cur[0])
                            cur = parent.get(cur)
                        return [start] + back[::-1] + [start]
                    continue
                steps = []
                if want(rels) and not last:
                    steps.append(True)
                if rest(rels):
                    steps.append(False)
                for is_want in steps:
                    st = (w, is_want)
                    if st not in seen:
                        seen.add(st)
                        parent[st] = (v, last)
                        q.append(st)
        return None

    saw_walk = False
    for start in scc:
        cyc = bfs(start)
        if cyc is None:
            continue
        saw_walk = True
        if len(set(cyc[:-1])) == len(cyc) - 1:
            return cyc
    if not saw_walk:
        # BFS is complete over walks, and every simple cycle is a walk:
        # no closing walk from any start ⇒ no qualifying cycle at all.
        return None
    # Some witness walk exists but every first-found one was non-simple.
    # Enumerate simple cycles directly with a budgeted DFS; exhausting
    # the budget yields INDETERMINATE rather than a silent downgrade to
    # the (SI-permitted) G2-item rung.
    if budget is None:
        budget = NONADJ_BUDGET
    found, exhausted = _simple_nonadjacent_dfs(g, members, scc, want, rest, budget)
    if found is not None:
        return found
    return INDETERMINATE if exhausted else None


def _simple_nonadjacent_dfs(
    g: Graph,
    members: Set[Any],
    scc: List[Any],
    want: Callable[[Set[str]], bool],
    rest: Callable[[Set[str]], bool],
    budget: int,
) -> Tuple[Optional[List[Any]], bool]:
    """Bounded DFS enumeration of simple nonadjacent-want cycles.
    Returns ``(cycle_or_None, budget_exhausted)``.  The first edge out
    of each start is forced to be a want edge (rotation completeness);
    interior vertices are never revisited, so every found cycle is
    simple by construction.  Per start, the walk is pruned to vertices
    that can still REACH the start over usable edges (Johnson-style):
    any simple cycle through start lies entirely in that set, so the
    prune is exact while dead-end subgraphs — the DFS's exponential
    waste on real dependency graphs — are never entered."""
    steps = 0

    # usable reverse adjacency within the SCC (edges failing both
    # predicates can never appear in a qualifying cycle)
    rpred: Dict[Any, List[Any]] = {v: [] for v in members}
    for v in members:
        for w in g.successors(v):
            if w in members and w != v:
                rels = g.edge_rels(v, w)
                if rest(rels) or want(rels):
                    rpred[w].append(v)

    def options(v: Any, last_want: bool, start: Any, on_path: Set[Any],
                reach: Set[Any]):
        for w in g.successors(v):
            if w not in members:
                continue
            rels = g.edge_rels(v, w)
            if w == start:
                # closing edge precedes the first (want) edge in the
                # rotation, so it must be non-want
                if rest(rels):
                    yield (w, False)
                continue
            if w in on_path or w not in reach:
                continue
            if rest(rels):
                yield (w, False)
            if not last_want and want(rels):
                yield (w, True)

    for start in scc:
        # skip the reach BFS entirely for starts with no qualifying
        # want out-edge — most vertices of a real dependency graph
        if not any(
            w in members and w != start and want(g.edge_rels(start, w))
            for w in g.successors(start)
        ):
            continue
        # vertices that can reach start over usable edges; its pops
        # count against the same budget as DFS steps so the budget
        # bounds TOTAL work, not just the enumeration phase
        reach: Set[Any] = {start}
        rq: deque = deque([start])
        while rq:
            steps += 1
            if steps > budget:
                return None, True
            x = rq.popleft()
            for p in rpred[x]:
                if p not in reach:
                    reach.add(p)
                    rq.append(p)
        for first in g.successors(start):
            if (
                first not in members
                or first == start
                or first not in reach
                or not want(g.edge_rels(start, first))
            ):
                continue
            path = [start, first]
            on_path = {start, first}
            stack = [options(first, True, start, on_path, reach)]
            while stack:
                steps += 1
                if steps > budget:
                    return None, True
                try:
                    w, is_want = next(stack[-1])
                except StopIteration:
                    stack.pop()
                    on_path.discard(path.pop())
                    continue
                if w == start:
                    return path + [start], False
                path.append(w)
                on_path.add(w)
                stack.append(options(w, is_want, start, on_path, reach))
    return None, False
