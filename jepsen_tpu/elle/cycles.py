"""Cycle classification: SCCs → anomaly-typed witness cycles.

Adya's phenomena as edge-type profiles over the dependency graph:

- G0            cycle of only ww edges
- G1c           cycle of ww/wr edges (not G0)
- G-single      cycle with exactly one rw edge, rest ww/wr
- G-nonadjacent cycle with ≥2 rw edges, no two cyclically adjacent —
                still impossible under snapshot isolation
- G2-item       cycle with ≥1 rw edges (≥2, some adjacent, once the
                previous two are excluded)

With realtime/process graphs unioned in, the same profiles allowing
those edges yield the -realtime / -process variants (e.g. a cycle of ww
+ realtime edges is G0-realtime, proscribed by strict serializability
but not plain serializability).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from .graph import (
    Graph,
    INDETERMINATE,
    WW,
    WR,
    RW,
    PROCESS,
    REALTIME,
    cycle_rels,
    find_cycle,
    find_cycle_with,
    find_nonadjacent_cycle,
    strongly_connected_components,
)

_ORDER = [PROCESS, REALTIME]


def _fmt_cycle(g: Graph, cyc: List[Any]) -> dict:
    steps = []
    for a, b in zip(cyc, cyc[1:]):
        steps.append(
            {"from": repr(a), "rels": sorted(g.edge_rels(a, b)), "to": repr(b)}
        )
    return {"cycle": [repr(v) for v in cyc], "steps": steps}


def _suffix(rels_used: Set[str]) -> str:
    if REALTIME in rels_used:
        return "-realtime"
    if PROCESS in rels_used:
        return "-process"
    return ""


def classify(g: Graph) -> Dict[str, list]:
    """Find one witness cycle per anomaly type per SCC."""
    anomalies: Dict[str, list] = {}

    def record(name: str, cyc: List[Any]) -> None:
        anomalies.setdefault(name, []).append(_fmt_cycle(g, cyc))

    for scc in strongly_connected_components(g):
        # Most-severe-first: G0, then G1c, then G-single, then G2-item.
        ww_only = lambda rels: rels <= {WW}  # noqa: E731
        ww_wr = lambda rels: bool(rels & {WW, WR}) and not (rels & {RW})  # noqa: E731
        has_rw = lambda rels: RW in rels  # noqa: E731

        sub = g.filtered(lambda rels: bool(rels & {WW}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G0", cyc)
            continue

        sub = g.filtered(lambda rels: bool(rels & {WW, WR}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G1c", cyc)
            continue

        cyc = find_cycle_with(
            g,
            scc,
            want=has_rw,
            rest=lambda rels: bool(rels & {WW, WR}),
            want_count=1,
        )
        if cyc is not None:
            record("G-single", cyc)
            continue

        # G-nonadjacent: ≥2 rw edges, none cyclically adjacent — still a
        # snapshot-isolation violation (SI cycles need two adjacent rws)
        cyc = find_nonadjacent_cycle(
            g,
            scc,
            want=has_rw,
            rest=lambda rels: bool(rels & {WW, WR}),
        )
        if cyc is INDETERMINATE:
            # simple-cycle search budget exhausted: a G-nonadjacent may
            # exist in this SCC.  Record the uncertainty (result() turns
            # it into valid?=unknown for models that proscribe the
            # anomaly) and fall through to the definite G2-item witness.
            anomalies.setdefault("G-nonadjacent-indeterminate", []).append(
                {
                    "scc-size": len(scc),
                    "reason": "simple-cycle search budget exhausted",
                }
            )
        elif cyc is not None:
            record("G-nonadjacent", cyc)
            continue

        sub = g.filtered(lambda rels: bool(rels & {WW, WR, RW}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G2-item", cyc)
            continue

        # Cycle requires process/realtime edges: -realtime/-process
        # variants of the same ladder.
        for want_rels, name in (
            ({WW}, "G0"),
            ({WW, WR}, "G1c"),
            (None, "G-single"),
            ("nonadjacent", "G-nonadjacent"),
            ({WW, WR, RW}, "G2-item"),
        ):
            if name == "G-single":
                cyc = find_cycle_with(
                    g,
                    scc,
                    want=has_rw,
                    rest=lambda rels: bool(rels & {WW, WR, PROCESS, REALTIME}),
                    want_count=1,
                )
            elif name == "G-nonadjacent":
                cyc = find_nonadjacent_cycle(
                    g,
                    scc,
                    want=has_rw,
                    rest=lambda rels: bool(rels & {WW, WR, PROCESS, REALTIME}),
                )
                if cyc is INDETERMINATE:
                    # this rung's hypothetical cycle needs process or
                    # realtime edges (the plain rung already answered
                    # definitively or recorded its own marker), so only
                    # the suffixed variants are uncertain — the plain
                    # marker would wrongly degrade serializable/SI
                    # verdicts that are provably clean
                    for suffixed in (
                        "G-nonadjacent-process-indeterminate",
                        "G-nonadjacent-realtime-indeterminate",
                    ):
                        anomalies.setdefault(suffixed, []).append(
                            {
                                "scc-size": len(scc),
                                "reason": (
                                    "simple-cycle search budget exhausted"
                                ),
                            }
                        )
                    cyc = None
            else:
                sub = g.filtered(
                    lambda rels, wr=want_rels: bool(
                        rels & (wr | {PROCESS, REALTIME})
                    )
                )
                cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
            if cyc is not None:
                used: Set[str] = set()
                for rels in cycle_rels(g, cyc):
                    used |= rels
                record(name + _suffix(used), cyc)
                break
    return anomalies


#: winner cache for the device-vs-CPU cycle screen, keyed by
#: (vertex-bucket, batch-size-bucket) — one runtime calibration per key
#: per process (see cyclic_graph_mask).  "cpu" is also the terminal
#: state when the device path errors or ever disagrees with the SCC
#: reference.
_SCREEN_CHOICE: dict = {}

#: never even calibrate the O(n³) closure kernel past this many
#: vertices: on the CPU backend it loses to SCC well before (0.6× at
#: n=256, benchmarks/elle_bench.py) — on the real chip it still wins
#: there (1.6× at n=256, RESULTS.md), which is why the cap sits at 512
#: and not lower — and a first-touch calibration on a huge padded
#: matrix would burn minutes proving the obvious
DEVICE_SCREEN_MAX_VERTICES = 512


def _screen_bucket(n: int) -> int:
    return 1 << max(4, int(n - 1).bit_length())


def _cpu_screen(graphs):
    import numpy as np

    return np.array(
        [bool(strongly_connected_components(g)) for g in graphs]
    )


def _adjacency_mats(graphs):
    return [g.adjacency()[1] for g in graphs]


def _device_screen(graphs, mats=None):
    from ..ops import cycles as ops_cycles

    if mats is None:
        mats = _adjacency_mats(graphs)
    return ops_cycles.has_cycle_batch(mats)


def cyclic_graph_mask(graphs: List[Graph], use_device: Optional[bool] = None):
    """Batched cycle screening: which of these graphs contain a cycle at
    all?  Pads adjacency matrices to a common bucket and runs the
    boolean-closure kernel (jepsen_tpu.ops.cycles) in one dispatch —
    the Elle-on-TPU formulation from SURVEY.md §7 step 8.

    Routing between the device kernel and per-graph CPU SCC is
    SELF-CALIBRATING: the first batch at each (vertex-count,
    batch-size) bucket pair runs BOTH paths (the device one twice, so
    compile time doesn't pollute the measurement), cross-checks their
    answers, and caches the faster engine for that pair on the backend
    actually in use — a band measured on this host's CPU would
    silently misroute on a real chip, where the crossover sits
    elsewhere, and a 1-graph batch's dispatch overhead says nothing
    about a 4096-graph batch's.  A device error or a cross-check
    mismatch pins the pair to CPU permanently (the screen must never
    trade correctness for speed), and graphs past
    DEVICE_SCREEN_MAX_VERTICES skip calibration entirely."""
    import logging
    import time

    import numpy as np

    if not graphs:
        return np.zeros((0,), dtype=bool)
    if use_device is not None:
        return (
            _device_screen(graphs) if use_device else _cpu_screen(graphs)
        )

    biggest = max(len(g.vertices) for g in graphs)
    if biggest > DEVICE_SCREEN_MAX_VERTICES:
        return _cpu_screen(graphs)
    key = (_screen_bucket(biggest), _screen_bucket(len(graphs)))
    choice = _SCREEN_CHOICE.get(key)
    if choice == "device":
        try:
            return _device_screen(graphs)
        except Exception:  # noqa: BLE001 - device died since calibration
            logging.getLogger(__name__).warning(
                "elle cycle-screen device path failed after calibration; "
                "repinning %s to CPU",
                key,
                exc_info=True,
            )
            _SCREEN_CHOICE[key] = "cpu"
            return _cpu_screen(graphs)
    if choice == "cpu":
        return _cpu_screen(graphs)

    # calibrate: both engines answer this batch; the winner takes the
    # bucket pair.  The batch's verdicts come for free (cross-checked).
    t0 = time.perf_counter()
    cpu_out = _cpu_screen(graphs)
    t_cpu = time.perf_counter() - t0
    try:
        _device_screen(graphs, _adjacency_mats(graphs))  # warm/compile
        # the timed run pays full production cost — including adjacency
        # construction, which the cached-choice path pays on every call
        t0 = time.perf_counter()
        dev_out = _device_screen(graphs)
        t_dev = time.perf_counter() - t0
    except Exception:  # noqa: BLE001 - unusable device pins to CPU
        logging.getLogger(__name__).warning(
            "elle cycle-screen device path failed; pinning %s to CPU",
            key,
            exc_info=True,
        )
        _SCREEN_CHOICE[key] = "cpu"
        return cpu_out
    if not np.array_equal(np.asarray(dev_out), cpu_out):
        logging.getLogger(__name__).warning(
            "elle cycle-screen device/CPU verdicts diverged; pinning %s "
            "to CPU",
            key,
        )
        _SCREEN_CHOICE[key] = "cpu"
        return cpu_out
    _SCREEN_CHOICE[key] = "device" if t_dev < t_cpu else "cpu"
    return cpu_out
