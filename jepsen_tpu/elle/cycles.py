"""Cycle classification: SCCs → anomaly-typed witness cycles.

Adya's phenomena as edge-type profiles over the dependency graph:

- G0            cycle of only ww edges
- G1c           cycle of ww/wr edges (not G0)
- G-single      cycle with exactly one rw edge, rest ww/wr
- G-nonadjacent cycle with ≥2 rw edges, no two cyclically adjacent —
                still impossible under snapshot isolation
- G2-item       cycle with ≥1 rw edges (≥2, some adjacent, once the
                previous two are excluded)

With realtime/process graphs unioned in, the same profiles allowing
those edges yield the -realtime / -process variants (e.g. a cycle of ww
+ realtime edges is G0-realtime, proscribed by strict serializability
but not plain serializability).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from .graph import (
    Graph,
    INDETERMINATE,
    WW,
    WR,
    RW,
    PROCESS,
    REALTIME,
    cycle_rels,
    find_cycle,
    find_cycle_with,
    find_nonadjacent_cycle,
    strongly_connected_components,
)

_ORDER = [PROCESS, REALTIME]


def _fmt_cycle(g: Graph, cyc: List[Any]) -> dict:
    steps = []
    for a, b in zip(cyc, cyc[1:]):
        steps.append(
            {"from": repr(a), "rels": sorted(g.edge_rels(a, b)), "to": repr(b)}
        )
    return {"cycle": [repr(v) for v in cyc], "steps": steps}


def _suffix(rels_used: Set[str]) -> str:
    if REALTIME in rels_used:
        return "-realtime"
    if PROCESS in rels_used:
        return "-process"
    return ""


def classify(g: Graph) -> Dict[str, list]:
    """Find one witness cycle per anomaly type per SCC."""
    anomalies: Dict[str, list] = {}

    def record(name: str, cyc: List[Any]) -> None:
        anomalies.setdefault(name, []).append(_fmt_cycle(g, cyc))

    for scc in strongly_connected_components(g):
        # Most-severe-first: G0, then G1c, then G-single, then G2-item.
        ww_only = lambda rels: rels <= {WW}  # noqa: E731
        ww_wr = lambda rels: bool(rels & {WW, WR}) and not (rels & {RW})  # noqa: E731
        has_rw = lambda rels: RW in rels  # noqa: E731

        sub = g.filtered(lambda rels: bool(rels & {WW}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G0", cyc)
            continue

        sub = g.filtered(lambda rels: bool(rels & {WW, WR}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G1c", cyc)
            continue

        cyc = find_cycle_with(
            g,
            scc,
            want=has_rw,
            rest=lambda rels: bool(rels & {WW, WR}),
            want_count=1,
        )
        if cyc is not None:
            record("G-single", cyc)
            continue

        # G-nonadjacent: ≥2 rw edges, none cyclically adjacent — still a
        # snapshot-isolation violation (SI cycles need two adjacent rws)
        cyc = find_nonadjacent_cycle(
            g,
            scc,
            want=has_rw,
            rest=lambda rels: bool(rels & {WW, WR}),
        )
        if cyc is INDETERMINATE:
            # simple-cycle search budget exhausted: a G-nonadjacent may
            # exist in this SCC.  Record the uncertainty (result() turns
            # it into valid?=unknown for models that proscribe the
            # anomaly) and fall through to the definite G2-item witness.
            anomalies.setdefault("G-nonadjacent-indeterminate", []).append(
                {
                    "scc-size": len(scc),
                    "reason": "simple-cycle search budget exhausted",
                }
            )
        elif cyc is not None:
            record("G-nonadjacent", cyc)
            continue

        sub = g.filtered(lambda rels: bool(rels & {WW, WR, RW}))
        cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G2-item", cyc)
            continue

        # Cycle requires process/realtime edges: -realtime/-process
        # variants of the same ladder.
        for want_rels, name in (
            ({WW}, "G0"),
            ({WW, WR}, "G1c"),
            (None, "G-single"),
            ("nonadjacent", "G-nonadjacent"),
            ({WW, WR, RW}, "G2-item"),
        ):
            if name == "G-single":
                cyc = find_cycle_with(
                    g,
                    scc,
                    want=has_rw,
                    rest=lambda rels: bool(rels & {WW, WR, PROCESS, REALTIME}),
                    want_count=1,
                )
            elif name == "G-nonadjacent":
                cyc = find_nonadjacent_cycle(
                    g,
                    scc,
                    want=has_rw,
                    rest=lambda rels: bool(rels & {WW, WR, PROCESS, REALTIME}),
                )
                if cyc is INDETERMINATE:
                    # this rung's hypothetical cycle needs process or
                    # realtime edges (the plain rung already answered
                    # definitively or recorded its own marker), so only
                    # the suffixed variants are uncertain — the plain
                    # marker would wrongly degrade serializable/SI
                    # verdicts that are provably clean
                    for suffixed in (
                        "G-nonadjacent-process-indeterminate",
                        "G-nonadjacent-realtime-indeterminate",
                    ):
                        anomalies.setdefault(suffixed, []).append(
                            {
                                "scc-size": len(scc),
                                "reason": (
                                    "simple-cycle search budget exhausted"
                                ),
                            }
                        )
                    cyc = None
            else:
                sub = g.filtered(
                    lambda rels, wr=want_rels: bool(
                        rels & (wr | {PROCESS, REALTIME})
                    )
                )
                cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
            if cyc is not None:
                used: Set[str] = set()
                for rels in cycle_rels(g, cyc):
                    used |= rels
                record(name + _suffix(used), cyc)
                break
    return anomalies


def cyclic_graph_mask(graphs: List[Graph], use_device: Optional[bool] = None):
    """Batched cycle screening: which of these graphs contain a cycle at
    all?  Pads adjacency matrices to a common bucket and runs the
    boolean-closure kernel (jepsen_tpu.ops.cycles) in one dispatch —
    the Elle-on-TPU formulation from SURVEY.md §7 step 8.  Falls back to
    CPU SCC when no accelerator is available."""
    import numpy as np

    if not graphs:
        return np.zeros((0,), dtype=bool)
    if use_device is None:
        # device wins by ~20x on the small, numerous per-key graphs and
        # loses to CPU SCC past a couple hundred vertices (measured in
        # benchmarks/elle_bench.py: 19.7x at n=16, 3.9x at n=64, 0.6x at
        # n=256) — dispatch only inside the winning band
        biggest = max(len(g.vertices) for g in graphs)
        use_device = 16 <= biggest <= 128
    if not use_device:
        return np.array(
            [bool(strongly_connected_components(g)) for g in graphs]
        )
    from ..ops import cycles as ops_cycles

    mats = [g.adjacency()[1] for g in graphs]
    return ops_cycles.has_cycle_batch(mats)
