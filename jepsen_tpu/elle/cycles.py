"""Cycle classification: SCCs → anomaly-typed witness cycles.

Adya's phenomena as edge-type profiles over the dependency graph:

- G0            cycle of only ww edges
- G1c           cycle of ww/wr edges (not G0)
- G-single      cycle with exactly one rw edge, rest ww/wr
- G-nonadjacent cycle with ≥2 rw edges, no two cyclically adjacent —
                still impossible under snapshot isolation
- G2-item       cycle with ≥1 rw edges (≥2, some adjacent, once the
                previous two are excluded)

With realtime/process graphs unioned in, the same profiles allowing
those edges yield the -realtime / -process variants (e.g. a cycle of ww
+ realtime edges is G0-realtime, proscribed by strict serializability
but not plain serializability).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from .graph import (
    Graph,
    INDETERMINATE,
    WW,
    WR,
    RW,
    PROCESS,
    REALTIME,
    cycle_rels,
    find_cycle,
    find_cycle_with,
    find_nonadjacent_cycle,
    strongly_connected_components,
)

_ORDER = [PROCESS, REALTIME]


def _fmt_cycle(g: Graph, cyc: List[Any]) -> dict:
    steps = []
    for a, b in zip(cyc, cyc[1:]):
        steps.append(
            {"from": repr(a), "rels": sorted(g.edge_rels(a, b)), "to": repr(b)}
        )
    return {"cycle": [repr(v) for v in cyc], "steps": steps}


def _suffix(rels_used: Set[str]) -> str:
    if REALTIME in rels_used:
        return "-realtime"
    if PROCESS in rels_used:
        return "-process"
    return ""


def classify(g: Graph, screen: Optional["GraphScreen"] = None
             ) -> Dict[str, list]:
    """Find one witness cycle per anomaly type per SCC.

    With a ``screen`` (the device's per-relation-filter SCC membership
    masks and nonadjacent-rw walk masks — :func:`screen_for_graphs`),
    every ladder rung the device has proven empty *under that rung's
    relation filter* is skipped outright: a skipped search is one the
    CPU would provably have answered None, so the output is
    byte-identical to the unscreened run (the fuzz corpus pins it) —
    Tarjan and the BFS witness searches only run on graphs, and
    rungs, already proven cyclic."""
    from . import encode as encode_mod

    anomalies: Dict[str, list] = {}

    def record(name: str, cyc: List[Any]) -> None:
        anomalies.setdefault(name, []).append(_fmt_cycle(g, cyc))

    if screen is not None:
        full = screen.members(encode_mod.ALL_MASK)
        if full is not None and not full:
            # no vertex sits on any cycle at all: no nontrivial SCCs,
            # so the whole classify pass (Tarjan included) is free
            return anomalies

    for scc in strongly_connected_components(g):
        def rung_empty(mask: int) -> bool:
            """Device-proven: this SCC has no cycle in the subgraph of
            edges carrying a relation in ``mask``."""
            if screen is None:
                return False
            mem = screen.members(mask)
            return mem is not None and not any(v in mem for v in scc)

        def walk_empty(rest_mask: int) -> bool:
            """Device-proven: no nonadjacent-rw closed walk through
            any vertex of this SCC (⇒ find_nonadjacent_cycle's walk
            BFS would see nothing and answer None)."""
            if screen is None:
                return False
            w = screen.nonadj(encode_mod.RW_BIT, rest_mask)
            return w is not None and not any(v in w for v in scc)

        # Most-severe-first: G0, then G1c, then G-single, then G2-item.
        ww_only = lambda rels: rels <= {WW}  # noqa: E731
        ww_wr = lambda rels: bool(rels & {WW, WR}) and not (rels & {RW})  # noqa: E731
        has_rw = lambda rels: RW in rels  # noqa: E731

        if rung_empty(encode_mod.WW_BIT):
            cyc = None
        else:
            sub = g.filtered(lambda rels: bool(rels & {WW}))
            cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G0", cyc)
            continue

        if rung_empty(encode_mod.WW_BIT | encode_mod.WR_BIT):
            cyc = None
        else:
            sub = g.filtered(lambda rels: bool(rels & {WW, WR}))
            cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G1c", cyc)
            continue

        # every remaining plain rung needs a cycle inside the
        # ww|wr|rw subgraph; one device mask screens all three
        rw_rungs_empty = rung_empty(
            encode_mod.WW_BIT | encode_mod.WR_BIT | encode_mod.RW_BIT
        )

        cyc = None if rw_rungs_empty else find_cycle_with(
            g,
            scc,
            want=has_rw,
            rest=lambda rels: bool(rels & {WW, WR}),
            want_count=1,
        )
        if cyc is not None:
            record("G-single", cyc)
            continue

        # G-nonadjacent: ≥2 rw edges, none cyclically adjacent — still a
        # snapshot-isolation violation (SI cycles need two adjacent rws)
        if rw_rungs_empty or walk_empty(
            encode_mod.WW_BIT | encode_mod.WR_BIT
        ):
            cyc = None
        else:
            cyc = find_nonadjacent_cycle(
                g,
                scc,
                want=has_rw,
                rest=lambda rels: bool(rels & {WW, WR}),
            )
        if cyc is INDETERMINATE:
            # simple-cycle search budget exhausted: a G-nonadjacent may
            # exist in this SCC.  Record the uncertainty (result() turns
            # it into valid?=unknown for models that proscribe the
            # anomaly) and fall through to the definite G2-item witness.
            anomalies.setdefault("G-nonadjacent-indeterminate", []).append(
                {
                    "scc-size": len(scc),
                    "reason": "simple-cycle search budget exhausted",
                }
            )
        elif cyc is not None:
            record("G-nonadjacent", cyc)
            continue

        if rw_rungs_empty:
            cyc = None
        else:
            sub = g.filtered(lambda rels: bool(rels & {WW, WR, RW}))
            cyc = find_cycle(sub, [v for v in scc if v in sub.vertices])
        if cyc is not None:
            record("G2-item", cyc)
            continue

        # Cycle requires process/realtime edges: -realtime/-process
        # variants of the same ladder.
        pr = encode_mod.PR_MASK
        for want_rels, name in (
            ({WW}, "G0"),
            ({WW, WR}, "G1c"),
            (None, "G-single"),
            ("nonadjacent", "G-nonadjacent"),
            ({WW, WR, RW}, "G2-item"),
        ):
            if name == "G-single":
                cyc = None if rung_empty(encode_mod.ALL_MASK) else (
                    find_cycle_with(
                        g,
                        scc,
                        want=has_rw,
                        rest=lambda rels: bool(
                            rels & {WW, WR, PROCESS, REALTIME}
                        ),
                        want_count=1,
                    )
                )
            elif name == "G-nonadjacent":
                cyc = (
                    None
                    if walk_empty(encode_mod.WW_BIT | encode_mod.WR_BIT | pr)
                    else find_nonadjacent_cycle(
                        g,
                        scc,
                        want=has_rw,
                        rest=lambda rels: bool(
                            rels & {WW, WR, PROCESS, REALTIME}
                        ),
                    )
                )
                if cyc is INDETERMINATE:
                    # this rung's hypothetical cycle needs process or
                    # realtime edges (the plain rung already answered
                    # definitively or recorded its own marker), so only
                    # the suffixed variants are uncertain — the plain
                    # marker would wrongly degrade serializable/SI
                    # verdicts that are provably clean
                    for suffixed in (
                        "G-nonadjacent-process-indeterminate",
                        "G-nonadjacent-realtime-indeterminate",
                    ):
                        anomalies.setdefault(suffixed, []).append(
                            {
                                "scc-size": len(scc),
                                "reason": (
                                    "simple-cycle search budget exhausted"
                                ),
                            }
                        )
                    cyc = None
            else:
                mask = encode_mod.rel_mask(want_rels) | pr
                if rung_empty(mask):
                    cyc = None
                else:
                    sub = g.filtered(
                        lambda rels, wr=want_rels: bool(
                            rels & (wr | {PROCESS, REALTIME})
                        )
                    )
                    cyc = find_cycle(
                        sub, [v for v in scc if v in sub.vertices]
                    )
            if cyc is not None:
                used: Set[str] = set()
                for rels in cycle_rels(g, cyc):
                    used |= rels
                record(name + _suffix(used), cyc)
                break
    return anomalies


#: winner cache for the device-vs-CPU cycle screen, keyed by
#: (vertex-bucket, batch-size-bucket) — one runtime calibration per key
#: per process (see cyclic_graph_mask).  "cpu" is also the terminal
#: state when the device path errors or ever disagrees with the SCC
#: reference.
_SCREEN_CHOICE: dict = {}

#: never even calibrate the O(n³) closure kernel past this many
#: vertices: on the CPU backend it loses to SCC well before (0.6× at
#: n=256, benchmarks/elle_bench.py) — on the real chip it still wins
#: there (1.6× at n=256, RESULTS.md), which is why the cap sits at 512
#: and not lower — and a first-touch calibration on a huge padded
#: matrix would burn minutes proving the obvious
DEVICE_SCREEN_MAX_VERTICES = 512


def _screen_bucket(n: int) -> int:
    return 1 << max(4, int(n - 1).bit_length())


def _cpu_screen(graphs):
    import numpy as np

    return np.array(
        [bool(strongly_connected_components(g)) for g in graphs]
    )


def _adjacency_mats(graphs):
    return [g.adjacency()[1] for g in graphs]


def _device_screen(graphs, mats=None):
    from ..ops import cycles as ops_cycles

    if mats is None:
        mats = _adjacency_mats(graphs)
    return ops_cycles.has_cycle_batch(mats)


def cyclic_graph_mask(graphs: List[Graph], use_device: Optional[bool] = None):
    """Batched cycle screening: which of these graphs contain a cycle at
    all?  Pads adjacency matrices to a common bucket and runs the
    boolean-closure kernel (jepsen_tpu.ops.cycles) in one dispatch —
    the Elle-on-TPU formulation from SURVEY.md §7 step 8.

    Routing between the device kernel and per-graph CPU SCC is
    SELF-CALIBRATING: the first batch at each (vertex-count,
    batch-size) bucket pair runs BOTH paths (the device one twice, so
    compile time doesn't pollute the measurement), cross-checks their
    answers, and caches the faster engine for that pair on the backend
    actually in use — a band measured on this host's CPU would
    silently misroute on a real chip, where the crossover sits
    elsewhere, and a 1-graph batch's dispatch overhead says nothing
    about a 4096-graph batch's.  A device error or a cross-check
    mismatch pins the pair to CPU permanently (the screen must never
    trade correctness for speed), and graphs past
    DEVICE_SCREEN_MAX_VERTICES skip calibration entirely."""
    import logging
    import time

    import numpy as np

    if not graphs:
        return np.zeros((0,), dtype=bool)
    if use_device is not None:
        return (
            _device_screen(graphs) if use_device else _cpu_screen(graphs)
        )

    biggest = max(len(g.vertices) for g in graphs)
    if biggest > DEVICE_SCREEN_MAX_VERTICES:
        return _cpu_screen(graphs)
    key = (_screen_bucket(biggest), _screen_bucket(len(graphs)))
    choice = _SCREEN_CHOICE.get(key)
    if choice == "device":
        try:
            return _device_screen(graphs)
        except Exception:  # noqa: BLE001 - device died since calibration
            logging.getLogger(__name__).warning(
                "elle cycle-screen device path failed after calibration; "
                "repinning %s to CPU",
                key,
                exc_info=True,
            )
            _SCREEN_CHOICE[key] = "cpu"
            return _cpu_screen(graphs)
    if choice == "cpu":
        return _cpu_screen(graphs)

    # calibrate: both engines answer this batch; the winner takes the
    # bucket pair.  The batch's verdicts come for free (cross-checked).
    t0 = time.perf_counter()
    cpu_out = _cpu_screen(graphs)
    t_cpu = time.perf_counter() - t0
    try:
        _device_screen(graphs, _adjacency_mats(graphs))  # warm/compile
        # the timed run pays full production cost — including adjacency
        # construction, which the cached-choice path pays on every call
        t0 = time.perf_counter()
        dev_out = _device_screen(graphs)
        t_dev = time.perf_counter() - t0
    except Exception:  # noqa: BLE001 - unusable device pins to CPU
        logging.getLogger(__name__).warning(
            "elle cycle-screen device path failed; pinning %s to CPU",
            key,
            exc_info=True,
        )
        _SCREEN_CHOICE[key] = "cpu"
        return cpu_out
    if not np.array_equal(np.asarray(dev_out), cpu_out):
        logging.getLogger(__name__).warning(
            "elle cycle-screen device/CPU verdicts diverged; pinning %s "
            "to CPU",
            key,
        )
        _SCREEN_CHOICE[key] = "cpu"
        return cpu_out
    _SCREEN_CHOICE[key] = "device" if t_dev < t_cpu else "cpu"
    return cpu_out


# ---------------------------------------------------------------------------
# Device-screened classify: batched SCC/relation-filter screens through
# the production engine (ops.cycles → engine.execution.Executor)
# ---------------------------------------------------------------------------

#: winner cache for the screened-vs-CPU classify router, keyed like
#: _SCREEN_CHOICE by (vertex-bucket, batch-size-bucket); "cpu" is the
#: terminal state after any device error or cross-check mismatch
_CLASSIFY_CHOICE: dict = {}

#: below this many screenable graphs the auto route stays on CPU —
#: dispatch overhead says nothing useful about tiny batches (the same
#: ≥16 gate version_graphs applies to its cycle screen)
ELLE_SCREEN_MIN_BATCH = 16


class GraphScreen:
    """One graph's device screens, decoded back into vertex space:
    ``members(mask)`` — the vertices on some cycle of the subgraph of
    edges carrying a relation in ``mask`` — and ``nonadj(want, rest)``
    — the vertices with a nonadjacent-want closed walk.  Queries
    canonicalize masks to the relation bits the graph actually has, so
    a graph with no process/realtime edges answers its suffixed-ladder
    rungs from the identical plain-relation closure.  Returns a set
    (possibly empty — a *definitive* no) or ``None`` for a filter the
    screen never computed (callers must then search, never skip)."""

    __slots__ = ("order", "present", "_members", "_walks", "_sets",
                 "_wsets")

    def __init__(self, enc, res):
        self.order = enc.order
        self.present = enc.present
        self._members = res.members
        self._walks = res.walks
        self._sets: dict = {}
        self._wsets: dict = {}

    def _vertex_set(self, arr):
        return frozenset(
            v for i, v in enumerate(self.order) if arr[i]
        )

    def members(self, mask: int):
        key = mask & self.present
        if key == 0:
            return frozenset()
        got = self._sets.get(key)
        if got is None:
            arr = self._members.get(key)
            if arr is None:
                return None
            got = self._sets[key] = self._vertex_set(arr)
        return got

    def nonadj(self, want: int, rest: int):
        if not (self.present & want):
            return frozenset()  # no want edge anywhere: trivially none
        key = (want, rest & self.present)
        got = self._wsets.get(key)
        if got is None:
            arr = self._walks.get(key)
            if arr is None:
                return None
            got = self._wsets[key] = self._vertex_set(arr)
        return got


def screen_for_graphs(graphs: List[Graph], executor=None):
    """Encode and screen a batch of dependency graphs through the
    engine: returns ``(screens, route)`` with one
    :class:`GraphScreen` (or ``None`` — CPU fallback for that graph)
    per input.  With the checker service opted in
    (``JEPSEN_TPU_SERVICE``), screens ride ``POST /elle`` and coalesce
    with concurrent runs on the daemon's resident executor; otherwise
    they dispatch through an in-process
    :class:`~jepsen_tpu.engine.execution.Executor` (window, per-chip
    budget, mesh)."""
    from . import encode as encode_mod
    from ..ops import cycles as ops_cycles

    encs = [encode_mod.encode_graph(g) for g in graphs]
    results = None
    route = "device"
    if executor is None:
        try:
            from ..serve import client as serve_client

            if serve_client.service_mode() != "off":
                results = serve_client.screen_graphs(encs)
                if results is not None:
                    route = "service"
        except Exception:  # noqa: BLE001 — any service trouble → local
            results = None
    if results is None:
        results = ops_cycles.screen_graphs(encs, executor=executor)
        route = "device"
    screens = [
        GraphScreen(enc, res) if res is not None else None
        for enc, res in zip(encs, results)
    ]
    return screens, route


def _classify_screened(graphs: List[Graph], executor=None,
                       count: bool = True) -> List[Dict[str, list]]:
    """Classify with device screens, recording the route and the
    witness-search fallback evidence per graph.  ``count=False``
    suppresses the counters — the calibration probes run this path
    without *serving* its results, and served-route accounting must
    reflect what callers actually received."""
    from . import encode as encode_mod
    from .. import obs

    screens, route = screen_for_graphs(graphs, executor=executor)
    out = []
    n_screened = n_fallback = n_cpu = 0
    for g, s in zip(graphs, screens):
        if s is None:
            n_cpu += 1
            out.append(classify(g))
            continue
        n_screened += 1
        full = s.members(encode_mod.ALL_MASK)
        if full:
            # the screen proved a cycle exists: CPU Tarjan + witness
            # search still runs for this graph — the measured
            # "witness-search fallback" fraction of the bench headline
            n_fallback += 1
        out.append(classify(g, s))
    if count and obs.enabled():
        if n_screened:
            obs.count("jepsen_elle_screen_route_total", n_screened,
                      route=route)
        if n_cpu:
            obs.count("jepsen_elle_screen_route_total", n_cpu,
                      route="cpu")
        if n_fallback:
            obs.count("jepsen_elle_witness_fallback_total", n_fallback)
    return out


def _classify_route() -> str:
    import os

    return os.environ.get("JEPSEN_TPU_ELLE_SCREEN", "auto").strip().lower()


def classify_graphs(
    graphs: List[Graph],
    route: Optional[str] = None,
    executor=None,
) -> List[Dict[str, list]]:
    """Batched :func:`classify`: screen every graph's relation-filter
    cycle structure on the device in shared engine dispatches, then
    pay CPU Tarjan + witness search only where the screens proved
    cycles exist.  ``route``: ``"cpu"`` (pure host path — the
    byte-identity reference), ``"device"`` (screens forced — smoke,
    fuzz, bench), or ``None``/``"auto"`` (default; also
    ``JEPSEN_TPU_ELLE_SCREEN``): SELF-CALIBRATING per (vertex-bucket,
    batch-bucket) pair exactly like :func:`cyclic_graph_mask` — the
    first batch at each pair runs both paths, cross-checks anomalies
    for equality, and pins the faster engine; a device error or
    mismatch pins the pair to CPU permanently (the screens must never
    trade correctness for speed).  Graphs past
    :data:`DEVICE_SCREEN_MAX_VERTICES` (or below 2 vertices) always
    classify on the CPU."""
    import logging
    import time

    from .. import obs

    route = (route or _classify_route()).lower()
    n = len(graphs)
    if n == 0:
        return []
    if route == "cpu":
        if obs.enabled():
            obs.count("jepsen_elle_screen_route_total", n, route="cpu")
        return [classify(g) for g in graphs]

    screenable = [
        i for i, g in enumerate(graphs)
        if 2 <= len(g.vertices) <= DEVICE_SCREEN_MAX_VERTICES
    ]
    out: List[Optional[Dict[str, list]]] = [None] * n
    rest = [i for i in set(range(n)) - set(screenable)]
    for i in sorted(rest):
        out[i] = classify(graphs[i])
    if rest and obs.enabled():
        obs.count("jepsen_elle_screen_route_total", len(rest), route="cpu")
    sub = [graphs[i] for i in screenable]

    if route in ("device", "service"):
        screened = _classify_screened(sub, executor=executor)
        for i, r in zip(screenable, screened):
            out[i] = r
        return out  # type: ignore[return-value]

    # auto: self-calibrating, with the small-batch gate
    if len(sub) < ELLE_SCREEN_MIN_BATCH:
        for i in screenable:
            out[i] = classify(graphs[i])
        if sub and obs.enabled():
            obs.count("jepsen_elle_screen_route_total", len(sub),
                      route="cpu")
        return out  # type: ignore[return-value]
    biggest = max(len(g.vertices) for g in sub)
    key = (_screen_bucket(biggest), _screen_bucket(len(sub)))
    choice = _CLASSIFY_CHOICE.get(key)
    if choice == "device":
        try:
            screened = _classify_screened(sub, executor=executor)
        except Exception:  # noqa: BLE001 — device died since calibration
            logging.getLogger(__name__).warning(
                "elle classify screens failed after calibration; "
                "repinning %s to CPU", key, exc_info=True,
            )
            _CLASSIFY_CHOICE[key] = "cpu"
            screened = [classify(g) for g in sub]
        for i, r in zip(screenable, screened):
            out[i] = r
        return out  # type: ignore[return-value]
    if choice == "cpu":
        for i in screenable:
            out[i] = classify(graphs[i])
        if obs.enabled():
            obs.count("jepsen_elle_screen_route_total", len(sub),
                      route="cpu")
        return out  # type: ignore[return-value]

    # calibrate: both engines classify this batch; the winner takes
    # the bucket pair, and a cross-check mismatch (or device error)
    # pins it to CPU — correctness is never traded for speed
    t0 = time.perf_counter()
    cpu_out = [classify(g) for g in sub]
    t_cpu = time.perf_counter() - t0
    try:
        # count=False: these are probes — the CPU results below are
        # what the caller is served, so the route counter must say cpu
        _classify_screened(sub, executor=executor,
                           count=False)  # warm/compile
        t0 = time.perf_counter()
        dev_out = _classify_screened(sub, executor=executor, count=False)
        t_dev = time.perf_counter() - t0
    except Exception:  # noqa: BLE001 — unusable device pins to CPU
        logging.getLogger(__name__).warning(
            "elle classify screens failed; pinning %s to CPU", key,
            exc_info=True,
        )
        _CLASSIFY_CHOICE[key] = "cpu"
        dev_out = None
    if dev_out is not None:
        if dev_out != cpu_out:
            logging.getLogger(__name__).warning(
                "elle screened/CPU classify diverged; pinning %s to CPU",
                key,
            )
            obs.count("jepsen_elle_screen_mismatch_total")
            _CLASSIFY_CHOICE[key] = "cpu"
        else:
            _CLASSIFY_CHOICE[key] = "device" if t_dev < t_cpu else "cpu"
    if obs.enabled():
        # the calibration batch is SERVED the CPU answers
        obs.count("jepsen_elle_screen_route_total", len(sub), route="cpu")
    for i, r in zip(screenable, cpu_out):
        out[i] = r
    return out  # type: ignore[return-value]
