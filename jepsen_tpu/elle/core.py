"""Transaction extraction plus process/realtime dependency graphs.

The realtime construction uses the interval-order frontier reduction:
edges are added from every frontier member at each invocation, and a
completion evicts frontier members it fully supersedes — the transitive
closure equals the true precedes-in-realtime relation without O(n²)
edges.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..history import History, Op, INVOKE, OK, FAIL, INFO
from .graph import Graph, PROCESS, REALTIME


class Txn:
    """One committed (or attempted) transaction: the invoke/completion
    pair, value = list of micro-ops."""

    __slots__ = ("invoke", "complete", "index")

    def __init__(self, invoke: Op, complete: Optional[Op], index: int):
        self.invoke = invoke
        self.complete = complete
        self.index = index  # position among txns; stable vertex id

    @property
    def ok(self) -> bool:
        return self.complete is not None and self.complete.type == OK

    @property
    def failed(self) -> bool:
        return self.complete is not None and self.complete.type == FAIL

    @property
    def value(self) -> list:
        """The committed mops when ok (completion value), else the
        attempted mops."""
        if self.ok and self.complete.value is not None:
            return self.complete.value
        return self.invoke.value or []

    @property
    def process(self) -> Any:
        return self.invoke.process

    def __repr__(self) -> str:
        t = self.complete.type if self.complete else "?"
        return f"T{self.index}({t} {self.value!r})"

    def __hash__(self) -> int:
        return self.index

    def __eq__(self, other) -> bool:
        return isinstance(other, Txn) and other.index == self.index


def transactions(history: History) -> List[Txn]:
    """Pair invocations with completions, in invocation order."""
    txns: List[Txn] = []
    open_by_process: Dict[Any, Txn] = {}
    for op in history:
        if not isinstance(op.process, int):
            continue
        if op.type == INVOKE:
            t = Txn(op, None, len(txns))
            txns.append(t)
            open_by_process[op.process] = t
        else:
            t = open_by_process.pop(op.process, None)
            if t is not None:
                t.complete = op
    return txns


def process_graph(txns: List[Txn]) -> Graph:
    """Successive ok txns of one process, in order."""
    g = Graph()
    last: Dict[Any, Txn] = {}
    for t in txns:
        if not t.ok:
            continue
        g.add_vertex(t)
        prev = last.get(t.process)
        if prev is not None:
            g.add_edge(prev, t, PROCESS)
        last[t.process] = t
    return g


def realtime_graph(txns: List[Txn]) -> Graph:
    """T1 → T2 when T1's completion precedes T2's invocation, reduced to
    a frontier relation whose transitive closure is the full interval
    order."""
    g = Graph()
    events: List[Tuple[int, int, str, Txn]] = []
    for t in txns:
        if not t.ok:
            continue
        g.add_vertex(t)
        events.append((t.invoke.time, t.index, "invoke", t))
        events.append((t.complete.time, t.index, "complete", t))
    events.sort(key=lambda e: (e[0], e[1]))
    frontier: List[Txn] = []
    for _, _, kind, t in events:
        if kind == "invoke":
            for f in frontier:
                g.add_edge(f, t, REALTIME)
        else:
            frontier = [f for f in frontier if f.complete.time >= t.invoke.time]
            frontier.append(t)
    return g
