"""Consistency models: pure state machines checked against histories.

Equivalent of the external knossos.model namespace the reference consumes
at jepsen/src/jepsen/checker.clj:19-26 (models: register, cas-register,
mutex, fifo-queue, unordered-queue) and jepsen/src/jepsen/tests/causal.clj's
local Model protocol (causal.clj:12-33).

A model is an immutable value with ``step(op) -> Model``; an invalid
transition returns an :class:`Inconsistent` model.  Models must be hashable
and comparable so searches can deduplicate configurations.

Every model here has a matching branchless TPU step kernel in
``jepsen_tpu.ops.step_kernels``; this module is the oracle the kernels are
differentially tested against.  The owner-aware/reentrant/fenced lock
and permit models (hazelcast's CP-subsystem probes) live in
:mod:`.locks`; they carry client identities in op values and are
re-exported here.  Owner/reentrant mutexes and the permit semaphore
ride dense device automata (encode-time reductions / table-built
transitions); the fenced flavors stay oracle-checked (unbounded
fencing tokens admit no small state enumeration).
"""

from __future__ import annotations

from collections.abc import Hashable
from typing import Any, Tuple


class Model:
    """Base class. Subclasses implement step(op) returning a new model.

    **Partition protocol (P-compositionality).**  Models whose
    linearizability provably factors into independent per-partition
    sub-histories — "Faster linearizability checking via
    P-compositionality", arXiv:1504.00204 — additionally override:

    - ``partition_key(op)``: the partition one op touches (a hashable
      key), or ``None`` when the op spans partitions / carries no key —
      the whole history then passes through undecomposed.  The base
      class pins the name to ``None`` (not a method), the "no declared
      partition" marker every decomposition pass checks.
    - ``subhistory_model(key)``: the independent sub-model one
      partition's sub-history is checked against (seeded from this
      model's state for that partition).
    - ``partition_op(op, key)``: the op as the sub-model consumes it
      (default: unchanged — every current partitioner keeps the
      parent vocabulary; the hook exists for sub-models that speak a
      different one).

    Soundness contract: the model must be (isomorphic to) a product of
    the per-key sub-models with every partitionable op acting on
    exactly one factor — then a history is linearizable iff every
    per-partition sub-history is, and the decomposition passes
    (``engine/decompose.py`` ahead of device dispatch,
    ``checker.linear._partition_by_key`` inside the CPU oracle) may
    AND the sub-verdicts.  See doc/checker-engines.md "Decomposition
    front-end".
    """

    #: None = no declared partition (see the class docstring); models
    #: implementing the protocol override this with a method
    partition_key = None

    def step(self, op) -> "Model":  # pragma: no cover - interface
        raise NotImplementedError

    def subhistory_model(self, key) -> "Model":  # pragma: no cover - interface
        raise NotImplementedError(
            f"{type(self).__name__} declares no partition protocol"
        )

    def partition_op(self, op, key):
        """The op as the partition's sub-model consumes it (default:
        unchanged — sound whenever the sub-model shares this model's op
        vocabulary, e.g. per-lock Mutex or per-value UnorderedQueue)."""
        return op

    @property
    def is_inconsistent(self) -> bool:
        return False


class Inconsistent(Model):
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def step(self, op) -> "Model":
        return self

    @property
    def is_inconsistent(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Inconsistent)

    def __hash__(self):
        return hash("inconsistent")

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


class Register(Model):
    """A read/write register.  fs: "write" (value v), "read" (observed v;
    a read of None — unknown value — always passes)."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op) -> Model:
        if op.f == "write":
            return Register(op.value)
        elif op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, Register) and other.value == self.value

    def __hash__(self):
        return hash(("register", self.value))

    def __repr__(self):
        return f"Register({self.value!r})"


class CASRegister(Model):
    """A register with read / write / compare-and-set.

    fs: "read" (observed v), "write" (v), "cas" ((old, new)).
    """

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op) -> Model:
        f = op.f
        if f == "write":
            return CASRegister(op.value)
        elif f == "cas":
            if op.value is None:
                return inconsistent("cas with nil value")
            old, new = op.value
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"cas expected {old!r}, had {self.value!r}")
        elif f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, other):
        return isinstance(other, CASRegister) and other.value == self.value

    def __hash__(self):
        return hash(("cas-register", self.value))

    def __repr__(self):
        return f"CASRegister({self.value!r})"


class Mutex(Model):
    """A lock. fs: "acquire", "release"."""

    __slots__ = ("locked",)

    def __init__(self, locked: bool = False):
        self.locked = locked

    def step(self, op) -> Model:
        if op.f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        elif op.f == "release":
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, Mutex) and other.locked == self.locked

    def __hash__(self):
        return hash(("mutex", self.locked))

    def __repr__(self):
        return f"Mutex({'locked' if self.locked else 'free'})"


class MultiRegister(Model):
    """A map of independent registers; op value is [(f, k, v), ...] mops."""

    __slots__ = ("values",)

    def __init__(self, values: Any = None):
        self.values = frozenset((values or {}).items()) if isinstance(values, dict) else (values or frozenset())

    def _as_dict(self):
        return dict(self.values)

    def step(self, op) -> Model:
        vals = self._as_dict()
        for f, k, v in op.value or []:
            if f in ("w", "write"):
                vals[k] = v
            elif f in ("r", "read"):
                if v is not None and vals.get(k) != v:
                    return inconsistent(f"read {v!r} of {k!r}, expected {vals.get(k)!r}")
            else:
                return inconsistent(f"unknown mop f={f!r}")
        return MultiRegister(vals)

    # -- partition protocol: one single-key register per key ----------------
    # A txn whose mops all touch ONE key acts on exactly one factor of
    # the product state, so such histories decompose per key into
    # single-key MultiRegister sub-histories — the register-family
    # sub-model in this codebase's vocabulary (its dense automaton at
    # K=1 IS the register automaton), and an atomic multi-mop
    # same-key txn stays expressible (a plain Register op could not
    # say read-then-write).  Cross-key txns return None and keep the
    # history undecomposed.

    def partition_key(self, op):
        v = op.value
        if not isinstance(v, (list, tuple)) or not v:
            return None
        keys = set()
        for mop in v:
            if not (
                isinstance(mop, (list, tuple))
                and len(mop) == 3
                and mop[0] in ("r", "read", "w", "write")
                and isinstance(mop[1], Hashable)
            ):
                return None
            keys.add(mop[1])
        if len(keys) != 1:
            return None
        k = keys.pop()
        return None if k is None else k

    def subhistory_model(self, key) -> "MultiRegister":
        return MultiRegister({key: self._as_dict().get(key)})

    def __eq__(self, other):
        return isinstance(other, MultiRegister) and other.values == self.values

    def __hash__(self):
        return hash(("multi-register", self.values))

    def __repr__(self):
        return f"MultiRegister({dict(self.values)!r})"


class FIFOQueue(Model):
    """A FIFO queue. fs: "enqueue" (v), "dequeue" (observed v)."""

    __slots__ = ("items",)

    def __init__(self, items: Tuple = ()):
        self.items = tuple(items)

    def step(self, op) -> Model:
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        elif op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if op.value is not None and op.value != head:
                return inconsistent(f"dequeued {op.value!r}, expected {head!r}")
            return FIFOQueue(rest)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, FIFOQueue) and other.items == self.items

    def __hash__(self):
        return hash(("fifo-queue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


class UnorderedQueue(Model):
    """A bag: enqueue/dequeue with no ordering constraint."""

    __slots__ = ("items",)

    def __init__(self, items=frozenset()):
        # multiset as frozenset of (value, count)
        if isinstance(items, frozenset):
            self.items = items
        else:
            counts: dict = {}
            for x in items:
                counts[x] = counts.get(x, 0) + 1
            self.items = frozenset(counts.items())

    def _counts(self):
        return dict(self.items)

    def step(self, op) -> Model:
        counts = self._counts()
        if op.f == "enqueue":
            counts[op.value] = counts.get(op.value, 0) + 1
            return UnorderedQueue(frozenset(counts.items()))
        elif op.f == "dequeue":
            v = op.value
            if v is None:
                return inconsistent("dequeue with unknown value")
            if counts.get(v, 0) <= 0:
                return inconsistent(f"dequeued {v!r} not in queue")
            counts[v] -= 1
            if counts[v] == 0:
                del counts[v]
            return UnorderedQueue(frozenset(counts.items()))
        return inconsistent(f"unknown op f={op.f!r}")

    # -- partition protocol: one queue per enqueued value -------------------
    # The bag is a product of per-value counters (enqueue/dequeue of v
    # touch only v's count — the same factoring the direct checker's
    # per-value matching exploits), so histories decompose per value.
    # A dequeue whose value never resolved (None) keeps the history
    # undecomposed: the full model owns the inconsistency verdict.

    def partition_key(self, op):
        if (
            op.f in ("enqueue", "dequeue")
            and op.value is not None
            and isinstance(op.value, Hashable)
        ):
            return op.value
        return None

    def subhistory_model(self, key) -> "UnorderedQueue":
        n = dict(self.items).get(key, 0)
        return UnorderedQueue(frozenset({(key, n)}) if n else frozenset())

    def __eq__(self, other):
        return isinstance(other, UnorderedQueue) and other.items == self.items

    def __hash__(self):
        return hash(("unordered-queue", self.items))

    def __repr__(self):
        return f"UnorderedQueue({dict(self.items)!r})"


class MultiMutex(Model):
    """A map of named locks: fs "acquire"/"release" with ``op.value`` =
    the lock name.  Semantically the product of one :class:`Mutex` per
    name — which is exactly its point: the model has no device kernel
    of its own (the undecomposed path is the generic oracle search),
    but the partition protocol splits its histories per lock name into
    plain Mutex sub-histories, which the direct mutex checker decides
    in O(n log n) — the P-compositionality win in its purest form."""

    __slots__ = ("held",)

    def __init__(self, held=frozenset()):
        self.held = frozenset(held)

    def step(self, op) -> Model:
        name = op.value
        if name is None:
            return inconsistent("lock op with nil lock name")
        if op.f == "acquire":
            if name in self.held:
                return inconsistent(f"cannot acquire held lock {name!r}")
            return MultiMutex(self.held | {name})
        elif op.f == "release":
            if name not in self.held:
                return inconsistent(f"cannot release free lock {name!r}")
            return MultiMutex(self.held - {name})
        return inconsistent(f"unknown op f={op.f!r}")

    # -- partition protocol: one Mutex per lock name ------------------------
    # Mutex.step ignores op.value, so the identity partition_op is
    # sound; the sub-model seeds from this model's held-set.

    def partition_key(self, op):
        if (
            op.f in ("acquire", "release")
            and op.value is not None
            and isinstance(op.value, Hashable)
        ):
            return op.value
        return None

    def subhistory_model(self, key) -> "Mutex":
        return Mutex(key in self.held)

    def __eq__(self, other):
        return isinstance(other, MultiMutex) and other.held == self.held

    def __hash__(self):
        return hash(("multi-mutex", self.held))

    def __repr__(self):
        return f"MultiMutex({sorted(self.held, key=repr)!r})"


class NoOp(Model):
    """A model that accepts everything."""

    def step(self, op) -> Model:
        return self

    def __eq__(self, other):
        return isinstance(other, NoOp)

    def __hash__(self):
        return hash("noop-model")

    def __repr__(self):
        return "NoOp()"


def register(value: Any = None) -> Register:
    return Register(value)


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex()


def multi_register(values: Any = None) -> MultiRegister:
    return MultiRegister(values)


def multi_mutex(held=()) -> MultiMutex:
    return MultiMutex(frozenset(held))


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


# owner-aware / reentrant / fenced locks and permits (hazelcast CP
# probes) — re-exported so `models.owner_mutex()` etc. work; imported
# at the bottom because locks.py imports Model/inconsistent from here
from .locks import (  # noqa: E402
    AcquiredPermits,
    FencedMutex,
    OwnerMutex,
    ReentrantFencedMutex,
    ReentrantMutex,
    acquired_permits,
    fenced_mutex,
    owner_mutex,
    reentrant_fenced_mutex,
    reentrant_mutex,
)
