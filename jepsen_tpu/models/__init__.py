"""Consistency models: pure state machines checked against histories.

Equivalent of the external knossos.model namespace the reference consumes
at jepsen/src/jepsen/checker.clj:19-26 (models: register, cas-register,
mutex, fifo-queue, unordered-queue) and jepsen/src/jepsen/tests/causal.clj's
local Model protocol (causal.clj:12-33).

A model is an immutable value with ``step(op) -> Model``; an invalid
transition returns an :class:`Inconsistent` model.  Models must be hashable
and comparable so searches can deduplicate configurations.

Every model here has a matching branchless TPU step kernel in
``jepsen_tpu.ops.step_kernels``; this module is the oracle the kernels are
differentially tested against.  The owner-aware/reentrant/fenced lock
and permit models (hazelcast's CP-subsystem probes) live in
:mod:`.locks`; they carry client identities in op values and are
re-exported here.  Owner/reentrant mutexes and the permit semaphore
ride dense device automata (encode-time reductions / table-built
transitions); the fenced flavors stay oracle-checked (unbounded
fencing tokens admit no small state enumeration).
"""

from __future__ import annotations

from typing import Any, Tuple


class Model:
    """Base class. Subclasses implement step(op) returning a new model."""

    def step(self, op) -> "Model":  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def is_inconsistent(self) -> bool:
        return False


class Inconsistent(Model):
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def step(self, op) -> "Model":
        return self

    @property
    def is_inconsistent(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Inconsistent)

    def __hash__(self):
        return hash("inconsistent")

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


class Register(Model):
    """A read/write register.  fs: "write" (value v), "read" (observed v;
    a read of None — unknown value — always passes)."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op) -> Model:
        if op.f == "write":
            return Register(op.value)
        elif op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, Register) and other.value == self.value

    def __hash__(self):
        return hash(("register", self.value))

    def __repr__(self):
        return f"Register({self.value!r})"


class CASRegister(Model):
    """A register with read / write / compare-and-set.

    fs: "read" (observed v), "write" (v), "cas" ((old, new)).
    """

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op) -> Model:
        f = op.f
        if f == "write":
            return CASRegister(op.value)
        elif f == "cas":
            if op.value is None:
                return inconsistent("cas with nil value")
            old, new = op.value
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"cas expected {old!r}, had {self.value!r}")
        elif f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"read {op.value!r}, expected {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, other):
        return isinstance(other, CASRegister) and other.value == self.value

    def __hash__(self):
        return hash(("cas-register", self.value))

    def __repr__(self):
        return f"CASRegister({self.value!r})"


class Mutex(Model):
    """A lock. fs: "acquire", "release"."""

    __slots__ = ("locked",)

    def __init__(self, locked: bool = False):
        self.locked = locked

    def step(self, op) -> Model:
        if op.f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        elif op.f == "release":
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, Mutex) and other.locked == self.locked

    def __hash__(self):
        return hash(("mutex", self.locked))

    def __repr__(self):
        return f"Mutex({'locked' if self.locked else 'free'})"


class MultiRegister(Model):
    """A map of independent registers; op value is [(f, k, v), ...] mops."""

    __slots__ = ("values",)

    def __init__(self, values: Any = None):
        self.values = frozenset((values or {}).items()) if isinstance(values, dict) else (values or frozenset())

    def _as_dict(self):
        return dict(self.values)

    def step(self, op) -> Model:
        vals = self._as_dict()
        for f, k, v in op.value or []:
            if f in ("w", "write"):
                vals[k] = v
            elif f in ("r", "read"):
                if v is not None and vals.get(k) != v:
                    return inconsistent(f"read {v!r} of {k!r}, expected {vals.get(k)!r}")
            else:
                return inconsistent(f"unknown mop f={f!r}")
        return MultiRegister(vals)

    def __eq__(self, other):
        return isinstance(other, MultiRegister) and other.values == self.values

    def __hash__(self):
        return hash(("multi-register", self.values))

    def __repr__(self):
        return f"MultiRegister({dict(self.values)!r})"


class FIFOQueue(Model):
    """A FIFO queue. fs: "enqueue" (v), "dequeue" (observed v)."""

    __slots__ = ("items",)

    def __init__(self, items: Tuple = ()):
        self.items = tuple(items)

    def step(self, op) -> Model:
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        elif op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if op.value is not None and op.value != head:
                return inconsistent(f"dequeued {op.value!r}, expected {head!r}")
            return FIFOQueue(rest)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, FIFOQueue) and other.items == self.items

    def __hash__(self):
        return hash(("fifo-queue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


class UnorderedQueue(Model):
    """A bag: enqueue/dequeue with no ordering constraint."""

    __slots__ = ("items",)

    def __init__(self, items=frozenset()):
        # multiset as frozenset of (value, count)
        if isinstance(items, frozenset):
            self.items = items
        else:
            counts: dict = {}
            for x in items:
                counts[x] = counts.get(x, 0) + 1
            self.items = frozenset(counts.items())

    def _counts(self):
        return dict(self.items)

    def step(self, op) -> Model:
        counts = self._counts()
        if op.f == "enqueue":
            counts[op.value] = counts.get(op.value, 0) + 1
            return UnorderedQueue(frozenset(counts.items()))
        elif op.f == "dequeue":
            v = op.value
            if v is None:
                return inconsistent("dequeue with unknown value")
            if counts.get(v, 0) <= 0:
                return inconsistent(f"dequeued {v!r} not in queue")
            counts[v] -= 1
            if counts[v] == 0:
                del counts[v]
            return UnorderedQueue(frozenset(counts.items()))
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, UnorderedQueue) and other.items == self.items

    def __hash__(self):
        return hash(("unordered-queue", self.items))

    def __repr__(self):
        return f"UnorderedQueue({dict(self.items)!r})"


class NoOp(Model):
    """A model that accepts everything."""

    def step(self, op) -> Model:
        return self

    def __eq__(self, other):
        return isinstance(other, NoOp)

    def __hash__(self):
        return hash("noop-model")

    def __repr__(self):
        return "NoOp()"


def register(value: Any = None) -> Register:
    return Register(value)


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex()


def multi_register(values: Any = None) -> MultiRegister:
    return MultiRegister(values)


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


# owner-aware / reentrant / fenced locks and permits (hazelcast CP
# probes) — re-exported so `models.owner_mutex()` etc. work; imported
# at the bottom because locks.py imports Model/inconsistent from here
from .locks import (  # noqa: E402
    AcquiredPermits,
    FencedMutex,
    OwnerMutex,
    ReentrantFencedMutex,
    ReentrantMutex,
    acquired_permits,
    fenced_mutex,
    owner_mutex,
    reentrant_fenced_mutex,
    reentrant_mutex,
)
