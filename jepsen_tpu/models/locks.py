"""Owner-aware, reentrant, and fenced lock models, plus a permit
(semaphore) model.

These mirror the hazelcast suite's CP-subsystem probes (reference:
hazelcast/src/jepsen/hazelcast.clj:515-650): unlike the plain
:class:`..Mutex`, each step knows WHICH client acted — an op's value
carries the client name (or a ``{"client": ..., "fence": ...}`` map for
the fenced flavors) — so the models catch a lock granted to two owners,
a release by a non-owner, more re-acquires than the configured bound,
fencing tokens that go backwards, and over-issued semaphore permits.

Fences use the reference's convention: 0 is the "invalid" (absent)
fence (hazelcast.clj:55); a real fence must strictly exceed every fence
observed so far.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from . import Model, inconsistent

#: a lock may be re-acquired at most this many times by its owner
#: (reference: hazelcast.clj:53 reentrant-lock-acquire-count)
REENTRANT_ACQUIRE_COUNT = 2

#: the "no fence" sentinel (reference: hazelcast.clj:55 invalid-fence)
INVALID_FENCE = 0


def _client(op) -> Optional[str]:
    v = op.value
    if isinstance(v, dict):
        return v.get("client")
    return v


def _fence(op) -> int:
    v = op.value
    if isinstance(v, dict):
        return int(v.get("fence", INVALID_FENCE))
    return INVALID_FENCE


class OwnerMutex(Model):
    """Non-reentrant mutex that tracks WHO holds it: acquire needs a
    free lock; release must come from the holder.  (reference:
    hazelcast.clj:538-557 OwnerAwareMutex)"""

    __slots__ = ("owner",)

    def __init__(self, owner: Optional[str] = None):
        self.owner = owner

    def step(self, op) -> Model:
        client = _client(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.owner is None:
                return OwnerMutex(client)
            return inconsistent(
                f"client {client} cannot acquire: held by {self.owner}"
            )
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(
                    f"client {client} cannot release: held by {self.owner}"
                )
            return OwnerMutex(None)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return isinstance(other, OwnerMutex) and other.owner == self.owner

    def __hash__(self):
        return hash(("owner-mutex", self.owner))

    def __repr__(self):
        return f"OwnerMutex(owner={self.owner!r})"


class ReentrantMutex(Model):
    """Mutex the holder may re-acquire, up to ``max_count`` holds; every
    release peels one hold.  (reference: hazelcast.clj:515-535
    ReentrantMutex)"""

    __slots__ = ("owner", "count", "max_count")

    def __init__(
        self,
        owner: Optional[str] = None,
        count: int = 0,
        max_count: int = REENTRANT_ACQUIRE_COUNT,
    ):
        self.owner = owner
        self.count = count
        self.max_count = max_count

    def step(self, op) -> Model:
        client = _client(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.count < self.max_count and (
                self.owner is None or self.owner == client
            ):
                return ReentrantMutex(client, self.count + 1, self.max_count)
            return inconsistent(
                f"client {client} cannot acquire: owner={self.owner} "
                f"count={self.count}"
            )
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(
                    f"client {client} cannot release: owner={self.owner}"
                )
            return ReentrantMutex(
                None if self.count == 1 else self.owner,
                self.count - 1,
                self.max_count,
            )
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return (
            isinstance(other, ReentrantMutex)
            and other.owner == self.owner
            and other.count == self.count
            and other.max_count == self.max_count
        )

    def __hash__(self):
        return hash(("reentrant-mutex", self.owner, self.count))

    def __repr__(self):
        return f"ReentrantMutex(owner={self.owner!r}, count={self.count})"


class FencedMutex(Model):
    """Non-reentrant mutex whose acquires may carry a fencing token; a
    real token must strictly exceed the largest fence ever observed
    (a stale or reused token is the anomaly this model exists to
    catch).  (reference: hazelcast.clj:565-587 FencedMutex)"""

    __slots__ = ("owner", "fence")

    def __init__(
        self, owner: Optional[str] = None, fence: int = INVALID_FENCE
    ):
        self.owner = owner
        self.fence = fence

    def step(self, op) -> Model:
        client = _client(op)
        if client is None:
            return inconsistent("no owner!")
        fence = _fence(op)
        if op.f == "acquire":
            if self.owner is not None:
                return inconsistent(
                    f"client {client} cannot acquire: held by {self.owner}"
                )
            if fence == INVALID_FENCE:
                return FencedMutex(client, self.fence)
            if fence > self.fence:
                return FencedMutex(client, fence)
            return inconsistent(
                f"client {client} acquired with non-monotonic fence "
                f"{fence} (highest observed {self.fence})"
            )
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(
                    f"client {client} cannot release: held by {self.owner}"
                )
            return FencedMutex(None, self.fence)
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return (
            isinstance(other, FencedMutex)
            and other.owner == self.owner
            and other.fence == self.fence
        )

    def __hash__(self):
        return hash(("fenced-mutex", self.owner, self.fence))

    def __repr__(self):
        return f"FencedMutex(owner={self.owner!r}, fence={self.fence})"


class ReentrantFencedMutex(Model):
    """Reentrant mutex with fencing tokens: a fresh hold must present a
    new (strictly larger) fence or none; re-acquires by the holder must
    reuse the hold's fence or none.  (reference: hazelcast.clj:590-627
    ReentrantFencedMutex)"""

    __slots__ = ("owner", "count", "fence", "highest", "max_count")

    def __init__(
        self,
        owner: Optional[str] = None,
        count: int = 0,
        fence: int = INVALID_FENCE,
        highest: int = INVALID_FENCE,
        max_count: int = REENTRANT_ACQUIRE_COUNT,
    ):
        self.owner = owner
        self.count = count
        self.fence = fence  # the current hold's fence
        self.highest = highest  # largest fence ever observed
        self.max_count = max_count

    def step(self, op) -> Model:
        client = _client(op)
        if client is None:
            return inconsistent("no owner!")
        fence = _fence(op)
        bad = inconsistent(
            f"client {client} cannot {op.f} (fence {fence}) on {self!r}"
        )
        if op.f == "acquire":
            if self.owner is None:
                # fresh hold: fenceless, or a fence past everything seen
                if fence == INVALID_FENCE or fence > self.highest:
                    return ReentrantFencedMutex(
                        client, 1, fence, max(fence, self.highest),
                        self.max_count,
                    )
                return bad
            if self.owner != client or self.count == self.max_count:
                return bad
            if self.fence == INVALID_FENCE:
                # hold began fenceless: a re-acquire may introduce a
                # (strictly newer) fence, or stay fenceless
                if fence == INVALID_FENCE or fence > self.highest:
                    return ReentrantFencedMutex(
                        client, self.count + 1, fence,
                        max(fence, self.highest), self.max_count,
                    )
                return bad
            # hold is fenced: re-acquires reuse its fence or none
            if fence == INVALID_FENCE or fence == self.fence:
                return ReentrantFencedMutex(
                    client, self.count + 1, self.fence, self.highest,
                    self.max_count,
                )
            return bad
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return bad
            if self.count == 1:
                return ReentrantFencedMutex(
                    None, 0, INVALID_FENCE, self.highest, self.max_count
                )
            return ReentrantFencedMutex(
                self.owner, self.count - 1, self.fence, self.highest,
                self.max_count,
            )
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return (
            isinstance(other, ReentrantFencedMutex)
            and other.owner == self.owner
            and other.count == self.count
            and other.fence == self.fence
            and other.highest == self.highest
        )

    def __hash__(self):
        return hash(
            ("reentrant-fenced-mutex", self.owner, self.count, self.fence,
             self.highest)
        )

    def __repr__(self):
        return (
            f"ReentrantFencedMutex(owner={self.owner!r}, "
            f"count={self.count}, fence={self.fence}, "
            f"highest={self.highest})"
        )


class AcquiredPermits(Model):
    """Semaphore: at most ``n_permits`` held across all clients, and a
    client may only release permits it holds.  (reference:
    hazelcast.clj:630-650 AcquiredPermitsModel, num-permits=2)"""

    __slots__ = ("n_permits", "acquired")

    def __init__(
        self,
        n_permits: int = 2,
        acquired: Tuple[Tuple[str, int], ...] = (),
    ):
        self.n_permits = n_permits
        self.acquired = acquired  # sorted ((client, count), ...)

    def _counts(self) -> dict:
        return dict(self.acquired)

    @staticmethod
    def _pack(counts: dict) -> Tuple[Tuple[str, int], ...]:
        return tuple(sorted((k, v) for k, v in counts.items() if v))

    def step(self, op) -> Model:
        client = _client(op)
        if client is None:
            return inconsistent("no owner!")
        counts = self._counts()
        if op.f == "acquire":
            if sum(counts.values()) < self.n_permits:
                counts[client] = counts.get(client, 0) + 1
                return AcquiredPermits(self.n_permits, self._pack(counts))
            return inconsistent(
                f"client {client} cannot acquire: all {self.n_permits} "
                "permits held"
            )
        if op.f == "release":
            if counts.get(client, 0) > 0:
                counts[client] -= 1
                return AcquiredPermits(self.n_permits, self._pack(counts))
            return inconsistent(
                f"client {client} releases a permit it does not hold"
            )
        return inconsistent(f"unknown op f={op.f!r}")

    def __eq__(self, other):
        return (
            isinstance(other, AcquiredPermits)
            and other.n_permits == self.n_permits
            and other.acquired == self.acquired
        )

    def __hash__(self):
        return hash(("acquired-permits", self.n_permits, self.acquired))

    def __repr__(self):
        return (
            f"AcquiredPermits(n={self.n_permits}, "
            f"acquired={dict(self.acquired)!r})"
        )


def owner_mutex() -> OwnerMutex:
    return OwnerMutex()


def reentrant_mutex(
    max_count: int = REENTRANT_ACQUIRE_COUNT,
) -> ReentrantMutex:
    return ReentrantMutex(max_count=max_count)


def fenced_mutex() -> FencedMutex:
    return FencedMutex()


def reentrant_fenced_mutex(
    max_count: int = REENTRANT_ACQUIRE_COUNT,
) -> ReentrantFencedMutex:
    return ReentrantFencedMutex(max_count=max_count)


def acquired_permits(n_permits: int = 2) -> AcquiredPermits:
    return AcquiredPermits(n_permits)
