"""jepsen_tpu: a TPU-native distributed-systems consistency-testing framework.

A test drives generator-scheduled client operations and injected faults
against a real (or fake, in-process) distributed system, records every
invocation/completion into a history, and verifies that history against
consistency models.  The analysis plane is TPU-first: histories are encoded
as integer op tensors and linearizability / transactional-anomaly checking
runs as jit-compiled JAX kernels, vmapped over batches of independent
histories and sharded across a device mesh (falling back to a pure-Python
oracle when no accelerator is present).

Capability map (reference: remysaissy/jepsen, studied in SURVEY.md):

- ``jepsen_tpu.history``    — op/history data model (knossos.op equivalent)
- ``jepsen_tpu.models``     — consistency models (knossos.model equivalent)
- ``jepsen_tpu.checker``    — Checker protocol + built-in checkers
- ``jepsen_tpu.ops``        — TPU kernels: encode, step kernels, WGL search
- ``jepsen_tpu.parallel``   — mesh/sharding helpers for batched checking
- ``jepsen_tpu.generator``  — pure-functional op scheduling DSL
- ``jepsen_tpu.interpreter``— threaded event loop building histories
- ``jepsen_tpu.client``     — Client protocol
- ``jepsen_tpu.nemesis``    — fault injection
- ``jepsen_tpu.control``    — remote execution (ssh/docker/k8s/dummy)
- ``jepsen_tpu.db``         — database lifecycle protocols
- ``jepsen_tpu.store``      — test persistence
- ``jepsen_tpu.cli``        — command-line entry points
- ``jepsen_tpu.elle``       — transactional anomaly (cycle) checking
- ``jepsen_tpu.trace``      — span tracing with pluggable exporters
- ``jepsen_tpu.suites``     — 28 database test suites over from-scratch
  wire protocols (incl. ``localkv``, a native C++ replicated register
  compiled on-node — the zero-dependency real-cluster proof)
"""

__version__ = "0.5.0"
