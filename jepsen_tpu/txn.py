"""Transaction micro-op helpers (reference: txn/src/jepsen/txn.clj:5-55).

A transactional op's :value is a list of micro-ops ("mops"), each a
``[f, k, v]`` triple: ``("r", key, value-read)``, ``("w", key, value)``,
or ``("append", key, element)``.  These helpers extract externally visible
reads/writes — the first read of a key before any write ("external read")
and the last write of a key ("external write").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Sequence, Tuple

Mop = Sequence  # (f, k, v)

R = "r"
W = "w"
APPEND = "append"


def reduce_mops(fn: Callable[[Any, Mop], Any], init: Any, txn: Iterable[Mop]) -> Any:
    """Fold fn over every micro-op in a transaction.
    (reference: txn.clj reduce-mops)"""
    acc = init
    for mop in txn:
        acc = fn(acc, mop)
    return acc


def ext_reads(txn: Iterable[Mop]) -> Dict[Any, Any]:
    """Externally-visible reads: key → value for each key read *before*
    being written in this txn.  (reference: txn.clj ext-reads)"""
    reads: Dict[Any, Any] = {}
    ignore = set()
    for f, k, v in txn:
        if f == R:
            if k not in ignore and k not in reads:
                reads[k] = v
        else:
            ignore.add(k)
    return reads


def ext_writes(txn: Iterable[Mop]) -> Dict[Any, Any]:
    """Externally-visible writes: key → final written value.
    (reference: txn.clj ext-writes)"""
    writes: Dict[Any, Any] = {}
    for f, k, v in txn:
        if f != R:
            writes[k] = v
    return writes


def ext_appends(txn: Iterable[Mop]) -> Dict[Any, List[Any]]:
    """key → list of appended elements, in order, for list-append txns."""
    appends: Dict[Any, List[Any]] = {}
    for f, k, v in txn:
        if f == APPEND:
            appends.setdefault(k, []).append(v)
    return appends


def reads_of_key(txn: Iterable[Mop], key: Any) -> List[Any]:
    return [v for f, k, v in txn if f == R and k == key]


def writes_of_key(txn: Iterable[Mop], key: Any) -> List[Any]:
    return [v for f, k, v in txn if f != R and k == key]


def op_mops(op) -> List[Tuple[Any, Mop]]:
    """[(op, mop)] pairs for a history op whose value is a txn."""
    return [(op, mop) for mop in (op.value or [])]


# ---------------------------------------------------------------------
# Micro-op accessors (reference: txn/src/jepsen/txn/micro_op.clj:1-35)
# ---------------------------------------------------------------------


def mop_f(mop: Mop) -> Any:
    """The function a micro-op executes."""
    return mop[0]


def mop_key(mop: Mop) -> Any:
    """The key a micro-op affects."""
    return mop[1]


def mop_value(mop: Mop) -> Any:
    """The value a micro-op used."""
    return mop[2]


def is_read(mop: Mop) -> bool:
    return mop_f(mop) == R


def is_write(mop: Mop) -> bool:
    return mop_f(mop) == W


def is_mop(mop: Any) -> bool:
    """Is this a legal [f k v] micro-op?"""
    try:
        return len(mop) == 3 and mop_f(mop) in (R, W)
    except TypeError:
        return False
