"""Disk-fault injection via the faultfs FUSE filesystem.

The TPU-native equivalent of the reference's CharybdeFS wrapper
(charybdefs/src/jepsen/charybdefs.clj): install build deps and compile
``native/faultfs.cc`` **on each DB node** (:40-65 — the reference
builds ScyllaDB's charybdefs + thrift there), mount ``/faulty`` as a
fault-injectable view of ``/real`` (:66-70), and flip faults at
runtime: ``break_all`` (every op → EIO), ``break_one_percent``
(probabilistic), ``clear`` (:72-85 cookbook recipes).  The control
channel is faultfs's own TCP command port instead of thrift.

Typical use: point the DB's data directory at /faulty and drive
``nemesis()`` ops ``{"f": "break-disk", "value": node-spec}`` /
``{"f": "heal-disk"}``.
"""

from __future__ import annotations

import errno as errno_mod
import os
from typing import Any, Iterable, Optional

from . import control
from .control import util as cu
from .nemesis import Nemesis
from .os_setup import debian

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
DIR = "/opt/faultfs"
BIN = f"{DIR}/faultfs"
REAL = "/real"      # (reference: charybdefs.clj:66-69)
FAULTY = "/faulty"
CTL_PORT = 7656


def _source() -> str:
    with open(os.path.join(NATIVE_DIR, "faultfs.cc")) as f:
        return f.read()


def install() -> None:
    """Build faultfs on the node and mount /faulty over /real.
    (reference: charybdefs.clj:41-70 install!)"""
    debian.install(["build-essential", "pkg-config", "libfuse-dev", "fuse"])
    with control.su():
        control.execute("mkdir", "-p", DIR, REAL, FAULTY)
        src = f"{DIR}/faultfs.cc"
        cu.write_file(_source(), src)
        control.execute(
            "bash", "-c",
            f"g++ -O2 -Wall {src} -o {BIN} "
            "$(pkg-config fuse --cflags --libs) -lpthread",
        )
        control.execute("modprobe", "fuse", check=False)
        control.execute("umount", FAULTY, check=False)
        control.execute(
            BIN, FAULTY, "-oallow_other,nonempty", "-r", REAL,
            "-p", str(CTL_PORT),
        )
        control.execute("chmod", "777", REAL, FAULTY)


def remove() -> None:
    with control.su():
        control.execute("umount", FAULTY, check=False)
        cu.grepkill("faultfs")


def _command(cmd: str) -> str:
    """Send one control command to the node-local faultfs."""
    res = control.execute(
        "python3", "-c",
        (
            "import socket,sys;"
            f"s=socket.create_connection(('127.0.0.1',{CTL_PORT}),timeout=5);"
            f"s.sendall({cmd!r}.encode()+b'\\n');"
            "print(s.recv(128).decode().strip())"
        ),
    )
    out = res.out.strip() if hasattr(res, "out") else str(res).strip()
    if not out.startswith(("OK", "mode=")):
        raise RuntimeError(f"faultfs control failed: {out!r}")
    return out


def break_all(errno: int = errno_mod.EIO) -> None:
    """All operations fail.  (reference: charybdefs.clj:72-75)"""
    _command(f"all {errno}")


def break_one_percent(errno: int = errno_mod.EIO) -> None:
    """1% of disk operations fail.  (reference: charybdefs.clj:77-80)"""
    _command(f"prob 10000 {errno}")


def break_probability(ppm: int, errno: int = errno_mod.EIO) -> None:
    """Fail ppm-per-million ops with errno."""
    _command(f"prob {ppm} {errno}")


def clear() -> None:
    """Remove fault injection.  (reference: charybdefs.clj:82-85)"""
    _command("clear")


def status() -> str:
    return _command("status")


class FaultFsNemesis(Nemesis):
    """Nemesis breaking/healing disks on a subset of nodes.

    Ops: {"f": "break-disk", "value": [nodes...] | None (all)},
         {"f": "break-disk-slow", ...} (1% probabilistic),
         {"f": "heal-disk", "value": ...}.
    """

    def setup(self, test):
        control.on_nodes(test, lambda t, n: install())
        return self

    def _targets(self, test, value) -> Iterable[Any]:
        if not value:
            return list(test["nodes"])
        if isinstance(value, str):  # a single node name, not a list
            return [value]
        return list(value)

    def invoke(self, test, op):
        nodes = self._targets(test, op.get("value"))
        if op["f"] == "break-disk":
            fn = lambda t, n: break_all()
        elif op["f"] == "break-disk-slow":
            fn = lambda t, n: break_one_percent()
        elif op["f"] == "heal-disk":
            fn = lambda t, n: clear()
        else:
            raise ValueError(f"unknown faultfs op {op['f']!r}")
        control.on_nodes(test, nodes, fn)
        return {**op, "value": {"disk": op["f"], "nodes": nodes}}

    def teardown(self, test):
        control.on_nodes(test, lambda t, n: cu.meh(remove))

    def fs(self):
        return {"break-disk", "break-disk-slow", "heal-disk"}
