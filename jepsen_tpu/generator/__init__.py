"""Pure-functional operation-scheduling DSL.

A *generator* is an immutable value that produces operations for worker
threads on demand (reference: jepsen/src/jepsen/generator.clj:382-390):

- ``op(gen, test, ctx)``   → ``(op, gen')`` | ``(PENDING, gen)`` | ``None``
- ``update(gen, test, ctx, event)`` → ``gen'`` — the generator's view of an
  event (invocation or completion) having happened.

Operations inside the DSL are plain dicts (``{"f": "write", "value": 1}``);
``fill_in_op`` assigns :type/:process/:time from the context.  Special op
types "sleep" and "log" instruct the worker rather than the client.  The
interpreter converts dicts to history Ops at the recording boundary.

Plain values lift into generators: ``None`` (exhausted), a dict (emit once,
filled from context), a callable (called — with (test, ctx) if it accepts
args — until it returns None), a list/tuple (run each element in turn).

Randomness goes through this module's ``rng`` so tests and the simulator
can pin seeds (the reference pins 45100, generator/test.clj:44-48).

Combinator inventory mirrors generator.clj:775-1593.
"""

from __future__ import annotations

import builtins
import random
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..history import NEMESIS

PENDING = "pending"

#: Module RNG; reseedable for deterministic tests.
rng = random.Random()


def set_seed(seed: Optional[int]) -> None:
    global rng
    rng = random.Random(seed)


def secs_to_nanos(s: float) -> int:
    return int(s * 1_000_000_000)


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------


def context(test: dict) -> dict:
    """Initial context: nemesis + `concurrency` numeric worker threads,
    each thread running the process of the same name.
    (reference: generator.clj:453-464)"""
    threads = [NEMESIS] + list(range(test.get("concurrency", 1)))
    return {
        "time": 0,
        "free_threads": tuple(threads),
        "workers": {t: t for t in threads},
    }


def free_processes(ctx: dict) -> List[Any]:
    w = ctx["workers"]
    return [w[t] for t in ctx["free_threads"]]


def some_free_process(ctx: dict) -> Optional[Any]:
    """A uniformly random free process (fair scheduling — see the
    reference's bifurcan-Set discussion, generator.clj:438-449)."""
    free = ctx["free_threads"]
    if not free:
        return None
    return ctx["workers"][free[rng.randrange(len(free))]]


def all_processes(ctx: dict) -> List[Any]:
    return list(ctx["workers"].values())


def free_threads(ctx: dict) -> Tuple:
    return ctx["free_threads"]


def all_threads(ctx: dict) -> List[Any]:
    return list(ctx["workers"].keys())


def process_to_thread(ctx: dict, process: Any) -> Optional[Any]:
    for t, p in ctx["workers"].items():
        if p == process:
            return t
    return None


def thread_to_process(ctx: dict, thread: Any) -> Any:
    return ctx["workers"].get(thread)


def next_process(ctx: dict, thread: Any) -> Any:
    """The replacement process id for a crashed thread (global context
    only).  (reference: generator.clj:519-527)"""
    if isinstance(thread, int):
        return ctx["workers"][thread] + len(
            [p for p in all_processes(ctx) if isinstance(p, int)]
        )
    return thread


def on_threads_context(pred: Callable[[Any], bool], ctx: dict) -> dict:
    """Restrict a context to threads satisfying pred.
    (reference: generator.clj:844-862)"""
    return {
        "time": ctx["time"],
        "free_threads": tuple(t for t in ctx["free_threads"] if pred(t)),
        "workers": {t: p for t, p in ctx["workers"].items() if pred(t)},
    }


# ---------------------------------------------------------------------------
# Protocol dispatch
# ---------------------------------------------------------------------------


class Generator:
    """Base class for combinator generators."""

    def op(self, test: dict, ctx: dict):
        raise NotImplementedError

    def update(self, test: dict, ctx: dict, event: dict) -> "Generator":
        return self


def _fn_arity_accepts_args(f: Callable) -> bool:
    try:
        import inspect

        sig = inspect.signature(f)
        required = [
            p
            for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is p.empty
        ]
        return len(required) == 2
    except (ValueError, TypeError):
        return False


def op(gen: Any, test: dict, ctx: dict):
    """Ask a (possibly-lifted) generator for an operation.
    (reference: generator.clj:545-590 base impls)"""
    while True:
        if gen is None:
            return None
        if isinstance(gen, Generator):
            return gen.op(test, ctx)
        if isinstance(gen, dict):
            filled = fill_in_op(gen, ctx)
            return (filled, gen if filled == PENDING else None)
        if callable(gen):
            x = gen(test, ctx) if _fn_arity_accepts_args(gen) else gen()
            if x is None:
                return None
            return op([x, gen], test, ctx)
        if isinstance(gen, (list, tuple)):
            if not gen:
                return None
            head, rest = gen[0], list(gen[1:])
            res = op(head, test, ctx)
            if res is None:
                gen = rest
                continue
            o, g2 = res
            return (o, ([g2] + rest) if rest else g2)
        raise TypeError(f"not a generator: {gen!r}")


def update(gen: Any, test: dict, ctx: dict, event: dict):
    """Inform a generator of an event.  (reference: generator.clj base
    impls; sequences pass updates to their first element)"""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        return [update(gen[0], test, ctx, event)] + list(gen[1:])
    raise TypeError(f"not a generator: {gen!r}")


def fill_in_op(o: dict, ctx: dict):
    """Fill :type/:process/:time from context; PENDING if no process is
    free.  (reference: generator.clj:531-543)"""
    p = some_free_process(ctx)
    if p is None:
        return PENDING
    out = dict(o)
    out.setdefault("time", ctx["time"])
    out.setdefault("process", p)
    out.setdefault("type", "invoke")
    return out


# ---------------------------------------------------------------------------
# Validation / debugging wrappers
# ---------------------------------------------------------------------------


class InvalidOp(Exception):
    def __init__(self, problems, res, gen, ctx):
        super().__init__(
            "Generator produced an invalid [op, gen'] tuple: "
            + "; ".join(problems)
            + f"\nresult: {res!r}\ncontext: {ctx!r}"
        )
        self.problems = problems


class Validate(Generator):
    """(reference: generator.clj:622-676)"""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        problems = []
        if not (isinstance(res, tuple) and len(res) == 2):
            problems.append("should return a tuple of two elements")
        else:
            o, _ = res
            if o != PENDING:
                if not isinstance(o, dict):
                    problems.append("should be either PENDING or a dict")
                else:
                    if o.get("type") not in ("invoke", "info", "sleep", "log"):
                        problems.append(
                            ":type should be invoke, info, sleep, or log"
                        )
                    if not isinstance(o.get("time"), (int, float)):
                        problems.append(":time should be a number")
                    if o.get("process") is None:
                        problems.append("no :process")
                    elif o["process"] not in free_processes(ctx):
                        problems.append(
                            f"process {o['process']!r} is not free"
                        )
        if problems:
            raise InvalidOp(problems, res, self.gen, ctx)
        return (res[0], Validate(res[1]))

    def update(self, test, ctx, event):
        return Validate(update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


class FriendlyExceptions(Generator):
    """(reference: generator.clj:678-718)"""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"Generator raised {type(e).__name__} when asked for an "
                f"operation.\nGenerator: {self.gen!r}\nContext: {ctx!r}"
            ) from e
        if res is None:
            return None
        return (res[0], FriendlyExceptions(res[1]))

    def update(self, test, ctx, event):
        try:
            g2 = update(self.gen, test, ctx, event)
        except Exception as e:
            raise RuntimeError(
                f"Generator raised {type(e).__name__} when updated with "
                f"{event!r}.\nGenerator: {self.gen!r}\nContext: {ctx!r}"
            ) from e
        return FriendlyExceptions(g2) if g2 is not None else None


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


class Trace(Generator):
    """Log every op/update through this generator.
    (reference: generator.clj:720-763)"""

    def __init__(self, k, gen, logger=None):
        import logging

        self.k = k
        self.gen = gen
        self.logger = logger or logging.getLogger("jepsen_tpu.generator")

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        self.logger.info("%s op ctx=%r -> %r", self.k, ctx, res and res[0])
        if res is None:
            return None
        return (res[0], Trace(self.k, res[1], self.logger))

    def update(self, test, ctx, event):
        self.logger.info("%s update event=%r", self.k, event)
        g2 = update(self.gen, test, ctx, event)
        return Trace(self.k, g2, self.logger) if g2 is not None else None


def trace(k, gen):
    return Trace(k, gen)


# ---------------------------------------------------------------------------
# Transformations
# ---------------------------------------------------------------------------


def concat(*gens):
    """Run each generator to exhaustion, in order.
    (reference: generator.clj:775-780)"""
    return list(gens)


class Map(Generator):
    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o if o == PENDING else self.f(o), Map(self.f, g2))

    def update(self, test, ctx, event):
        return Map(self.f, update(self.gen, test, ctx, event))


def map(f, gen):  # noqa: A001 — mirrors gen/map
    """Transform every op with f.  (reference: generator.clj:782-788)"""
    return Map(f, gen)


def f_map(fm: Dict[Any, Any], gen):
    """Rewrite op :f values through the mapping fm (for composed
    nemeses).  (reference: generator.clj:790-796)"""
    return Map(lambda o: {**o, "f": fm.get(o.get("f"), o.get("f"))}, gen)


class Filter(Generator):
    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = op(gen, test, ctx)
            if res is None:
                return None
            o, g2 = res
            if o == PENDING or self.f(o):
                return (o, Filter(self.f, g2))
            gen = g2

    def update(self, test, ctx, event):
        return Filter(self.f, update(self.gen, test, ctx, event))


def filter(f, gen):  # noqa: A001 — mirrors gen/filter
    """Pass through only ops satisfying f.
    (reference: generator.clj:798-817)"""
    return Filter(f, gen)


class IgnoreUpdates(Generator):
    """Note: unlike the reference's (internal, unconstructed) record of
    the same name, this preserves itself across op calls so updates stay
    blocked for the generator's whole lifetime."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], IgnoreUpdates(res[1]))

    def update(self, test, ctx, event):
        return self


def ignore_updates(gen):
    return IgnoreUpdates(gen)


class OnUpdate(Generator):
    """(reference: generator.clj:827-842)"""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], OnUpdate(self.f, res[1]))

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


class OnThreads(Generator):
    """(reference: generator.clj:864-881)"""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, on_threads_context(self.pred, ctx))
        if res is None:
            return None
        return (res[0], OnThreads(self.pred, res[1]))

    def update(self, test, ctx, event):
        if self.pred(process_to_thread(ctx, event.get("process"))):
            g2 = update(
                self.gen, test, on_threads_context(self.pred, ctx), event
            )
            return OnThreads(self.pred, g2)
        return self


def on_threads(pred, gen):
    return OnThreads(pred, gen)


on = on_threads


def soonest_op_map(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Pick whichever wrapped op occurs sooner; ties resolve randomly in
    proportion to :weight.  (reference: generator.clj:885-927)"""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    op1, op2 = m1["op"], m2["op"]
    if op1 == PENDING:
        return m2
    if op2 == PENDING:
        return m1
    t1, t2 = op1["time"], op2["time"]
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        w = w1 + w2
        chosen = m1 if rng.randrange(w) < w1 else m2
        return {**chosen, "weight": w}
    return m1 if t1 < t2 else m2


class Any(Generator):
    """(reference: generator.clj:929-944)"""

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            res = op(g, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i}
                )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Any(gens))

    def update(self, test, ctx, event):
        return Any([update(g, test, ctx, event) for g in self.gens])


def any(*gens):  # noqa: A001 — mirrors gen/any
    if len(gens) == 0:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(gens)


class EachThread(Generator):
    """Independent copy of the generator per thread.
    (reference: generator.clj:955-1007)"""

    def __init__(self, fresh_gen, gens: Dict[Any, Any]):
        self.fresh_gen = fresh_gen
        self.gens = gens

    def op(self, test, ctx):
        free = free_threads(ctx)
        all_t = all_threads(ctx)
        soonest = None
        for thread in free:
            g = self.gens.get(thread, self.fresh_gen)
            process = ctx["workers"][thread]
            sub_ctx = {
                "time": ctx["time"],
                "free_threads": (thread,),
                "workers": {thread: process},
            }
            res = op(g, test, sub_ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "thread": thread}
                )
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return (soonest["op"], EachThread(self.fresh_gen, gens))
        if len(free) != len(all_t):
            return (PENDING, self)
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        if thread is None:
            return self
        g = self.gens.get(thread, self.fresh_gen)
        sub_ctx = {
            "time": ctx["time"],
            "free_threads": tuple(
                t for t in ctx["free_threads"] if t == thread
            ),
            "workers": {thread: event.get("process")},
        }
        g2 = update(g, test, sub_ctx, event)
        gens = dict(self.gens)
        gens[thread] = g2
        return EachThread(self.fresh_gen, gens)


def each_thread(gen):
    return EachThread(gen, {})


class Reserve(Generator):
    """(reference: generator.clj:1009-1089)"""

    def __init__(self, ranges: List[set], gens: List[Any]):
        self.ranges = ranges  # list of thread-sets; gens has one extra
        self.all_ranges = set().union(*ranges) if ranges else set()
        self.gens = gens

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            sub = on_threads_context(lambda t, s=threads: t in s, ctx)
            res = op(self.gens[i], test, sub)
            if res is not None:
                soonest = soonest_op_map(
                    soonest,
                    {
                        "op": res[0],
                        "gen": res[1],
                        "weight": len(threads),
                        "i": i,
                    },
                )
        default_ctx = on_threads_context(
            lambda t: t not in self.all_ranges, ctx
        )
        res = op(self.gens[-1], test, default_ctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest,
                {
                    "op": res[0],
                    "gen": res[1],
                    "weight": len(default_ctx["workers"]),
                    "i": len(self.ranges),
                },
            )
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Reserve(self.ranges, gens))

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        i = len(self.ranges)
        for j, r in enumerate(self.ranges):
            if thread in r:
                i = j
                break
        gens = list(self.gens)
        gens[i] = update(gens[i], test, ctx, event)
        return Reserve(self.ranges, gens)


def reserve(*args):
    """(reserve 5, write_gen, 10, cas_gen, default_gen): thread ranges per
    generator plus a default for the rest."""
    if not args:
        raise ValueError("reserve needs a default generator")
    *pairs, default = args
    if len(pairs) % 2 != 0:
        raise ValueError("reserve takes count/generator pairs + default")
    ranges = []
    gens = []
    n = 0
    for i in range(0, len(pairs), 2):
        count, gen = pairs[i], pairs[i + 1]
        ranges.append(set(range(n, n + count)))
        gens.append(gen)
        n += count
    gens.append(default)
    return Reserve(ranges, gens)


def clients(client_gen, nemesis_gen=None):
    """Restrict to client threads; two-arity combines client + nemesis
    generators.  (reference: generator.clj:1093-1103)"""
    if nemesis_gen is None:
        return on_threads(lambda t: t != NEMESIS, client_gen)
    return any(clients(client_gen), nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    """(reference: generator.clj:1105-1115)"""
    if client_gen is None:
        return on_threads(lambda t: t == NEMESIS, nemesis_gen)
    return any(nemesis(nemesis_gen), clients(client_gen))


class Mix(Generator):
    """The next-index draw happens lazily at op time (not construction)
    so seeding the module rng after building a test still yields
    deterministic schedules.  (reference: generator.clj:1124-1154)"""

    def __init__(self, i, gens):
        self.i = i  # None = not yet drawn
        self.gens = list(gens)

    def op(self, test, ctx):
        if not self.gens:
            return None
        i = self.i if self.i is not None else rng.randrange(len(self.gens))
        res = op(self.gens[i], test, ctx)
        if res is not None:
            gens = list(self.gens)
            gens[i] = res[1]
            return (res[0], Mix(rng.randrange(len(gens)), gens))
        gens = list(self.gens)
        del gens[i]
        if not gens:
            return None
        return Mix(None, gens).op(test, ctx)

    def update(self, test, ctx, event):
        return self


def mix(gens):
    gens = list(gens)
    if not gens:
        return None
    return Mix(None, gens)


class Limit(Generator):
    def __init__(self, remaining, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, Limit(self.remaining, g2))
        return (o, Limit(self.remaining - 1, g2))

    def update(self, test, ctx, event):
        return Limit(self.remaining, update(self.gen, test, ctx, event))


def limit(remaining, gen):
    """At most `remaining` operations.  (reference: generator.clj:1156-1170)"""
    return Limit(remaining, gen)


def once(gen):
    return limit(1, gen)


def log(msg):
    """A one-shot op instructing the worker to log a message.
    (reference: generator.clj:1177-1181)"""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Emit ops from an unchanging generator forever (or `remaining`
    times).  (reference: generator.clj:1183-1210)"""

    def __init__(self, remaining, gen):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, _ = res
        if o == PENDING:
            return (o, self)
        return (o, Repeat(self.remaining - 1, self.gen))

    def update(self, test, ctx, event):
        return Repeat(self.remaining, update(self.gen, test, ctx, event))


def repeat(*args):
    if len(args) == 1:
        return Repeat(-1, args[0])
    n, gen = args
    if n < 0:
        raise ValueError("repeat limit must be non-negative")
    return Repeat(n, gen)


class Cycle(Generator):
    """Re-run a finite generator when it exhausts.
    (reference: generator.clj:1212-1238)"""

    def __init__(self, remaining, original_gen, gen):
        self.remaining = remaining
        self.original_gen = original_gen
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is not None:
            return (res[0], Cycle(self.remaining, self.original_gen, res[1]))
        return Cycle(
            self.remaining - 1, self.original_gen, self.original_gen
        ).op(test, ctx)

    def update(self, test, ctx, event):
        return Cycle(
            self.remaining,
            self.original_gen,
            update(self.gen, test, ctx, event),
        )


def cycle(*args):
    if len(args) == 1:
        return Cycle(-1, args[0], args[0])
    n, gen = args
    return Cycle(n, gen, gen)


class ProcessLimit(Generator):
    """(reference: generator.clj:1240-1265)"""

    def __init__(self, n, procs: frozenset, gen):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, ProcessLimit(self.n, self.procs, g2))
        procs = self.procs | frozenset(all_processes(ctx))
        if len(procs) <= self.n:
            return (o, ProcessLimit(self.n, procs, g2))
        return None

    def update(self, test, ctx, event):
        return ProcessLimit(
            self.n, self.procs, update(self.gen, test, ctx, event)
        )


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


class TimeLimit(Generator):
    """(reference: generator.clj:1267-1291)"""

    def __init__(self, limit_nanos, cutoff, gen):
        self.limit = limit_nanos
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, TimeLimit(self.limit, self.cutoff, g2))
        cutoff = self.cutoff if self.cutoff is not None else o["time"] + self.limit
        if o["time"] < cutoff:
            return (o, TimeLimit(self.limit, cutoff, g2))
        return None

    def update(self, test, ctx, event):
        return TimeLimit(
            self.limit, self.cutoff, update(self.gen, test, ctx, event)
        )


def time_limit(dt_seconds, gen):
    return TimeLimit(secs_to_nanos(dt_seconds), None, gen)


class Stagger(Generator):
    """Uniformly-random inter-op delays, global across threads.
    (reference: generator.clj:1293-1330)"""

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, self)
        next_time = self.next_time if self.next_time is not None else ctx["time"]
        jitter = int(rng.random() * self.dt)
        if next_time <= o["time"]:
            return (o, Stagger(self.dt, o["time"] + jitter, g2))
        o = {**o, "time": next_time}
        return (o, Stagger(self.dt, next_time + jitter, g2))

    def update(self, test, ctx, event):
        return Stagger(
            self.dt, self.next_time, update(self.gen, test, ctx, event)
        )


def stagger(dt_seconds, gen):
    """Ops roughly every dt seconds (delays uniform in [0, 2dt)), across
    all threads together."""
    return Stagger(secs_to_nanos(2 * dt_seconds), None, gen)


class Delay(Generator):
    """Ops exactly dt apart (catching up if behind).
    (reference: generator.clj:1369-1395)"""

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, Delay(self.dt, self.next_time, g2))
        next_time = self.next_time if self.next_time is not None else o["time"]
        o = {**o, "time": max(o["time"], next_time)}
        return (o, Delay(self.dt, o["time"] + self.dt, g2))

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time, update(self.gen, test, ctx, event))


def delay(dt_seconds, gen):
    return Delay(secs_to_nanos(dt_seconds), None, gen)


def sleep(dt_seconds):
    """One special op making its process do nothing for dt seconds.
    (reference: generator.clj:1397-1401)"""
    return {"type": "sleep", "value": dt_seconds}


class Synchronize(Generator):
    """Wait for all workers to be free, then become the inner generator.
    (reference: generator.clj:1403-1423)"""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        free = free_threads(ctx)
        allt = all_threads(ctx)
        if len(free) == len(allt) and set(free) == set(allt):
            return op(self.gen, test, ctx)
        return (PENDING, self)

    def update(self, test, ctx, event):
        return Synchronize(update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Each generator runs to completion, with a barrier between.
    (reference: generator.clj:1425-1430)"""
    return [synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronize a). Argument order reads well in pipelines.
    (reference: generator.clj:1432-1441)"""
    return [b, synchronize(a)]


class UntilOk(Generator):
    """(reference: generator.clj:1443-1473)"""

    def __init__(self, gen, done, active_processes: frozenset):
        self.gen = gen
        self.done = done
        self.active = active_processes

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o == PENDING:
            return (o, UntilOk(g2, self.done, self.active))
        return (o, UntilOk(g2, self.done, self.active | {o.get("process")}))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        p = event.get("process")
        if p in self.active:
            t = event.get("type")
            if t == "ok":
                return UntilOk(g2, True, self.active - {p})
            if t in ("info", "fail"):
                return UntilOk(g2, self.done, self.active - {p})
        return UntilOk(g2, self.done, self.active)


def until_ok(gen):
    return UntilOk(gen, False, frozenset())


class FlipFlop(Generator):
    """(reference: generator.clj:1475-1489)"""

    def __init__(self, gens, i):
        self.gens = list(gens)
        self.i = i

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        gens = list(self.gens)
        gens[self.i] = res[1]
        return (res[0], FlipFlop(gens, (self.i + 1) % len(gens)))

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b):
    return FlipFlop([a, b], 0)


class CycleTimes(Generator):
    """Rotate between generators on a time schedule.
    (reference: generator.clj:1491-1581)"""

    def __init__(self, period, t0, intervals, cutoffs, gens):
        self.period = period
        self.t0 = t0
        self.intervals = intervals
        self.cutoffs = cutoffs
        self.gens = list(gens)

    def op(self, test, ctx):
        now = ctx["time"]
        t0 = self.t0 if self.t0 is not None else now
        in_period = (now - t0) % self.period
        cycle_start = now - in_period
        i = 0
        while i < len(self.cutoffs) and in_period >= self.cutoffs[i]:
            i += 1
        if i == len(self.gens):
            i = 0
        t = cycle_start + sum(self.intervals[:i])
        for _ in range(100_000):  # guard against pathological inner gens
            gen = self.gens[i]
            interval = self.intervals[i]
            t_end = t + interval
            res = op(gen, test, {**ctx, "time": max(now, t)})
            if res is None:
                return None
            o, g2 = res
            gens = list(self.gens)
            gens[i] = g2
            nxt = CycleTimes(self.period, t0, self.intervals, self.cutoffs, gens)
            if o == PENDING:
                return (PENDING, nxt)
            if o["time"] < t_end:
                return (o, nxt)
            # op falls after this window; try the next generator's window
            i = (i + 1) % len(self.gens)
            t = t_end
        raise RuntimeError("cycle_times could not place an op in any window")

    def update(self, test, ctx, event):
        return CycleTimes(
            self.period,
            self.t0,
            self.intervals,
            self.cutoffs,
            [update(g, test, ctx, event) for g in self.gens],
        )


def cycle_times(*specs):
    """cycle_times(5, gen_a, 10, gen_b): a for 5s, b for 10s, repeat."""
    if not specs:
        return None
    if len(specs) % 2 != 0:
        raise ValueError("cycle_times takes duration/generator pairs")
    intervals = [secs_to_nanos(specs[i]) for i in range(0, len(specs), 2)]
    gens = [specs[i] for i in range(1, len(specs), 2)]
    period = sum(intervals)
    cutoffs = []
    acc = 0
    for iv in intervals:
        acc += iv
        cutoffs.append(acc)
    return CycleTimes(period, None, intervals, cutoffs[:-1], gens)
