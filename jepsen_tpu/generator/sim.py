"""Deterministic generator simulation — the fake scheduler used to unit
test generators without threads or wall clocks.

(reference: jepsen/src/jepsen/generator/test.clj:50-182; fixed seed 45100
per :44-48)
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..history import NEMESIS
from . import (
    PENDING,
    context as make_context,
    next_process,
    op as gen_op,
    process_to_thread,
    set_seed,
    update as gen_update,
    validate,
)

RAND_SEED = 45100

DEFAULT_TEST: dict = {}

PERFECT_LATENCY = 10  # nanoseconds


def n_plus_nemesis_context(n: int) -> dict:
    return make_context({"concurrency": n})


def default_context() -> dict:
    return n_plus_nemesis_context(2)


def simulate(
    gen,
    complete_fn: Callable[[dict, dict], dict],
    ctx: Optional[dict] = None,
    test: Optional[dict] = None,
    seed: int = RAND_SEED,
) -> List[dict]:
    """Run a generator against a virtual-time scheduler; complete_fn maps
    (ctx, invocation) to its completion op.  Returns the full history of
    op dicts.  (reference: generator/test.clj:50-108)"""
    set_seed(seed)
    ctx = dict(ctx or default_context())
    test = test if test is not None else DEFAULT_TEST
    ops: List[dict] = []
    in_flight: List[dict] = []  # sorted by time
    gen = validate(gen)

    while True:
        res = gen_op(gen, test, ctx)
        if res is None:
            return ops + in_flight
        invoke, gen2 = res

        if invoke != PENDING and (
            not in_flight or invoke["time"] <= in_flight[0]["time"]
        ):
            # invocation happens before every in-flight completion
            thread = process_to_thread(ctx, invoke["process"])
            ctx = {
                **ctx,
                "time": max(ctx["time"], invoke["time"]),
                "free_threads": tuple(
                    t for t in ctx["free_threads"] if t != thread
                ),
            }
            gen2 = gen_update(gen2, test, ctx, invoke)
            complete = complete_fn(ctx, invoke)
            in_flight = sorted(in_flight + [complete], key=lambda o: o["time"])
            ops.append(invoke)
            gen = gen2
        else:
            # must complete something first
            if not in_flight:
                raise AssertionError(
                    "generator pending and nothing in flight???"
                )
            done = in_flight[0]
            thread = process_to_thread(ctx, done["process"])
            ctx = {
                **ctx,
                "time": max(ctx["time"], done["time"]),
                "free_threads": tuple(ctx["free_threads"]) + (thread,),
            }
            # NOTE: gen (not gen2) — a pending op result doesn't advance
            # the generator (reference: generator/test.clj:102 updates
            # `gen`, the pre-op generator)
            gen = gen_update(gen, test, ctx, done)
            if thread != NEMESIS and done.get("type") == "info":
                workers = dict(ctx["workers"])
                workers[thread] = next_process(ctx, thread)
                ctx = {**ctx, "workers": workers}
            ops.append(done)
            in_flight = in_flight[1:]


def invocations(history: List[dict]) -> List[dict]:
    return [o for o in history if o.get("type") == "invoke"]


def quick_ops(gen, ctx=None, test=None) -> List[dict]:
    """Every op completes perfectly, instantly, zero latency.
    (reference: generator/test.clj:110-117)"""
    return simulate(
        gen, lambda ctx, inv: {**inv, "type": "ok"}, ctx=ctx, test=test
    )


def quick(gen, ctx=None, test=None) -> List[dict]:
    return invocations(quick_ops(gen, ctx, test))


def perfect_star(gen, ctx=None, test=None) -> List[dict]:
    """Ops succeed after 10ns; full history.
    (reference: generator/test.clj:130-141)"""
    return simulate(
        gen,
        lambda ctx, inv: {
            **inv,
            "type": "ok",
            "time": inv["time"] + PERFECT_LATENCY,
        },
        ctx=ctx,
        test=test,
    )


def perfect(gen, ctx=None, test=None) -> List[dict]:
    return invocations(perfect_star(gen, ctx, test))


def perfect_info(gen, ctx=None, test=None) -> List[dict]:
    """Every op crashes after 10ns; invocations only.
    (reference: generator/test.clj:152-163)"""
    return invocations(
        simulate(
            gen,
            lambda ctx, inv: {
                **inv,
                "type": "info",
                "time": inv["time"] + PERFECT_LATENCY,
            },
            ctx=ctx,
            test=test,
        )
    )


def imperfect(gen, ctx=None, test=None) -> List[dict]:
    """Threads cycle fail → info → ok; full history.
    (reference: generator/test.clj:165-182)"""
    state: dict = {}
    transitions = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(ctx, inv):
        t = process_to_thread(ctx, inv["process"])
        state[t] = transitions[state.get(t)]
        return {**inv, "type": state[t], "time": inv["time"] + PERFECT_LATENCY}

    return simulate(gen, complete, ctx=ctx, test=test)
