"""Bind stdout to a report file.  (reference: jepsen/src/jepsen/report.clj)"""

from __future__ import annotations

import contextlib
import io
import sys
from contextlib import contextmanager


@contextmanager
def to(filename: str):
    """Within the block, stdout tees to `filename`.
    (reference: report.clj:7-16)"""
    real = sys.stdout

    class Tee(io.TextIOBase):
        def __init__(self, f):
            self.f = f

        def write(self, s):
            real.write(s)
            self.f.write(s)
            return len(s)

        def flush(self):
            real.flush()
            self.f.flush()

    with open(filename, "w") as f:
        tee = Tee(f)
        with contextlib.redirect_stdout(tee):
            yield
