"""TPU analysis kernels: history encoding, model step kernels, and the
batched linearizability search.

- ``encode``: host-side packing of histories into padded int32 tensors
- ``step_kernels``: branchless jit-compatible model transition functions
- ``wgl``: the vmapped bitset-frontier linearizability search
- ``cycle``: batched transitive-closure cycle detection (Elle-style)
"""
