"""Host-side encoding of histories into the padded int tensors the WGL
kernel consumes.

The key idea is *slot remapping*: at any moment at most ``slot_cap`` ops
are open (invoked, not yet ok — including indeterminate ops, which stay
open forever), so each op borrows a transient slot id and a config's
linearized-set fits one uint32 **independent of history length**.  Slots
free when their op completes (the completed op joins the common linearized
prefix); info ops hold their slot to the end.

Invoke and info events are no-ops for the search (closure is deferred to
the filtering events — see jepsen_tpu.checker.linear), so the event stream
the device sees is just the *ok* completions, each with a snapshot of the
currently-open candidate ops:

- ``ev_slot[E]``      slot of the op completing at event e (-1 = padding)
- ``cand_slot[E,C]``  open slots at event e (-1 = unused lane)
- ``cand_f/a/b[E,C]`` the op encodings for those slots

Histories whose open-op count ever exceeds slot_cap fall back to the CPU
oracle (reported by returning None), mirroring how the reference degrades
to :unknown rather than guessing (checker.clj:74-85).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..history import History
from ..checker import linear
from .. import models as m
from .step_kernels import ModelSpec, spec_for

DEFAULT_SLOT_CAP = 32

#: value ids ride int16 lanes to halve HBM/PCIe traffic for the event
#: stream; histories with more distinct values fall back to the oracle
MAX_VALUE_ID = 32_000


@dataclass
class EncodedHistory:
    init_state: int
    ev_slot: np.ndarray      # [E] int32
    cand_slot: np.ndarray    # [E, C] int8 (-1 = unused lane)
    cand_f: np.ndarray       # [E, C] int8
    cand_a: np.ndarray       # [E, C] int16
    cand_b: np.ndarray       # [E, C] int16
    n_ops: int
    #: peak concurrently-open op count — every slot id used is < this, so
    #: the batch can trim candidate lanes (and linset bits) down to it
    max_open: int = 0


@dataclass
class EncodedBatch:
    """A stack of encoded histories padded to common [B, E, C] shapes."""

    init_state: np.ndarray   # [B] int32
    ev_slot: np.ndarray      # [B, E] int32
    cand_slot: np.ndarray    # [B, E, C]
    cand_f: np.ndarray       # [B, E, C]
    cand_a: np.ndarray       # [B, E, C]
    cand_b: np.ndarray       # [B, E, C]
    #: positions of histories that could not be encoded (oracle fallback)
    fallback: List[int] = field(default_factory=list)
    #: original batch order index per encoded row
    row_history: List[int] = field(default_factory=list)


def _prepare_encoding(history, model, spec):
    """Shared front half: event stream + per-op (f, a, b) codes, or
    None when the model/ops can't be encoded."""
    events, ops = linear.prepare(history, pure_fs=spec.pure_fs)
    valmap: Dict[Any, int] = {}
    try:
        init = spec.init_state(model, valmap)
        enc_ops = [spec.encode_op(op, valmap) for op in ops]
    except ValueError:
        return None
    if len(valmap) > MAX_VALUE_ID:
        return None  # value ids would overflow the int16 lanes
    return events, ops, init, enc_ops


def encode_history(
    history: History,
    model: m.Model,
    slot_cap: int = DEFAULT_SLOT_CAP,
    spec: Optional[ModelSpec] = None,
) -> Optional[EncodedHistory]:
    """Encode one history, or None if unsupported (model has no kernel,
    open-op count exceeds slot_cap, or an op can't be encoded).

    The per-event candidate snapshots are built vectorized — an op is a
    candidate at completion row r iff its invoke precedes r's event
    position and its own completion doesn't, a CONTIGUOUS row range
    computed via searchsorted, so work and memory scale with candidate
    pairs (E × average open ops), never E × n_ops — because host
    encoding is the production ingest path and per-event Python loops
    would cap the device's throughput (SURVEY.md §7, host↔device feed
    rate).  Only slot assignment stays a (cheap, O(n)) sequential
    pass: which slot an op borrows depends on the free set at its
    invoke."""
    import heapq

    spec = spec or spec_for(model)
    if spec is None:
        return None
    pre = _prepare_encoding(history, model, spec)
    if pre is None:
        return None
    events, ops, init, enc_ops = pre

    n = len(ops)
    T = len(events)
    # event-position bookkeeping: t_inv[o], t_done[o] (inf if never ok),
    # and the stream positions of ok events (the kernel's rows)
    t_inv = np.zeros((n,), np.int64)
    t_done = np.full((n,), T + 1, np.int64)
    ok_pos = []
    ok_op_ids = []
    slot = np.full((n,), -1, np.int16)
    free: list = list(range(slot_cap))
    heapq.heapify(free)
    open_count = 0
    max_open = 0
    for t, (kind, op_id) in enumerate(events):
        if kind == "invoke":
            if not free:
                return None  # too many concurrently-open ops
            slot[op_id] = heapq.heappop(free)
            t_inv[op_id] = t
            open_count += 1
            max_open = max(max_open, open_count)
        elif kind == "ok":
            t_done[op_id] = t
            ok_pos.append(t)
            ok_op_ids.append(op_id)
            heapq.heappush(free, int(slot[op_id]))
            open_count -= 1
        # info: op keeps its slot forever

    E = len(ok_pos)
    C = slot_cap
    cand_slot = np.full((E, C), -1, np.int8)
    cand_f = np.zeros((E, C), np.int8)
    cand_a = np.zeros((E, C), np.int16)
    cand_b = np.zeros((E, C), np.int16)
    if E:
        ok_pos_a = np.asarray(ok_pos, np.int64)
        # an op is a candidate at completion row r iff r's event
        # position lies in (t_inv, t_done] — and rows are ordered by
        # position, so each op's candidacy is one CONTIGUOUS row range:
        # total work scales with candidate pairs (E × avg open ops),
        # not E × n_ops
        r_lo = np.searchsorted(ok_pos_a, t_inv, side="right")
        r_hi = np.searchsorted(ok_pos_a, t_done, side="right") - 1
        spans = np.maximum(r_hi - r_lo + 1, 0)
        op_idx = np.repeat(np.arange(n), spans)
        span_starts = np.concatenate(([0], np.cumsum(spans[:-1])))
        within = np.arange(int(spans.sum())) - np.repeat(span_starts, spans)
        rows = np.repeat(r_lo, spans) + within
        # lane order: ops ascending within each row (pairs arrive
        # op-major; resort row-major)
        order = np.lexsort((op_idx, rows))
        rows, op_idx = rows[order], op_idx[order]
        counts = np.bincount(rows, minlength=E)
        row_starts = np.concatenate(([0], np.cumsum(counts[:-1])))
        lanes = np.arange(len(op_idx)) - np.repeat(row_starts, counts)
        fab = np.asarray(enc_ops, np.int32).reshape(n, 3)
        cand_slot[rows, lanes] = slot[op_idx].astype(np.int8)
        cand_f[rows, lanes] = fab[op_idx, 0].astype(np.int8)
        cand_a[rows, lanes] = fab[op_idx, 1].astype(np.int16)
        cand_b[rows, lanes] = fab[op_idx, 2].astype(np.int16)
        ev_slot_arr = slot[np.asarray(ok_op_ids, np.int64)].astype(np.int32)
    else:
        ev_slot_arr = np.full((0,), -1, np.int32)

    return EncodedHistory(
        init_state=init,
        ev_slot=ev_slot_arr,
        cand_slot=cand_slot,
        cand_f=cand_f,
        cand_a=cand_a,
        cand_b=cand_b,
        n_ops=n,
        max_open=max_open,
    )


def _encode_history_loop(
    history: History,
    model: m.Model,
    slot_cap: int = DEFAULT_SLOT_CAP,
    spec: Optional[ModelSpec] = None,
) -> Optional[EncodedHistory]:
    """The straightforward per-event-loop encoder, kept as the
    differential reference for the vectorized encode_history (the two
    must agree array-for-array; tests/test_wgl.py pins it)."""
    spec = spec or spec_for(model)
    if spec is None:
        return None
    pre = _prepare_encoding(history, model, spec)
    if pre is None:
        return None
    events, ops, init, enc_ops = pre

    E = sum(1 for kind, _ in events if kind == "ok")
    C = slot_cap
    ev_slot_arr = np.full((E,), -1, np.int32)
    cand_slot = np.full((E, C), -1, np.int8)
    cand_f = np.zeros((E, C), np.int8)
    cand_a = np.zeros((E, C), np.int16)
    cand_b = np.zeros((E, C), np.int16)

    slot_of: Dict[int, int] = {}
    free = sorted(range(slot_cap), reverse=True)  # pop() takes smallest
    row = 0
    max_open = 0
    for kind, op_id in events:
        if kind == "invoke":
            if not free:
                return None  # too many concurrently-open ops
            slot_of[op_id] = free.pop()
            max_open = max(max_open, len(slot_of))
        elif kind == "ok":
            # snapshot of open ops (incl. the completing one) BEFORE filter
            for lane, oid in enumerate(sorted(slot_of.keys())):
                f, a, b = enc_ops[oid]
                cand_slot[row, lane] = slot_of[oid]
                cand_f[row, lane] = f
                cand_a[row, lane] = a
                cand_b[row, lane] = b
            ev_slot_arr[row] = slot_of[op_id]
            row += 1
            free.append(slot_of.pop(op_id))
            free.sort(reverse=True)
        # info: op keeps its slot forever

    return EncodedHistory(
        init_state=init,
        ev_slot=ev_slot_arr,
        cand_slot=cand_slot,
        cand_f=cand_f,
        cand_a=cand_a,
        cand_b=cand_b,
        n_ops=len(ops),
        max_open=max_open,
    )


def round_up(n: int, multiple: int = 64) -> int:
    """Bucket sizes to multiples to bound recompilation."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def bucket_key(
    e: EncodedHistory, slot_cap: int, event_bucket: int = 64
) -> tuple:
    """The padded ``(E, C)`` shape bucket one encoded history stacks
    into: events round to ``event_bucket`` (bounding recompiles),
    candidate lanes to the history's own peak concurrency rounded to 4
    and capped at ``slot_cap``.  Shared by :func:`batch_encode`'s
    bucketed mode and the streaming bucketer in
    :mod:`jepsen_tpu.engine.pipeline`, so the two can never disagree
    about which histories share a compiled shape."""
    E = round_up(e.ev_slot.shape[0], event_bucket)
    C = min(slot_cap, round_up(e.max_open, 4))
    return E, C


def global_shape(
    encoded: Sequence[EncodedHistory], slot_cap: int, event_bucket: int = 64
) -> tuple:
    """The historical single-batch padded ``(E, C)``: every history
    padded to the global max event count, candidate lanes to the
    batch's peak concurrency (rounded to 4, capped at ``slot_cap``) —
    this shrinks the frontier-expansion width and sort size, usually
    the dominant cost.  The ONE definition both ``batch_encode``'s
    unbucketed mode and the engine's ``bucketed=False`` path read, so
    "bucketed=False restores the old single-batch behavior" can never
    silently desynchronize."""
    E = round_up(max(e.ev_slot.shape[0] for e in encoded), event_bucket)
    C = min(slot_cap, round_up(max(e.max_open for e in encoded), 4))
    return E, C


def empty_batch(slot_cap: int, fallback=(), rows=()) -> EncodedBatch:
    """A zero-row EncodedBatch (the all-fallback shape)."""
    return EncodedBatch(
        init_state=np.zeros((0,), np.int32),
        ev_slot=np.zeros((0, 0), np.int32),
        cand_slot=np.zeros((0, 0, slot_cap), np.int8),
        cand_f=np.zeros((0, 0, slot_cap), np.int8),
        cand_a=np.zeros((0, 0, slot_cap), np.int16),
        cand_b=np.zeros((0, 0, slot_cap), np.int16),
        fallback=list(fallback),
        row_history=list(rows),
    )


def stack_encoded(
    encoded: Sequence[EncodedHistory],
    rows: Sequence[int],
    E: int,
    C: int,
    fallback=(),
) -> EncodedBatch:
    """Stack encoded histories into one padded ``[B, E, C]`` batch.
    Candidate lanes are trimmed to ``C`` — sound because every slot id
    used is < the history's ``max_open`` ≤ C (the caller derives C from
    the stack's peak concurrency, see :func:`bucket_key`)."""
    B = len(encoded)
    init_state = np.zeros((B,), np.int32)
    ev_slot = np.full((B, E), -1, np.int32)
    cand_slot = np.full((B, E, C), -1, np.int8)
    cand_f = np.zeros((B, E, C), np.int8)
    cand_a = np.zeros((B, E, C), np.int16)
    cand_b = np.zeros((B, E, C), np.int16)
    for bi, e in enumerate(encoded):
        n = e.ev_slot.shape[0]
        init_state[bi] = e.init_state
        ev_slot[bi, :n] = e.ev_slot
        cand_slot[bi, :n] = e.cand_slot[:, :C]
        cand_f[bi, :n] = e.cand_f[:, :C]
        cand_a[bi, :n] = e.cand_a[:, :C]
        cand_b[bi, :n] = e.cand_b[:, :C]
    return EncodedBatch(
        init_state=init_state,
        ev_slot=ev_slot,
        cand_slot=cand_slot,
        cand_f=cand_f,
        cand_a=cand_a,
        cand_b=cand_b,
        fallback=list(fallback),
        row_history=list(rows),
    )


def batch_encode(
    histories: Sequence[History],
    model: m.Model,
    slot_cap: int = DEFAULT_SLOT_CAP,
    event_bucket: int = 64,
    bucketed: bool = False,
):
    """Encode histories into padded batches; unencodable ones land in
    ``fallback`` for the CPU oracle.

    ``bucketed=False`` (the default, the historical behavior) returns
    ONE :class:`EncodedBatch` padded to the global max event count —
    every short history pays the longest history's padding.
    ``bucketed=True`` instead returns a ``List[EncodedBatch]``, one per
    padded ``(E, C)`` shape bucket (:func:`bucket_key`), sorted by
    shape, so the engine dispatches tight shapes; the global
    ``fallback`` list rides on the FIRST returned batch (an
    all-fallback input returns a single zero-row batch carrying it)."""
    spec = spec_for(model)
    encoded: List[EncodedHistory] = []
    rows: List[int] = []
    fallback: List[int] = []
    for i, h in enumerate(histories):
        e = encode_history(h, model, slot_cap, spec) if spec else None
        if e is None:
            fallback.append(i)
        else:
            encoded.append(e)
            rows.append(i)

    if not bucketed:
        if not encoded:
            return empty_batch(slot_cap, fallback, rows)
        E, C = global_shape(encoded, slot_cap, event_bucket)
        return stack_encoded(encoded, rows, E, C, fallback)

    buckets: dict = {}
    for e, i in zip(encoded, rows):
        buckets.setdefault(bucket_key(e, slot_cap, event_bucket), []).append(
            (e, i)
        )
    if not buckets:
        return [empty_batch(slot_cap, fallback, rows)]
    out: List[EncodedBatch] = []
    for key in sorted(buckets):
        E, C = key
        es = [e for e, _ in buckets[key]]
        idxs = [i for _, i in buckets[key]]
        out.append(
            stack_encoded(es, idxs, E, C, fallback if not out else ())
        )
    return out
