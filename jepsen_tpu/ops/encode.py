"""Host-side encoding of histories into the padded int tensors the WGL
kernel consumes.

The key idea is *slot remapping*: at any moment at most ``slot_cap`` ops
are open (invoked, not yet ok — including indeterminate ops, which stay
open forever), so each op borrows a transient slot id and a config's
linearized-set fits one uint32 **independent of history length**.  Slots
free when their op completes (the completed op joins the common linearized
prefix); info ops hold their slot to the end.

Invoke and info events are no-ops for the search (closure is deferred to
the filtering events — see jepsen_tpu.checker.linear), so the event stream
the device sees is just the *ok* completions, each with a snapshot of the
currently-open candidate ops:

- ``ev_slot[E]``      slot of the op completing at event e (-1 = padding)
- ``cand_slot[E,C]``  open slots at event e (-1 = unused lane)
- ``cand_f/a/b[E,C]`` the op encodings for those slots

Histories whose open-op count ever exceeds slot_cap fall back to the CPU
oracle (reported by returning None), mirroring how the reference degrades
to :unknown rather than guessing (checker.clj:74-85).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..history import History
from ..checker import linear
from .. import models as m
from .step_kernels import ModelSpec, spec_for

DEFAULT_SLOT_CAP = 32

#: value ids ride int16 lanes to halve HBM/PCIe traffic for the event
#: stream; histories with more distinct values fall back to the oracle
MAX_VALUE_ID = 32_000


@dataclass
class EncodedHistory:
    init_state: int
    ev_slot: np.ndarray      # [E] int32
    cand_slot: np.ndarray    # [E, C] int8 (-1 = unused lane)
    cand_f: np.ndarray       # [E, C] int8
    cand_a: np.ndarray       # [E, C] int16
    cand_b: np.ndarray       # [E, C] int16
    n_ops: int
    #: peak concurrently-open op count — every slot id used is < this, so
    #: the batch can trim candidate lanes (and linset bits) down to it
    max_open: int = 0


@dataclass
class EncodedBatch:
    """A stack of encoded histories padded to common [B, E, C] shapes."""

    init_state: np.ndarray   # [B] int32
    ev_slot: np.ndarray      # [B, E] int32
    cand_slot: np.ndarray    # [B, E, C]
    cand_f: np.ndarray       # [B, E, C]
    cand_a: np.ndarray       # [B, E, C]
    cand_b: np.ndarray       # [B, E, C]
    #: positions of histories that could not be encoded (oracle fallback)
    fallback: List[int] = field(default_factory=list)
    #: original batch order index per encoded row
    row_history: List[int] = field(default_factory=list)


def encode_history(
    history: History,
    model: m.Model,
    slot_cap: int = DEFAULT_SLOT_CAP,
    spec: Optional[ModelSpec] = None,
) -> Optional[EncodedHistory]:
    """Encode one history, or None if unsupported (model has no kernel,
    open-op count exceeds slot_cap, or an op can't be encoded)."""
    spec = spec or spec_for(model)
    if spec is None:
        return None
    events, ops = linear.prepare(history, pure_fs=spec.pure_fs)

    valmap: Dict[Any, int] = {}
    try:
        init = spec.init_state(model, valmap)
        enc_ops = [spec.encode_op(op, valmap) for op in ops]
    except ValueError:
        return None
    if len(valmap) > MAX_VALUE_ID:
        return None  # value ids would overflow the int16 lanes

    E = sum(1 for kind, _ in events if kind == "ok")
    C = slot_cap
    ev_slot_arr = np.full((E,), -1, np.int32)
    cand_slot = np.full((E, C), -1, np.int8)
    cand_f = np.zeros((E, C), np.int8)
    cand_a = np.zeros((E, C), np.int16)
    cand_b = np.zeros((E, C), np.int16)

    slot_of: Dict[int, int] = {}
    free = sorted(range(slot_cap), reverse=True)  # pop() takes smallest
    row = 0
    max_open = 0
    for kind, op_id in events:
        if kind == "invoke":
            if not free:
                return None  # too many concurrently-open ops
            slot_of[op_id] = free.pop()
            max_open = max(max_open, len(slot_of))
        elif kind == "ok":
            # snapshot of open ops (incl. the completing one) BEFORE filter
            for lane, oid in enumerate(sorted(slot_of.keys())):
                f, a, b = enc_ops[oid]
                cand_slot[row, lane] = slot_of[oid]
                cand_f[row, lane] = f
                cand_a[row, lane] = a
                cand_b[row, lane] = b
            ev_slot_arr[row] = slot_of[op_id]
            row += 1
            free.append(slot_of.pop(op_id))
            free.sort(reverse=True)
        # info: op keeps its slot forever

    return EncodedHistory(
        init_state=init,
        ev_slot=ev_slot_arr,
        cand_slot=cand_slot,
        cand_f=cand_f,
        cand_a=cand_a,
        cand_b=cand_b,
        n_ops=len(ops),
        max_open=max_open,
    )


def round_up(n: int, multiple: int = 64) -> int:
    """Bucket sizes to multiples to bound recompilation."""
    if n <= 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def batch_encode(
    histories: Sequence[History],
    model: m.Model,
    slot_cap: int = DEFAULT_SLOT_CAP,
    event_bucket: int = 64,
) -> EncodedBatch:
    """Encode histories into one padded batch; unencodable ones land in
    ``fallback`` for the CPU oracle."""
    spec = spec_for(model)
    encoded: List[EncodedHistory] = []
    rows: List[int] = []
    fallback: List[int] = []
    for i, h in enumerate(histories):
        e = encode_history(h, model, slot_cap, spec) if spec else None
        if e is None:
            fallback.append(i)
        else:
            encoded.append(e)
            rows.append(i)

    if not encoded:
        return EncodedBatch(
            init_state=np.zeros((0,), np.int32),
            ev_slot=np.zeros((0, 0), np.int32),
            cand_slot=np.zeros((0, 0, slot_cap), np.int8),
            cand_f=np.zeros((0, 0, slot_cap), np.int8),
            cand_a=np.zeros((0, 0, slot_cap), np.int16),
            cand_b=np.zeros((0, 0, slot_cap), np.int16),
            fallback=fallback,
            row_history=rows,
        )

    E = round_up(max(e.ev_slot.shape[0] for e in encoded), event_bucket)
    B = len(encoded)
    # candidate lanes bucket to the batch's actual peak concurrency (every
    # slot id used is < max_open), not the slot cap — this shrinks the
    # frontier-expansion width and sort size, usually the dominant cost
    C = min(slot_cap, round_up(max(e.max_open for e in encoded), 4))

    init_state = np.zeros((B,), np.int32)
    ev_slot = np.full((B, E), -1, np.int32)
    cand_slot = np.full((B, E, C), -1, np.int8)
    cand_f = np.zeros((B, E, C), np.int8)
    cand_a = np.zeros((B, E, C), np.int16)
    cand_b = np.zeros((B, E, C), np.int16)
    for bi, e in enumerate(encoded):
        n = e.ev_slot.shape[0]
        init_state[bi] = e.init_state
        ev_slot[bi, :n] = e.ev_slot
        cand_slot[bi, :n] = e.cand_slot[:, :C]
        cand_f[bi, :n] = e.cand_f[:, :C]
        cand_a[bi, :n] = e.cand_a[:, :C]
        cand_b[bi, :n] = e.cand_b[:, :C]

    return EncodedBatch(
        init_state=init_state,
        ev_slot=ev_slot,
        cand_slot=cand_slot,
        cand_f=cand_f,
        cand_a=cand_a,
        cand_b=cand_b,
        fallback=fallback,
        row_history=rows,
    )
