"""Batched cycle detection and transactional screens on the accelerator.

Dependency graphs become dense matrices; transitive closure by log₂(N)
rounds of boolean matrix squaring — each round one batched matmul,
which XLA tiles straight onto the MXU in bfloat16.  Three kernel
families share that core:

- **has-cycle** (:func:`has_cycle_batch`): a graph is cyclic iff its
  closure has a true diagonal — the boolean screen the rw-register
  per-key version graphs ride (SURVEY.md §7 step 8).
- **SCC membership screens** (:func:`_screen_fn` members): per-vertex
  forward×backward closure intersection — ``member[v] = ∃j r[v,j] ∧
  r[j,v]`` — computed per relation-filter mask of the Elle classify
  ladder (``ww`` for G0, ``ww|wr`` for G1c, ``+rw`` for G2, the
  process/realtime-suffixed variants), so ``elle.cycles.classify``
  only pays CPU Tarjan + BFS witness search on graphs (and ladder
  rungs) the device has already proven cyclic *under that filter*.
- **nonadjacent-rw walk screens** (:func:`_screen_fn` walks): closure
  over the 2n×2n lifted product graph (state = vertex × last-edge-was-
  rw) decides exactly whether a closed walk with no two cyclically
  adjacent rw edges exists through each vertex — the screening
  question of the snapshot-isolation cycle test (Adya G-SI); no walk
  anywhere means ``find_nonadjacent_cycle`` would answer None for
  every SCC, so the whole rung is skippable.

**Plane packing** (the peak-FLOP closure work): one screen dispatch no
longer pays a separate log₂(n)-round closure per ladder filter — the F
filter masks expand on-device into a ``(B·F, n, n)`` plane stack and
the Q lifted walk queries into ``(B·Q, 2n, 2n)``, then ONE
:func:`_bool_closure` runs per shape family.  A 5-rung screen bucket
therefore lowers to ~log₂(n) large batched matmuls instead of
5·log₂(n) small ones (pinned by the jaxpr ``dot_general``-count
regression test); the per-plane arithmetic is untouched, so results
stay byte-identical to the per-mask lowering (``make kernels-smoke``).

**Closure modes**: :func:`_bool_closure` either runs the full fixed
log₂(n) squaring ladder (``"fixed"``, a ``lax.scan``) or stops at
fixpoint (``"earlyexit"``, a ``lax.while_loop`` — byte-identical by
construction since post-fixpoint squarings are the identity on the
saturated {0,1} lattice).  The mode is a tuned engine knob
(``JEPSEN_TPU_CYCLES_CLOSURE`` > calibration ``closure_mode`` >
:data:`DEFAULT_CLOSURE_MODE`; doc/tuning.md) because the convergence
check is a device-wide sync whose cost only pays off at large n.
Rounds actually run come back as a per-row output and settle into
``jepsen_cycles_closure_rounds_total`` / ``_rounds_saved_total``.

**Closure implementations**: orthogonal to the mode, the squaring
*arithmetic* is a second tuned knob (``JEPSEN_TPU_CYCLES_IMPL`` >
calibration ``closure_impl`` > :data:`DEFAULT_CLOSURE_IMPL`):
``"uint8"`` is the historical saturated-bfloat16 lowering over the
uint8 relation planes (1 live bit per lane), ``"bf16"`` keeps a
boolean carry and casts to bfloat16 only for each round's MXU matmul
(threshold > 0), and ``"packed32"`` bit-packs adjacency rows into
uint32 words — :func:`_pack_words`, ``W = ⌈n/32⌉`` — and squares in
the boolean semiring as an AND-broadcast + OR-reduce over word lanes
(no popcount: reachability only cares about any-bit).  All three run
the identical closure recurrence on the same {0,1} lattice, so
members/walks/rounds are byte-identical by construction (the
kernels-smoke and fuzz gates pin it); what changes is density — the
packed stack moves W/n ≈ 1/32 of the uint8 bytes, so the budget math
(:func:`cycles_max_dispatch`, the plane-weight ``frontier``) prices
packed rows 32× cheaper and a packed bucket legally dispatches ~32×
more rows per chunk (doc/checker-engines.md "Word-packed closure").

Since the engine-routing work these kernels no longer dispatch through
a private loop: every batch is planned into :class:`CyclePlan` /
:class:`ScreenPlan` buckets (power-of-two vertex buckets ×
filter-profile, stacked ``(B, n, n)`` uint8 relation matrices — see
:mod:`jepsen_tpu.elle.encode`) and submitted through the production
:class:`~jepsen_tpu.engine.execution.Executor`: the bounded
``DispatchWindow``, the per-chip ``safe_dispatch`` row budget
(:func:`cycles_max_dispatch`, the crash-avoidance analogue of
``FRONTIER_DISPATCH_BUDGET``), mesh ``shard_map`` dispatch, and the
``(kernel="cycles", E=n, C=0, F=plane-weight)`` rows of the tune cost
table all apply to Elle traffic exactly as they do to history
checking (``F`` is the packed plane weight — one n×n plane per filter
mask plus four per lifted query; :func:`jepsen_tpu.elle.encode.plane_weight`).
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import dense


def _bucket(n: int) -> int:
    """Pad sizes to powers of two (min 16) to bound recompiles."""
    return max(16, 1 << (n - 1).bit_length())


#: compiled-closure cache bound: buckets are powers of two ≥ 16
#: (2^4, 2^5, …), so 32 distinct entries cover every size to 2^35
#: vertices — far past anything dispatchable — while adversarial size
#: streams (one graph per power of two, forever) can no longer leak
#: compiled executables without limit the way ``maxsize=None`` did
CLOSURE_CACHE_SIZE = 32

#: per-dispatch footprint budget for the cycle kernels, in bf16 words
#: of live closure state — the crash-avoidance analogue of
#: ``wgl.FRONTIER_DISPATCH_BUDGET`` for the matrix-closure family.
#: The packed screen stack holds 2 n² words per row per filter plane
#: (adjacency + closure) and 8 n² per lifted nonadjacent plane (the
#: 2n×2n product graph); 16M words keeps every measured-good
#: elle_bench shape (B=4096 × n=16 … B=256 × n=256) dispatchable in
#: ≤2 chunks while bounding in-flight HBM the same way the engine
#: bounds history kernels — ``has_cycle_batch`` historically had NO
#: such cap, so a huge graph batch could exceed the per-chip budget
#: the engine enforces everywhere else (the PR's pinned regression).
CYCLES_DISPATCH_BUDGET = 16_777_216

#: largest row count per dispatch, shared ceiling with the engine
DEFAULT_CYCLES_MAX_DISPATCH = 16384

#: closure-iteration lowering when neither the environment nor a
#: calibration picks one: the fixed log₂(n) scan — the earlyexit
#: while_loop's fixpoint test is a device-wide sync per round, a cost
#: the tuner must measure before opting in (doc/tuning.md)
DEFAULT_CLOSURE_MODE = "fixed"

_VALID_CLOSURE_MODES = ("fixed", "earlyexit")

#: closure squaring arithmetic when neither the environment nor a
#: calibration picks one: the historical saturated-bf16 lowering over
#: uint8 planes — packed32's unpack/repack and bf16's per-round cast
#: are real per-round costs the tuner must measure before opting in
DEFAULT_CLOSURE_IMPL = "uint8"

_VALID_CLOSURE_IMPLS = ("uint8", "packed32", "bf16")


def closure_mode() -> str:
    """Resolved closure-iteration mode for the cycle kernels:
    ``JEPSEN_TPU_CYCLES_CLOSURE`` > active calibration
    (``closure_mode`` param — ``jepsen_tpu tune`` measures the
    fixed/earlyexit gap per chip) > :data:`DEFAULT_CLOSURE_MODE`.
    Part of every closure-kernel cache key, so flipping it can never
    serve a stale lowering."""
    from ..tune import artifact as _cal

    def parse(v: str):
        v = v.strip().lower()
        return v if v in _VALID_CLOSURE_MODES else None

    return _cal.resolve_knob(
        "JEPSEN_TPU_CYCLES_CLOSURE",
        parse,
        lambda cal: cal.closure_mode(),
        DEFAULT_CLOSURE_MODE,
    )


def closure_impl() -> str:
    """Resolved closure-squaring arithmetic for the cycle kernels:
    ``JEPSEN_TPU_CYCLES_IMPL`` > active calibration (``closure_impl``
    param — ``jepsen_tpu tune`` measures the uint8/packed32/bf16 gap
    per chip and shape) > :data:`DEFAULT_CLOSURE_IMPL`.  Part of every
    closure-kernel cache key (and of the mesh ``shard_fn`` key), so
    flipping it can never serve a stale lowering."""
    from ..tune import artifact as _cal

    def parse(v: str):
        v = v.strip().lower()
        return v if v in _VALID_CLOSURE_IMPLS else None

    return _cal.resolve_knob(
        "JEPSEN_TPU_CYCLES_IMPL",
        parse,
        lambda cal: cal.closure_impl(),
        DEFAULT_CLOSURE_IMPL,
    )


def _pack_words(adj):
    """Device word-packing: ``(..., n) bool → (..., W) uint32`` with
    lane ``j`` at word ``j // 32``, bit ``j % 32`` — bit-for-bit the
    little-order layout of the host
    :func:`jepsen_tpu.ops.dense.pack_words_np` (the round-trip
    property tests pin the two equal).  The weighted sum over 32-lane
    groups is exact: distinct powers of two never carry."""
    n = adj.shape[-1]
    W = dense.word_count(n)
    lanes = adj.astype(jnp.uint32)
    pad = W * dense.WORD_LANES - n
    if pad:
        lanes = jnp.pad(lanes, [(0, 0)] * (lanes.ndim - 1) + [(0, pad)])
    weights = jnp.uint32(1) << jnp.arange(
        dense.WORD_LANES, dtype=jnp.uint32
    )
    return jnp.sum(
        lanes.reshape(lanes.shape[:-1] + (W, dense.WORD_LANES)) * weights,
        axis=-1,
        dtype=jnp.uint32,
    )


def _unpack_words(words, n: int):
    """Inverse of :func:`_pack_words`: ``(..., W) uint32 → (..., n)``
    bool; lanes past ``n`` are word-floor padding and are dropped."""
    shifts = jnp.arange(dense.WORD_LANES, dtype=jnp.uint32)
    lanes = (words[..., None] >> shifts) & jnp.uint32(1)
    return lanes.reshape(words.shape[:-1] + (-1,))[..., :n] > 0


def closure_rounds(n: int) -> int:
    """Squaring rounds that guarantee full transitive closure of an
    n-vertex graph (path length doubles per round)."""
    return max(1, math.ceil(math.log2(max(2, n))))


def cycles_max_dispatch(
    n: int,
    n_filters: int = 1,
    n_lifted: int = 0,
    max_dispatch: Optional[int] = None,
    impl: str = "uint8",
) -> int:
    """Largest safe per-dispatch row count for a cycle kernel over
    ``n``-vertex graphs whose packed stack carries ``n_filters``
    membership planes and ``n_lifted`` lifted (2n×2n) walk planes per
    row.  Returns 0 when even a single row exceeds the budget —
    callers must route those graphs to the CPU path instead of
    dispatching.

    ``impl="packed32"`` prices each plane at its word-packed footprint
    — ``n·W`` uint32 words (``W = ⌈n/32⌉``, the lifted planes at
    ``2n·⌈2n/32⌉``) instead of ``n²`` lanes, i.e. W/n ≈ 1/32 of the
    uint8 footprint — so a packed bucket legally dispatches ~32× more
    rows per chunk under the same :data:`CYCLES_DISPATCH_BUDGET`
    (``"bf16"`` keeps the uint8 pricing: its carry is still one lane
    per vertex pair)."""
    if max_dispatch is None:
        max_dispatch = DEFAULT_CYCLES_MAX_DISPATCH
    if impl == "packed32":
        per_row = (2 * n * dense.word_count(n) * max(1, n_filters)
                   + 2 * (2 * n) * dense.word_count(2 * n) * n_lifted)
    else:
        per_row = n * n * (2 * max(1, n_filters) + 8 * n_lifted)
    if per_row > CYCLES_DISPATCH_BUDGET:
        return 0
    return max(1, min(max_dispatch, CYCLES_DISPATCH_BUDGET // per_row))


def _bool_closure(adj, mode: str = "fixed", impl: str = "uint8"):
    """Transitive (≥1 step) boolean closure by rounds of matrix
    squaring; shape-static, trace-safe.  Returns
    ``(closure bool, rounds-run int32 scalar)``.

    ``mode="fixed"`` always runs the full log₂(n) ladder as a
    ``lax.scan``; ``mode="earlyexit"`` wraps the same squaring step in
    a ``lax.while_loop`` that stops once a round changes nothing.
    Byte-identical by construction: the squaring step is monotone and
    idempotent at fixpoint on the saturated {0,1} values, so the extra
    rounds the fixed ladder runs past convergence are the identity.

    ``impl`` picks the squaring arithmetic (module docstring "Closure
    implementations"): ``"uint8"`` the historical saturated-bf16
    carry, ``"bf16"`` a boolean carry with a per-round bf16 MXU matmul
    thresholded > 0, ``"packed32"`` a uint32 word carry
    (:func:`_pack_words`) squared in the boolean semiring — one round
    is an AND-broadcast of row lanes against the word rows plus an
    OR-reduce over the intermediate-vertex axis, no popcount.  All
    three run the same recurrence ``r ← r ∪ r·r`` on the same lattice,
    so closures AND fixpoint round counts are byte-identical across
    impls (the fuzz gate pins diameters 1..n)."""
    n = adj.shape[-1]
    rounds = closure_rounds(n)

    if impl == "packed32":
        def square(rc):  # rc: (..., n, W) uint32 word rows
            lanes = _unpack_words(rc, n)  # (..., n, n): i reaches k?
            hops = jnp.bitwise_or.reduce(
                jnp.where(lanes[..., None], rc[..., None, :, :],
                          jnp.uint32(0)),
                axis=-2,
            )
            return rc | hops

        rw = _pack_words(adj)
        if mode == "earlyexit":
            def cond(carry):
                _, changed, i = carry
                return changed & (i < rounds)

            def body(carry):
                rc, _, i = carry
                rr = square(rc)
                return rr, jnp.any(rr != rc), i + jnp.int32(1)

            rw, _, used = jax.lax.while_loop(
                cond, body, (rw, jnp.bool_(True), jnp.int32(0))
            )
            return _unpack_words(rw, n), used

        def step(rc, _):
            return square(rc), None

        rw, _ = jax.lax.scan(step, rw, None, length=rounds)
        return _unpack_words(rw, n), jnp.int32(rounds)

    if impl == "bf16":
        def square_b(rb):  # rb: (..., n, n) bool carry
            f = rb.astype(jnp.bfloat16)
            return rb | (jnp.matmul(f, f) > 0)

        rb = adj > 0 if adj.dtype != jnp.bool_ else adj
        if mode == "earlyexit":
            def cond(carry):
                _, changed, i = carry
                return changed & (i < rounds)

            def body(carry):
                rc, _, i = carry
                rr = square_b(rc)
                return rr, jnp.any(rr != rc), i + jnp.int32(1)

            rb, _, used = jax.lax.while_loop(
                cond, body, (rb, jnp.bool_(True), jnp.int32(0))
            )
            return rb, used

        def step(rc, _):
            return square_b(rc), None

        rb, _ = jax.lax.scan(step, rb, None, length=rounds)
        return rb, jnp.int32(rounds)

    r = adj.astype(jnp.bfloat16)

    if mode == "earlyexit":
        def cond(carry):
            _, changed, i = carry
            return changed & (i < rounds)

        def body(carry):
            rc, _, i = carry
            rr = jnp.clip(rc + jnp.matmul(rc, rc), 0.0, 1.0)
            return rr, jnp.any(rr != rc), i + jnp.int32(1)

        r, _, used = jax.lax.while_loop(
            cond, body, (r, jnp.bool_(True), jnp.int32(0))
        )
        return r > 0.0, used

    def step(rc, _):
        # r ∪ r·r, saturated to {0,1}; stays in bfloat16 for the MXU
        rr = jnp.clip(rc + jnp.matmul(rc, rc), 0.0, 1.0)
        return rr, None

    r, _ = jax.lax.scan(step, r, None, length=rounds)
    return r > 0.0, jnp.int32(rounds)


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _closure_fn(n: int, mode: str = "fixed", impl: str = "uint8"):  # jt: allow[budget-missing-cap] — capped by the engine-facing wrapper _cyclic_fn
    @jax.jit
    def has_cycle(adj):  # adj: (B, n, n) bool
        r, used = _bool_closure(adj, mode, impl)
        diag = jnp.diagonal(r, axis1=-2, axis2=-1)
        flags = jnp.any(diag, axis=-1)
        return flags, jnp.broadcast_to(used, flags.shape)

    has_cycle.closure_mode = mode  # rides the mesh shard_fn cache key
    has_cycle.closure_impl = impl
    return has_cycle


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _cyclic_fn(n: int, mode: str = "fixed", impl: str = "uint8"):  # jt: jaxpr(dot_generals<=log2n+2, dtype[uint8]=bfloat16, dtype[packed32]=uint32, dtype[bf16]=bool, budget=0.2..0.6)
    """Engine-facing variant of :func:`_closure_fn`: tuple outputs (the
    execution layer materializes output *tuples* — flags plus the
    per-row rounds-run evidence) and a ``safe_dispatch`` row cap like
    every other engine kernel."""
    base = _closure_fn(n, mode, impl)
    fn = jax.jit(lambda adj: base(adj))
    fn.safe_dispatch = cycles_max_dispatch(n, 1, 0, impl=impl)
    fn.closure_mode = mode  # both knobs ride the mesh shard_fn cache key
    fn.closure_impl = impl
    return fn


def _screen_fn(n: int, masks: Tuple[int, ...],
               nonadj: Tuple[Tuple[int, int], ...]):
    """The production transactional-screen kernel: the packed lowering
    at the resolved :func:`closure_mode` / :func:`closure_impl` (see
    :func:`_screen_fn_variant` for the cache and the per-mask
    reference lowering)."""
    return _screen_fn_variant(n, masks, nonadj, True, closure_mode(),
                              closure_impl())


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _screen_fn_variant(n: int, masks: Tuple[int, ...],  # jt: jaxpr(dot_generals<=2*log2n+3, dtype[uint8]=bfloat16, dtype[packed32]=uint32, dtype[bf16]=bool, budget=0.1..0.35)
                       nonadj: Tuple[Tuple[int, int], ...],
                       packed: bool, mode: str, impl: str = "uint8"):
    """The transactional screen kernel for ``n``-vertex graphs: per
    relation-filter SCC membership masks plus per-(want, rest) lifted
    nonadjacent-walk masks, all in ONE dispatch over a ``(B, n, n)``
    uint8 relation-bit batch (bit assignment:
    ``jepsen_tpu.elle.encode.REL_BITS``).  Returns
    ``(members: (B, F, n) bool, walks: (B, Q, n) bool,
    rounds: (B,) int32)`` — rounds is the closure-squaring count the
    dispatch actually ran (broadcast per row; settle turns it into the
    rounds/rounds-saved counters).

    ``packed=True`` (production) folds the F filter planes into the
    batch axis as a ``(B·F, n, n)`` stack and the Q lifted queries as
    ``(B·Q, 2n, 2n)``, running ONE :func:`_bool_closure` per shape
    family — ~log₂(n) large batched matmuls for the whole ladder.
    ``packed=False`` keeps the historical per-mask loop (F + Q small
    closures) as the differential reference the equality gates compare
    against; both produce byte-identical members/walks because batched
    matmul is independent per batch element.  ``impl`` selects the
    closure squaring arithmetic (:func:`closure_impl`); it only
    touches :func:`_bool_closure` internals, so every
    (packed, mode, impl) combination screens identically."""
    F, Q = len(masks), len(nonadj)

    @jax.jit
    def screen(rel):  # rel: (B, n, n) uint8
        B = rel.shape[0]
        used = jnp.int32(0)
        if packed:
            if masks:
                marr = jnp.asarray(masks, jnp.uint8)
                planes = (rel[:, None] & marr[None, :, None, None]) > 0
                c, um = _bool_closure(planes.reshape(B * F, n, n), mode, impl)
                c = c.reshape(B, F, n, n)
                # v sits on a cycle of this filtered subgraph iff some
                # j is reachable forward AND backward (j = v covers
                # self loops, which the graph layer already drops)
                m = jnp.any(c & jnp.swapaxes(c, -1, -2), axis=-1)
                used = used + um
            else:
                m = jnp.zeros((B, 0, n), bool)
            if nonadj:
                wants = jnp.asarray([wq for wq, _ in nonadj], jnp.uint8)
                rests = jnp.asarray([rq for _, rq in nonadj], jnp.uint8)
                aw = (rel[:, None] & wants[None, :, None, None]) > 0
                ar = (rel[:, None] & rests[None, :, None, None]) > 0
                # lifted product graph over (vertex, last-edge-was-
                # want): a want edge is only traversable from state 0
                # (previous edge not want) and lands in state 1; rest
                # edges land in state 0 from either.  A closed walk
                # u →want→ w →…→ (u, state 0) is exactly a walk whose
                # want edges are never cyclically adjacent (the
                # closing rest edge precedes the forced first want
                # edge in the rotation).
                top = jnp.concatenate([ar, aw], axis=-1)
                bot = jnp.concatenate([ar, jnp.zeros_like(ar)], axis=-1)
                lifted = jnp.concatenate([top, bot], axis=-2)
                c, uw = _bool_closure(
                    lifted.reshape(B * Q, 2 * n, 2 * n), mode, impl
                )
                c = c.reshape(B, Q, 2 * n, 2 * n)
                reach = c[:, :, n:, :n]  # from (·, 1) to (·, 0), ≥1 step
                w = jnp.any(aw & jnp.swapaxes(reach, -1, -2), axis=-1)
                used = used + uw
            else:
                w = jnp.zeros((B, 0, n), bool)
        else:
            members = []
            for mask in masks:
                r, u = _bool_closure((rel & jnp.uint8(mask)) > 0, mode, impl)
                members.append(
                    jnp.any(r & jnp.swapaxes(r, -1, -2), axis=-1)
                )
                used = used + u
            walks = []
            for want, rest in nonadj:
                aw = (rel & jnp.uint8(want)) > 0
                ar = (rel & jnp.uint8(rest)) > 0
                top = jnp.concatenate([ar, aw], axis=-1)
                bot = jnp.concatenate([ar, jnp.zeros_like(ar)], axis=-1)
                c, u = _bool_closure(
                    jnp.concatenate([top, bot], axis=-2), mode, impl
                )
                reach = c[:, n:, :n]
                walks.append(
                    jnp.any(aw & jnp.swapaxes(reach, -1, -2), axis=-1)
                )
                used = used + u
            m = (jnp.stack(members, axis=1) if members
                 else jnp.zeros((B, 0, n), bool))
            w = (jnp.stack(walks, axis=1) if walks
                 else jnp.zeros((B, 0, n), bool))
        rounds = jnp.broadcast_to(used, (B,)).astype(jnp.int32)
        return m, w, rounds

    screen.safe_dispatch = cycles_max_dispatch(n, F, Q, impl=impl)
    screen.closure_mode = mode  # both knobs ride the mesh shard_fn cache key
    screen.closure_impl = impl
    return screen


def _run_elle(fn, mesh, rel, n_out: int):
    """Dispatch one stacked relation batch, sharded when a mesh is
    resident (the executor hands us device-multiple row counts)."""
    if mesh is None:
        return fn(jnp.asarray(rel))
    from ..parallel import mesh as mesh_mod

    return mesh_mod.sharded_elle(fn, mesh, rel, n_out)


def _settle_closure_obs(plan, rounds: np.ndarray, n_live: int) -> None:
    """Record one settled dispatch's closure evidence: rounds actually
    run vs the plan's full ladder (the earlyexit savings — identically
    zero under ``"fixed"``), and the packed-plane batch occupancy
    (live planes / dispatched planes; padding rows are the only dead
    planes, so the ratio equals live rows / padded rows)."""
    from .. import obs

    if not obs.enabled() or rounds.size == 0:
        return
    live = rounds[: max(1, n_live)]
    used = int(live.max())
    obs.count("jepsen_cycles_closure_rounds_total", used,
              mode=plan.closure_mode)
    obs.count("jepsen_cycles_closure_rounds_saved_total",
              max(0, plan.rounds_full - used), mode=plan.closure_mode)
    obs.gauge_set("jepsen_cycles_packed_plane_occupancy",
                  n_live / rounds.shape[0])
    # which squaring arithmetic actually dispatched (the tuner settles
    # the winner per shape; this is the evidence it actually ran)
    obs.count("jepsen_cycles_impl_total", 1, impl=plan.closure_impl)
    if plan.closure_impl == "packed32":
        # live vertex lanes / carried word lanes: 1.0 on word-floored
        # buckets, < 1 only when a caller bypasses encode.graph_bucket
        obs.gauge_set(
            "jepsen_cycles_word_lane_occupancy",
            plan.E / (dense.word_count(plan.E) * dense.WORD_LANES),
        )
    # estimated MXU work this dispatch actually ran: each round squares
    # every live row's packed plane stack (~2·E³ flops per E-plane;
    # the lifted 2E-planes ride the plan's frontier weight), so the
    # bench can report a closure FLOP-rate without re-deriving shapes
    obs.count("jepsen_cycles_closure_flops_total",
              int(2.0 * float(plan.E) ** 3 * plan.frontier * used
                  * max(1, n_live)),
              mode=plan.closure_mode)


class ScreenResult:
    """One graph's device screens, bucket-width: ``members[mask]`` and
    ``walks[(want, rest)]`` are per-vertex bool arrays over the padded
    bucket (callers slice by their own vertex count/order)."""

    __slots__ = ("members", "walks")

    def __init__(self, members, walks):
        self.members = members
        self.walks = walks


class CyclePlan:
    """Executor-conforming plan for the boolean has-cycle screen: one
    uint8/bool adjacency input, one cyclic-flag output per row (plus
    the rounds-run evidence).  Row tokens are ``(sink, idx)`` — settle
    writes ``sink[idx]``."""

    kernel = "cycles"
    #: neutral pad rows are all-zero relation matrices — edge-free,
    #: hence acyclic, hence invisible to every screen (the executor
    #: pads with these; the plan owns the convention, never borrowing
    #: the history kernels' 6-array fills)
    pad_fills = (0,)
    __slots__ = ("fn", "disp", "E", "C", "frontier", "closure_mode",
                 "closure_impl", "rounds_full")

    def __init__(self, n: int, max_dispatch: Optional[int] = None):
        mode = closure_mode()
        impl = closure_impl()
        self.closure_mode = mode
        self.closure_impl = impl
        self.fn = _cyclic_fn(n, mode, impl)
        self.E, self.C, self.frontier = n, 0, 1
        self.rounds_full = closure_rounds(n)
        self.disp = cycles_max_dispatch(n, 1, 0, max_dispatch, impl)

    def run_rows(self, mesh, arrays):
        return _run_elle(self.fn, mesh, arrays[0], 2)

    def settle_rows(self, rows, mat, n_live: int) -> None:
        flags = np.asarray(mat[0])[:n_live]
        _settle_closure_obs(self, np.asarray(mat[1]), n_live)
        for row, (sink, idx) in enumerate(rows):
            sink[idx] = bool(flags[row])


class ScreenPlan:
    """Executor-conforming plan for the full transactional screen of
    one (vertex bucket, filter profile): settle hands each row token's
    sink a :class:`ScreenResult` keyed by the profile's masks.  The
    cost-table/proxy ``frontier`` axis is the packed plane weight —
    the batch-axis expansion factor of the one-closure lowering."""

    kernel = "cycles"
    pad_fills = (0,)  # see CyclePlan.pad_fills
    __slots__ = ("fn", "disp", "E", "C", "frontier", "masks", "nonadj",
                 "closure_mode", "closure_impl", "rounds_full")

    def __init__(self, n: int, masks: Tuple[int, ...],
                 nonadj: Tuple[Tuple[int, int], ...],
                 max_dispatch: Optional[int] = None):
        from ..elle import encode as encode_mod

        self.masks = tuple(masks)
        self.nonadj = tuple(nonadj)
        mode = closure_mode()
        impl = closure_impl()
        self.closure_mode = mode
        self.closure_impl = impl
        self.fn = _screen_fn_variant(n, self.masks, self.nonadj, True,
                                     mode, impl)
        self.E, self.C = n, 0
        self.frontier = encode_mod.plane_weight(self.masks, self.nonadj,
                                                impl)
        self.rounds_full = (
            (closure_rounds(n) if self.masks else 0)
            + (closure_rounds(2 * n) if self.nonadj else 0)
        )
        self.disp = cycles_max_dispatch(
            n, len(self.masks), len(self.nonadj), max_dispatch, impl
        )

    def run_rows(self, mesh, arrays):
        return _run_elle(self.fn, mesh, arrays[0], 3)

    def settle_rows(self, rows, mat, n_live: int) -> None:
        members = np.asarray(mat[0])[:n_live]
        walks = np.asarray(mat[1])[:n_live]
        _settle_closure_obs(self, np.asarray(mat[2]), n_live)
        for row, (sink, idx) in enumerate(rows):
            sink[idx] = ScreenResult(
                {m: members[row, f] for f, m in enumerate(self.masks)},
                {q: walks[row, w] for w, q in enumerate(self.nonadj)},
            )


def _submit_elle_buckets(planned, window, executor):
    """Dispatch planned elle buckets through the production engine:
    largest estimated cost first (the same scheduling hook history
    buckets use), bounded window, per-chip budget, mesh — then drain
    and record the graphs-per-dispatch evidence."""
    from .. import obs
    from ..engine import execution, planning

    ex = executor if executor is not None else execution.Executor(window)
    planned.sort(key=planning.estimated_cost, reverse=True)
    sub0 = ex.submitted
    total_rows = 0
    for pb in planned:
        total_rows += len(pb.rows)
        ex.submit(pb)
    ex.drain()
    n_disp = ex.submitted - sub0
    if obs.enabled() and n_disp:
        obs.registry().histogram(
            "jepsen_elle_graphs_per_dispatch",
            buckets=(1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0),
        ).observe(total_rows / n_disp)


def _np_bool_closure(adj: np.ndarray) -> np.ndarray:
    """Vectorized host transitive closure: numpy boolean matmul
    squaring over an arbitrary leading batch shape
    (``(..., n, n) → (..., n, n)``) — the CPU mirror of
    :func:`_bool_closure`."""
    r = np.asarray(adj, dtype=bool)
    for _ in range(closure_rounds(r.shape[-1])):
        r = r | (r @ r)
    return r


def _np_has_cycle(adj: np.ndarray):
    """Host boolean-closure fallback for graphs past the dispatch
    budget (the engine must never dispatch a shape it cannot cap).
    Accepts one ``(n, n)`` matrix (→ bool) or a stacked ``(B, n, n)``
    batch (→ ``(B,)`` bool) — the batch form is one vectorized
    matmul-squaring ladder, not a per-matrix loop."""
    r = _np_bool_closure(adj)
    any_diag = np.diagonal(r, axis1=-2, axis2=-1).any(axis=-1)
    return any_diag if any_diag.ndim else bool(any_diag)


def _np_screen(rel: np.ndarray, masks: Sequence[int],
               nonadj: Sequence[Tuple[int, int]]):
    """Pure-numpy reference of the screen kernel: ``(B, n, n)`` uint8
    relation batch → ``(members (B, F, n), walks (B, Q, n))`` — the
    CPU parity oracle the packed/per-mask equality gates compare
    against (tests and ``make kernels-smoke``)."""
    rel = np.asarray(rel, np.uint8)
    B, n = rel.shape[0], rel.shape[-1]
    members = np.zeros((B, len(masks), n), bool)
    for f, mask in enumerate(masks):
        r = _np_bool_closure((rel & np.uint8(mask)) > 0)
        members[:, f] = (r & np.swapaxes(r, -1, -2)).any(axis=-1)
    walks = np.zeros((B, len(nonadj), n), bool)
    for q, (want, rest) in enumerate(nonadj):
        aw = (rel & np.uint8(want)) > 0
        ar = (rel & np.uint8(rest)) > 0
        top = np.concatenate([ar, aw], axis=-1)
        bot = np.concatenate([ar, np.zeros_like(ar)], axis=-1)
        c = _np_bool_closure(np.concatenate([top, bot], axis=-2))
        reach = c[:, n:, :n]
        walks[:, q] = (aw & np.swapaxes(reach, -1, -2)).any(axis=-1)
    return members, walks


#: host-fallback stacking bound, in words of resident state:
#: over-budget buckets batch through the word-packed numpy closure in
#: chunks of this many uint32 words so the fallback never materializes
#: an unbounded stack for the very shapes that were too big for the
#: device.  Historically the resident stack was (B, n, n) bool — one
#: word per LANE — so CPU-oracle parity on large n blew this budget
#: 32× earlier than the device path, whose budget counts packed words
#: (the PR's pinned n=1024 regression)
_NP_STACK_BUDGET = 1 << 26


def _np_chunk_rows(n: int) -> int:
    """Host-fallback chunk size for ``n``-vertex graphs: the resident
    stack is word-packed (``n·W`` uint32 words per row,
    :func:`jepsen_tpu.ops.dense.pack_words_np`) so the budget divides
    by ``n·W`` instead of the ``n²`` bools the unpacked stacking paid
    — 32× more rows per chunk at n = 1024."""
    return max(1, _NP_STACK_BUDGET // (n * dense.word_count(n)))


def _np_packed_closure(rw: np.ndarray, n: int) -> np.ndarray:
    """Word-packed host transitive closure: ``(B, n, W) uint32 →
    (B, n, W)`` closed, ``n`` a multiple of 32 (callers word-floor the
    pad; all-zero padding rows are edge-free, hence inert).  One
    squaring round ORs intermediate row ``k``'s word row into every
    row ``i`` whose packed lanes reach ``k``, grouped by bit position
    ``j`` (the intermediates ``k = 32·w + j`` live at one fixed bit of
    every word), so the transient is ``(B, n, W, W)`` uint32 — never
    the ``(B, n, n)`` bool plane the unpacked host closure
    materializes.  Fixpoint rounds short-circuit: the host path
    reports no rounds evidence, so stopping early is pure savings."""
    rw = np.array(rw, np.uint32, copy=True)
    for _ in range(closure_rounds(n)):
        sq = np.zeros_like(rw)
        for j in range(dense.WORD_LANES):
            # pj[b, i, w]: row i reaches intermediate k = 32·w + j?
            pj = ((rw >> np.uint32(j)) & np.uint32(1)).astype(bool)
            rj = rw[:, j::dense.WORD_LANES, :]  # (B, W, W): those rows
            sq |= np.bitwise_or.reduce(
                np.where(pj[..., None], rj[:, None, :, :],
                         np.uint32(0)),
                axis=2,
            )
        nxt = rw | sq
        if np.array_equal(nxt, rw):
            break
        rw = nxt
    return rw


def _np_packed_has_cycle(rw: np.ndarray, n: int) -> np.ndarray:
    """Cyclic flags for a word-packed ``(B, n, W)`` stack: closure in
    sub-blocks whose ``(blk, n, W, W)`` squaring transient stays under
    :data:`_NP_STACK_BUDGET`, then the packed diagonal test (bit
    ``i % 32`` of word ``i // 32`` on row ``i``)."""
    B, W = rw.shape[0], rw.shape[-1]
    blk = max(1, _NP_STACK_BUDGET // (n * W * W))
    flags = np.zeros(B, bool)
    idx = np.arange(n)
    shifts = (idx % dense.WORD_LANES).astype(np.uint32)
    for lo in range(0, B, blk):
        closed = _np_packed_closure(rw[lo:lo + blk], n)
        diag = (closed[:, idx, idx // dense.WORD_LANES] >> shifts) & 1
        flags[lo:lo + blk] = diag.any(axis=-1)
    return flags


def has_cycle_batch(
    mats: Sequence[np.ndarray],
    window: Optional[int] = None,
    executor=None,
    max_dispatch: Optional[int] = None,
) -> np.ndarray:
    """Which of these adjacency matrices contain a cycle?  Matrices
    bucket by padded size so one compile covers many shapes, and the
    buckets dispatch through the production engine
    :class:`~jepsen_tpu.engine.execution.Executor` — the bounded
    window (``window=None`` takes the engine default; 1 = the old
    strictly serial dispatch-sync loop), the per-chip
    :func:`cycles_max_dispatch` row budget (a huge batch chunks
    instead of exceeding the HBM bound the engine enforces for every
    other kernel), and mesh sharding when a slice is resident.
    ``executor=`` lets a resident owner (the serve daemon, smoke
    checks) supply its own."""
    from ..engine import planning

    out = np.zeros(len(mats), dtype=bool)
    by_bucket: dict = {}
    order: List[int] = []
    for i, m in enumerate(mats):
        n = _bucket(max(1, m.shape[0]))
        if n not in by_bucket:
            by_bucket[n] = []
            order.append(n)
        by_bucket[n].append(i)

    planned = []
    for n in order:
        idxs = by_bucket[n]
        plan = CyclePlan(n, max_dispatch)
        if plan.disp == 0:
            # even one row of this vertex bucket busts the dispatch
            # budget: decide on the host instead of crashing a worker
            # — batched through the word-packed numpy closure, chunked
            # in uint32 words so the resident stack is priced like the
            # device path (32× more rows per chunk than bool stacking)
            nw = dense.word_count(n) * dense.WORD_LANES  # word floor
            chunk = _np_chunk_rows(nw)
            for lo in range(0, len(idxs), chunk):
                part = idxs[lo:lo + chunk]
                stack = np.zeros(
                    (len(part), nw, dense.word_count(nw)), np.uint32
                )
                for row, i in enumerate(part):
                    m = np.asarray(mats[i], dtype=bool)
                    plane = np.zeros((nw, nw), bool)
                    plane[: m.shape[0], : m.shape[1]] = m
                    stack[row] = dense.pack_words_np(plane)
                out[part] = _np_packed_has_cycle(stack, nw)
            continue
        batch = np.zeros((len(idxs), n, n), dtype=np.uint8)
        for row, i in enumerate(idxs):
            m = mats[i]
            batch[row, : m.shape[0], : m.shape[1]] = np.asarray(
                m, dtype=bool
            ).astype(np.uint8)
        rows = [(out, i) for i in idxs]
        planned.append(planning.PlannedBucket(n, plan, (batch,), rows))
    if planned:
        _submit_elle_buckets(planned, window, executor)
    return out


def screen_graphs(
    encs: Sequence,
    window: Optional[int] = None,
    executor=None,
    max_dispatch: Optional[int] = None,
) -> List[Optional[ScreenResult]]:
    """Run the full transactional screens for a batch of encoded
    graphs (:class:`jepsen_tpu.elle.encode.EncodedGraph`): bucket by
    (vertex bucket, canonical filter profile), stack each bucket into
    one ``(B, n, n)`` relation batch, and dispatch through the engine
    Executor.  Graphs whose profile exceeds the dispatch budget (cap
    0) come back ``None`` — the caller keeps those on the CPU path."""
    from ..elle import encode as encode_mod
    from ..engine import planning

    results: List[Optional[ScreenResult]] = [None] * len(encs)
    buckets, order = encode_mod.bucket_graphs(encs)
    planned = []
    for key in order:
        n, masks, nonadj = key
        plan = ScreenPlan(n, masks, nonadj, max_dispatch)
        if plan.disp == 0:
            continue  # beyond the budget even one row at a time: CPU
        idxs = buckets[key]
        batch = encode_mod.stack_rel([encs[i] for i in idxs], n)
        rows = [(results, i) for i in idxs]
        planned.append(planning.PlannedBucket(key, plan, (batch,), rows))
    if planned:
        _submit_elle_buckets(planned, window, executor)
    return results


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _reach_fn(n: int):  # jt: allow[budget-missing-cap] — single-matrix (B=1) convenience kernel, see reachability
    @jax.jit
    def close(a):
        r, _ = _bool_closure(a)
        return r

    return close


def reachability(adj: np.ndarray) -> np.ndarray:
    """Full boolean transitive closure of one adjacency matrix (device)."""
    n = _bucket(adj.shape[0])
    padded = np.zeros((n, n), dtype=bool)
    padded[: adj.shape[0], : adj.shape[1]] = adj
    # single-matrix convenience API: the caller wants the closure NOW,
    # there is no batch to overlap with — sanctioned inline sync
    return np.asarray(_reach_fn(n)(jnp.asarray(padded)))[  # jt: allow[trace-sync, budget-direct-dispatch] — B=1, no batch to chunk
        : adj.shape[0], : adj.shape[1]
    ]
