"""Batched cycle detection on the accelerator.

Dependency graphs become dense boolean adjacency matrices; transitive
closure by log₂(N) rounds of boolean matrix squaring — each round one
batched matmul, which XLA tiles straight onto the MXU in bfloat16 — and
a graph is cyclic iff its closure has a true diagonal.  This is the
screening kernel for the Elle-equivalent checker (SURVEY.md §7 step 8):
thousands of per-key graphs are screened in one dispatch and only the
cyclic ones get a CPU witness search.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp


def _bucket(n: int) -> int:
    """Pad sizes to powers of two (min 16) to bound recompiles."""
    return max(16, 1 << (n - 1).bit_length())


#: compiled-closure cache bound: buckets are powers of two ≥ 16
#: (2^4, 2^5, …), so 32 distinct entries cover every size to 2^35
#: vertices — far past anything dispatchable — while adversarial size
#: streams (one graph per power of two, forever) can no longer leak
#: compiled executables without limit the way ``maxsize=None`` did
CLOSURE_CACHE_SIZE = 32


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _closure_fn(n: int):
    rounds = max(1, math.ceil(math.log2(n)))

    @jax.jit
    def has_cycle(adj):  # adj: (B, n, n) bool
        r = adj.astype(jnp.bfloat16)

        def step(r, _):
            # r ∪ r·r, saturated to {0,1}; stays in bfloat16 for the MXU
            rr = jnp.clip(r + jnp.matmul(r, r), 0.0, 1.0)
            return rr, None

        r, _ = jax.lax.scan(step, r, None, length=rounds)
        diag = jnp.diagonal(r, axis1=-2, axis2=-1)
        return jnp.any(diag > 0.0, axis=-1)

    return has_cycle


def has_cycle_batch(
    mats: Sequence[np.ndarray], window: Optional[int] = None
) -> np.ndarray:
    """Which of these adjacency matrices contain a cycle?  Matrices are
    bucketed by padded size so one compile covers many shapes, and the
    per-bucket dispatches ride the engine's bounded
    :class:`~jepsen_tpu.engine.pipeline.DispatchWindow`: bucket *k+1*
    packs on the host while bucket *k*'s closure computes, syncing only
    when the window fills (``window=None`` takes the engine default;
    1 = the old strictly serial dispatch-sync loop)."""
    from ..engine import DispatchWindow

    out = np.zeros(len(mats), dtype=bool)
    by_bucket: dict = {}
    for i, m in enumerate(mats):
        by_bucket.setdefault(_bucket(m.shape[0]), []).append(i)

    def settle(idxs, verdicts, _t):
        for row, i in enumerate(idxs):
            out[i] = bool(verdicts[row])

    win = DispatchWindow(window, on_retire=settle)
    for n, idxs in by_bucket.items():
        batch = np.zeros((len(idxs), n, n), dtype=bool)
        for row, i in enumerate(idxs):
            m = mats[i]
            batch[row, : m.shape[0], : m.shape[1]] = m
        win.submit(
            tuple(idxs),
            lambda n=n, batch=batch: _closure_fn(n)(jnp.asarray(batch)),
            attrs={"engine": "elle-screen", "rows": len(idxs)},
        )
    win.drain()
    return out


@lru_cache(maxsize=CLOSURE_CACHE_SIZE)
def _reach_fn(n: int):
    rounds = max(1, math.ceil(math.log2(n)))

    @jax.jit
    def close(a):
        r = a.astype(jnp.bfloat16)

        def step(r, _):
            return jnp.clip(r + jnp.matmul(r, r), 0.0, 1.0), None

        r, _ = jax.lax.scan(step, r, None, length=rounds)
        return r > 0.0

    return close


def reachability(adj: np.ndarray) -> np.ndarray:
    """Full boolean transitive closure of one adjacency matrix (device)."""
    n = _bucket(adj.shape[0])
    padded = np.zeros((n, n), dtype=bool)
    padded[: adj.shape[0], : adj.shape[1]] = adj
    # single-matrix convenience API: the caller wants the closure NOW,
    # there is no batch to overlap with — sanctioned inline sync
    return np.asarray(_reach_fn(n)(jnp.asarray(padded)))[  # jt: allow[trace-sync]
        : adj.shape[0], : adj.shape[1]
    ]
