"""Closure/union kernel smoke check: ``python -m jepsen_tpu.ops.smoke``.

The peak-FLOP kernel gate (doc/checker-engines.md "Transactional
screens"): the plane-packed one-closure screens, the convergence
early-exit closure, and the matmul subset-union lowering are pure
performance work — every one of them must be byte-identical to the
lowering it replaces.  This gate fails loudly on:

- packed screens diverging from the per-mask reference kernels OR from
  the pure-numpy ``_np_screen`` oracle, on rw-register-shaped (plain)
  and list-append/realtime-shaped (suffixed masks + both lifted walk
  queries) filter profiles, across vertex buckets — under every
  closure arithmetic (``uint8``/``packed32``/``bf16``);
- the early-exit (``lax.while_loop``) closure diverging from the
  fixed-round scan on either Elle kernel route (has-cycle flags and
  full screens) — and the saved rounds not being recorded;
- any closure impl's executor-routed has-cycle verdicts diverging
  from the direct host closure, or the settled dispatches not
  recording their ``jepsen_cycles_impl_total{impl}`` evidence;
- ``union="matmul"`` verdicts diverging from gather/unroll on the
  register AND queue dense kernels;
- a budget-accounting breach for packed shapes: under a deliberately
  tiny dispatch cap the executor must chunk the packed screen buckets
  and no kernel's peak in-flight per-chip rows may exceed its cap.

Run plain for the single-device gate and with
``JEPSEN_TPU_ENGINE_MESH=1`` for the 8-virtual-device sharded gate
(the Makefile's ``kernels-smoke`` target runs both).

Exit codes: 0 ok, 1 divergence or missing evidence.
"""

from __future__ import annotations

import os
import sys


def _rel_corpus(rng, n: int, rows: int):
    """Seeded ``(rows, n, n)`` uint8 relation batches mixing ring,
    chain, and sparse-random graphs over all five relation bits —
    cyclic and acyclic rows in every batch."""
    import numpy as np

    rel = np.zeros((rows, n, n), np.uint8)
    bits = (1, 2, 4, 8, 16)
    for b in range(rows):
        for i in range(n - 1):
            rel[b, i, i + 1] = bits[(b + i) % 5]
        if b % 3 == 0:
            rel[b, n - 1, 0] = bits[b % 5]  # close into a ring
        extra = rng.random((n, n)) < 0.05
        np.fill_diagonal(extra, False)
        rel[b] |= extra.astype(np.uint8) * bits[b % 5]
    return rel


def _queue_corpus(rng, n_hists: int):
    """Handcrafted unique-element unordered-queue histories (the tests'
    simulated generator, compacted): enqueues of fresh values, dequeues
    of any present element, with every third history corrupted by a
    dequeue of a value never enqueued."""
    from jepsen_tpu.history import History, fail_op, invoke_op, ok_op

    hists = []
    for h_i in range(n_hists):
        present, pending, hist = set(), {}, []
        idle, next_v, done = list(range(4)), 1, 0
        while done < 20 or pending:
            if idle and done < 20 and (not pending or rng.random() < 0.6):
                p = idle.pop(int(rng.integers(len(idle))))
                if present and rng.random() < 0.45:
                    hist.append(invoke_op(p, "dequeue", None))
                    pending[p] = ("dequeue", None)
                else:
                    hist.append(invoke_op(p, "enqueue", next_v))
                    pending[p] = ("enqueue", next_v)
                    next_v += 1
                done += 1
            else:
                p = sorted(pending)[int(rng.integers(len(pending)))]
                f, v = pending.pop(p)
                idle.append(p)
                if f == "enqueue":
                    present.add(v)
                    hist.append(ok_op(p, "enqueue", v))
                elif present:
                    got = sorted(present)[int(rng.integers(len(present)))]
                    present.discard(got)
                    if h_i % 3 == 0 and done > 10:
                        got = 9000 + h_i  # never enqueued
                    hist.append(ok_op(p, "dequeue", got))
                else:
                    hist.append(fail_op(p, "dequeue", None, error="empty"))
        hists.append(History(hist))
    return hists


def main(argv=None) -> int:
    from jepsen_tpu.platform import force_cpu_platform

    force_cpu_platform(8)

    import numpy as np

    from jepsen_tpu import obs
    from jepsen_tpu.elle import encode as elle_encode
    from jepsen_tpu.engine import execution
    from jepsen_tpu.ops import cycles as ops_cycles

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    rng = np.random.default_rng(45120)

    # -- packed ≡ per-mask ≡ numpy oracle, plain and suffixed filter
    # profiles across two vertex buckets (the rw-register canonical
    # profile and the full list-append/realtime ladder with both
    # lifted nonadjacent-rw walk queries)
    profiles = (
        ("rw-register/plain", (1, 3, 7), ((4, 3),)),
        ("list-append/realtime", (1, 3, 7, 25, 27, 31),
         ((4, 3), (4, 27))),
    )
    impls = ops_cycles._VALID_CLOSURE_IMPLS
    for label, masks, nonadj in profiles:
        for n in (16, 32):
            rel = _rel_corpus(rng, n, 12)
            want_m, want_w = ops_cycles._np_screen(rel, masks, nonadj)
            outs = {}
            for packed in (True, False):
                for mode in ("fixed", "earlyexit"):
                    for impl in impls:
                        fn = ops_cycles._screen_fn_variant(
                            n, masks, nonadj, packed, mode, impl
                        )
                        m, w, rounds = fn(rel)
                        outs[(packed, mode, impl)] = (
                            np.asarray(m), np.asarray(w),
                            np.asarray(rounds)
                        )
            base = outs[(True, "fixed", "uint8")]
            check(
                np.array_equal(base[0], want_m)
                and np.array_equal(base[1], want_w),
                f"{label} n={n}: packed screen diverges from numpy oracle",
            )
            for key, (m, w, rounds) in outs.items():
                check(
                    np.array_equal(m, base[0])
                    and np.array_equal(w, base[1]),
                    f"{label} n={n}: variant {key} diverges from packed",
                )
            for impl in impls:
                check(
                    int(outs[(True, "earlyexit", impl)][2].max())
                    <= int(outs[(True, "fixed", impl)][2].max()),
                    f"{label} n={n} impl={impl}: earlyexit ran MORE "
                    f"rounds than fixed",
                )

    # -- early-exit ≡ fixed on the has-cycle route, and the corpus
    # diameters actually save rounds somewhere
    mats = [
        np.asarray(m, bool) if i % 2 == 0
        else np.triu(np.asarray(m, bool), k=1)  # acyclic twin
        for i, m in enumerate(_rel_corpus(rng, 24, 10))
    ]
    want = ops_cycles._np_has_cycle(np.stack(mats))
    check(bool(want.any()) and not bool(want.all()),
          "has-cycle corpus should mix verdicts")
    obs.enable(reset=True)
    for mode in ("fixed", "earlyexit"):
        for impl in impls:
            os.environ["JEPSEN_TPU_CYCLES_CLOSURE"] = mode
            os.environ["JEPSEN_TPU_CYCLES_IMPL"] = impl
            try:
                got = ops_cycles.has_cycle_batch(mats)
                # the executor-routed lowering must agree with the
                # direct dispatch it replaces, per impl
                ex_r = execution.Executor(2)
                routed = ops_cycles.has_cycle_batch(mats, executor=ex_r)
            finally:
                os.environ.pop("JEPSEN_TPU_CYCLES_CLOSURE", None)
                os.environ.pop("JEPSEN_TPU_CYCLES_IMPL", None)
            check(
                np.array_equal(np.asarray(got), want),
                f"has_cycle_batch[{mode},{impl}] diverges from host "
                f"closure",
            )
            check(
                np.array_equal(np.asarray(routed), want),
                f"executor-routed has_cycle_batch[{mode},{impl}] "
                f"diverges from direct",
            )
    reg = obs.registry()
    for impl in impls:
        check(
            (reg.value("jepsen_cycles_impl_total", impl=impl) or 0) > 0,
            f"no jepsen_cycles_impl_total evidence for impl={impl}",
        )
    obs.enable(reset=True)

    # -- union="matmul" ≡ gather ≡ unroll on the register and queue
    # dense kernels (mixed valid/corrupt corpora)
    import random

    from jepsen_tpu import models as m
    from jepsen_tpu import synth
    from jepsen_tpu.ops import dense, encode

    prng = random.Random(45121)
    cas = [synth.generate_history(prng, n_procs=6, n_ops=60, crash_p=0.0,
                                  corrupt=(i % 3 == 0)) for i in range(8)]
    batch = encode.batch_encode(cas, m.cas_register(0), slot_cap=8)
    V = encode.round_up(
        int(max(batch.cand_a.max(), batch.cand_b.max(),
                batch.init_state.max())) + 1, 4)
    qb = encode.batch_encode(_queue_corpus(rng, 6), m.unordered_queue(),
                             slot_cap=6)
    for spec, bt, v in (("cas-register", batch, V),
                        ("unordered-queue", qb, 0)):
        args = (bt.init_state, bt.ev_slot, bt.cand_slot,
                bt.cand_f, bt.cand_a, bt.cand_b)
        outs = {}
        for union in dense.VALID_UNIONS:
            os.environ["JEPSEN_TPU_DENSE_UNION"] = union
            try:
                fn = dense.make_dense_fn(
                    spec, bt.ev_slot.shape[1], bt.cand_slot.shape[2], v
                )
                ok, fail, _ = fn(*args)
            finally:
                os.environ.pop("JEPSEN_TPU_DENSE_UNION", None)
            outs[union] = (np.asarray(ok), np.asarray(fail))
        for union in ("unroll", "matmul"):
            check(
                np.array_equal(outs["gather"][0], outs[union][0])
                and np.array_equal(outs["gather"][1], outs[union][1]),
                f"{spec}: union={union} diverges from gather",
            )
        check(not outs["gather"][0].all(),
              f"{spec}: union corpus should mix verdicts")

    # -- budget accounting for packed shapes through an explicit
    # resident executor: a tiny dispatch cap must chunk the packed
    # screen buckets, and no kernel's peak in-flight per-chip rows may
    # exceed its cap; the rounds metrics must record
    masks, nonadj = profiles[0][1], profiles[0][2]
    encs = [
        elle_encode.EncodedGraph(list(range(nn)), r, 7, masks, nonadj)
        for nn in (16, 32)
        for r in _rel_corpus(rng, nn, 8)
    ]
    def _same_screens(a, b):
        return (a is None) == (b is None) and (
            a is None or (
                all(np.array_equal(a.members[k], b.members[k])
                    for k in a.members)
                and all(np.array_equal(a.walks[k], b.walks[k])
                        for k in a.walks)
            )
        )

    def _check_accounting(ex_, what):
        check(ex_.submitted > 0,
              f"no {what} dispatches reached the executor")
        for acct in ex_.chip_row_accounting.values():
            cap = acct["chip_cap"]
            if acct["kernel"] == "dense":
                cap *= ex_.window_size
            check(acct["peak_chip_rows"] <= cap,
                  f"{what} per-chip budget breach: {acct}")

    obs.enable(reset=True)
    base = ops_cycles.screen_graphs(encs)
    ex = execution.Executor(4)
    capped = ops_cycles.screen_graphs(encs, executor=ex, max_dispatch=64)
    reg = obs.registry()
    for a, b in zip(base, capped):
        same = _same_screens(a, b)
        check(same, "capped packed screens diverge from uncapped")
        if not same:
            break
    _check_accounting(ex, "packed")
    # the same capped drill under the word-packed arithmetic: the
    # repriced caps are wider, but accounting must still hold and the
    # screens must stay byte-identical
    os.environ["JEPSEN_TPU_CYCLES_IMPL"] = "packed32"
    try:
        ex_w = execution.Executor(4)
        word = ops_cycles.screen_graphs(encs, executor=ex_w,
                                        max_dispatch=64)
    finally:
        os.environ.pop("JEPSEN_TPU_CYCLES_IMPL", None)
    for a, b in zip(base, word):
        same = _same_screens(a, b)
        check(same, "packed32 capped screens diverge from uint8")
        if not same:
            break
    _check_accounting(ex_w, "packed32")
    rounds_seen = sum(
        reg.value("jepsen_cycles_closure_rounds_total", mode=md) or 0
        for md in ("fixed", "earlyexit")
    )
    check(rounds_seen > 0, "no closure rounds recorded by the screens")
    check(
        reg.value("jepsen_cycles_packed_plane_occupancy") is not None,
        "no packed-plane occupancy gauge recorded",
    )
    obs.enable(reset=True)
    mesh_mode = os.environ.get("JEPSEN_TPU_ENGINE_MESH", "").strip()
    if mesh_mode in ("1", "on", "true", "yes", "force"):
        check(ex.n_devices == 8,
              f"mesh gate expected 8 devices, got {ex.n_devices}")

    if failures:
        for f_ in failures:
            print(f"kernels-smoke: FAIL — {f_}", file=sys.stderr)
        return 1
    print(
        "kernels-smoke: ok (packed ≡ per-mask ≡ numpy on plain+suffixed "
        "profiles; uint8 ≡ packed32 ≡ bf16 on both routes, "
        "executor-routed ≡ direct; earlyexit ≡ fixed; matmul ≡ gather ≡ "
        "unroll on register+queue; packed + packed32 budget accounting "
        f"over {ex.n_devices} device(s))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
