"""Branchless model step kernels for the TPU linearizability search.

Each supported model (see jepsen_tpu.models for the CPU oracles these are
differentially tested against) gets:

- an integer encoding of its state (one int32),
- an op encoding ``(f, a, b)`` of int32s, and
- a pure, branchless ``step(state, f, a, b) -> (state', ok)`` built from
  jnp.where/select so it vectorizes over (frontier × candidate) lanes and
  compiles into the surrounding scan without data-dependent control flow.

Covers the knossos.model set the reference's linearizable checker uses
(jepsen/src/jepsen/checker.clj:19-26,185-216): register, cas-register,
mutex, multi-register, and unordered-queue (as a unique-element bitset —
see unordered_queue_step for the envelope).  FIFO queues stay on the CPU
oracle: their state is the pending *sequence*, which depends on the
linearization order itself and admits no fixed-width encoding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp

from .. import models as m

# Op function codes shared by the register-family kernels.
F_READ = 0        # a = expected value id (observed at completion)
F_WRITE = 1       # a = written value id
F_CAS = 2         # a = expected old value id, b = new value id
F_READ_ANY = 3    # read with unknown value: always ok, no state change
F_ACQUIRE = 4     # mutex
F_RELEASE = 5     # mutex
F_ENQUEUE = 6     # unordered queue: a = value id
F_DEQUEUE = 7     # unordered queue: a = observed value id
F_RACQUIRE = 8    # reentrant mutex: a = client id (see reentrant_mutex_step)
F_RRELEASE = 9    # reentrant mutex: a = client id
F_PACQUIRE = 10   # permit (semaphore) acquire: a = client id
F_PRELEASE = 11   # permit release: a = client id

#: Value id reserved for "unknown/None". Known values are 1-based.
V_UNKNOWN = 0


def register_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Read/write register.  (oracle: models.Register)"""
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_read_any = f == F_READ_ANY
    ok = is_write | is_read_any | (is_read & (state == a))
    state2 = jnp.where(is_write, a, state)
    return state2, ok


def cas_register_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Read/write/compare-and-set register.  (oracle: models.CASRegister)"""
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    is_read_any = f == F_READ_ANY
    cas_ok = is_cas & (state == a)
    ok = is_write | is_read_any | (is_read & (state == a)) | cas_ok
    state2 = jnp.where(is_write, a, jnp.where(cas_ok, b, state))
    return state2, ok


def mutex_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Lock: state 0 = free, 1 = held.  (oracle: models.Mutex)"""
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = (is_acq & (state == 0)) | (is_rel & (state == 1))
    state2 = jnp.where(is_acq, 1, jnp.where(is_rel, 0, state)).astype(state.dtype)
    return state2, ok


def reentrant_mutex_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Reentrant owner-aware mutex with hold bound 2 (the hazelcast CP
    probe's reentrant-lock-acquire-count).  State ids: 0 = free,
    2c-1 = client c holds once, 2c = client c holds twice (a = client
    id c ≥ 1).  acquire: free → (c,1) or (c,1) → (c,2); release:
    (c,2) → (c,1) or (c,1) → free.  (oracle: models.ReentrantMutex)"""
    is_acq = f == F_RACQUIRE
    is_rel = f == F_RRELEASE
    once = 2 * a - 1
    twice = 2 * a
    acq_fresh = is_acq & (state == 0)
    acq_re = is_acq & (state == once)
    rel_two = is_rel & (state == twice)
    rel_one = is_rel & (state == once)
    ok = acq_fresh | acq_re | rel_two | rel_one
    state2 = jnp.where(
        acq_fresh, once,
        jnp.where(
            acq_re, twice,
            jnp.where(rel_two, once, jnp.where(rel_one, 0, state)),
        ),
    ).astype(state.dtype)
    return state2, ok


#: multi-register packing: up to 4 registers, 8-bit value ids each, in
#: one int32 state word.  Wider maps fall back to the CPU oracle.
MR_REGISTERS = 4
MR_VALUE_BITS = 8
MR_MAX_VALUE_ID = (1 << MR_VALUE_BITS) - 1


def multi_register_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Single-mop multi-register: b = register index, a = value id; the
    int32 state packs MR_REGISTERS byte-wide registers.
    (oracle: models.MultiRegister)"""
    sh = (b.astype(jnp.int32) & (MR_REGISTERS - 1)) * MR_VALUE_BITS
    mask = jnp.int32(MR_MAX_VALUE_ID) << sh
    cur = (state >> sh) & MR_MAX_VALUE_ID
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_read_any = f == F_READ_ANY
    ok = is_write | is_read_any | (is_read & (cur == a))
    written = (state & ~mask) | ((a.astype(jnp.int32) & MR_MAX_VALUE_ID) << sh)
    state2 = jnp.where(is_write, written, state)
    return state2, ok


#: unordered-queue packing: a bitset of present values in one int32 —
#: sound only when every value appears at most once (initial contents +
#: enqueues), which IS the shape real queue workloads generate (unique
#: elements, e.g. suites/common.py queue_workload); histories breaking
#: it fall back to the oracle at encode time.  A FIFO queue's state is
#: the *sequence* of pending values — it depends on the linearization
#: order itself, so no fixed-width encoding exists without bounding the
#: whole history; FIFOQueue therefore stays on the CPU oracle
#: (models.FIFOQueue), like knossos's queue model effectively does for
#: all but tiny histories.
UQ_MAX_VALUES = 31  # ids 1..31 → bits 0..30, sign bit untouched


def unordered_queue_step(state, f, a, b):  # jt: traced jaxpr(dot_generals<=0, dtype=int32)
    """Bag of unique values as a bitset.  (oracle: models.UnorderedQueue
    restricted to multiplicity ≤ 1)"""
    bit = jnp.int32(1) << (a.astype(jnp.int32) - 1)
    present = (state & bit) != 0
    is_enq = f == F_ENQUEUE
    is_deq = f == F_DEQUEUE
    ok = (is_enq & ~present) | (is_deq & present)
    state2 = jnp.where(
        is_enq, state | bit, jnp.where(is_deq, state & ~bit, state)
    ).astype(state.dtype)
    return state2, ok


@dataclass(frozen=True)
class ModelSpec:
    """Host-side description of how a model maps onto the kernel."""

    name: str
    step: Callable  # (state, f, a, b) -> (state', ok), broadcastable
    #: encode an op (with completion value already propagated) into
    #: (f, a, b) int codes, given a mutable value→id map
    encode_op: Callable[[Any, Dict[Any, int]], Tuple[int, int, int]]
    #: initial kernel state from the oracle model instance
    init_state: Callable[[m.Model, Dict[Any, int]], int]
    #: fs that never change state — indeterminate ones are stripped
    pure_fs: Tuple[str, ...]
    #: True when only the dense automaton exists for this spec (its
    #: state enumeration is built from host tables the branchless step
    #: functions can't express); outside the dense envelope such
    #: batches go straight to the oracle, never the frontier kernel
    dense_only: bool = False


def _value_id(value, valmap: Dict[Any, int]) -> int:
    if value is None:
        return V_UNKNOWN
    vid = valmap.get(value)
    if vid is None:
        vid = len(valmap) + 1  # ids are 1-based; 0 is V_UNKNOWN
        valmap[value] = vid
    return vid


def _encode_register_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "write":
        return F_WRITE, _value_id(op.value, valmap), 0
    if op.f == "read":
        if op.value is None:
            return F_READ_ANY, 0, 0
        return F_READ, _value_id(op.value, valmap), 0
    raise ValueError(f"register cannot encode op f={op.f!r}")


def _encode_cas_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "cas":
        if op.value is None:
            raise ValueError("cas with nil value is never linearizable")
        old, new = op.value
        return F_CAS, _value_id(old, valmap), _value_id(new, valmap)
    return _encode_register_op(op, valmap)


def _encode_mutex_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "acquire":
        return F_ACQUIRE, 0, 0
    if op.f == "release":
        return F_RELEASE, 0, 0
    raise ValueError(f"mutex cannot encode op f={op.f!r}")


def _owner_client(op):
    # the oracle's identity extraction (models.locks._client) is the
    # single source of truth: encoder and oracle MUST agree on WHO
    # acted or device and oracle verdicts diverge
    from ..models.locks import _client

    client = _client(op)
    if client is None:
        # an op that never reported WHO acted (e.g. a crashed acquire
        # whose client died before stamping) cannot ride the value
        # automaton; the whole history falls back to the oracle
        raise ValueError("owner-mutex op without client identity")
    return client


def _rm_client_id(client, valmap: Dict[Any, int]) -> int:
    """1-based client index (the reentrant encoder interns nothing
    else, so _value_id stays contiguous over clients); the state
    domain is 2·N+1 ids for N clients (see reentrant_mutex_step)."""
    return _value_id(("rm-client", client), valmap)


def _encode_reentrant_mutex_op(op, valmap) -> Tuple[int, int, int]:
    """Reentrant mutex ops: a = client index; the step function owns
    the (free / once / twice) state algebra.  Only the reference's
    hold bound of 2 has a kernel; other bounds ride the oracle (the
    spec's init_state raises)."""
    client = _owner_client(op)
    cid = _rm_client_id(client, valmap)
    if op.f == "acquire":
        return F_RACQUIRE, cid, 0
    if op.f == "release":
        return F_RRELEASE, cid, 0
    raise ValueError(f"reentrant-mutex cannot encode op f={op.f!r}")


def _reentrant_mutex_init(model, valmap) -> int:
    from ..models.locks import REENTRANT_ACQUIRE_COUNT

    if model.max_count != REENTRANT_ACQUIRE_COUNT:
        raise ValueError(
            "reentrant-mutex kernel supports the hold bound of "
            f"{REENTRANT_ACQUIRE_COUNT} only"
        )
    if model.owner is None:
        return 0
    if model.count not in (1, 2):
        # a held owner with a count outside the algebra (count=0 is
        # constructible) has no state id — oracle fallback, not a
        # silently-diverging kernel verdict
        raise ValueError("reentrant-mutex init outside the kernel algebra")
    cid = _rm_client_id(model.owner, valmap)
    return 2 * cid - 1 if model.count == 1 else 2 * cid


def _pm_client_id(client, valmap: Dict[Any, int]) -> int:
    """1-based client index for the permit automaton (the permits
    encoder interns nothing else, so _value_id stays contiguous)."""
    return _value_id(("pm-client", client), valmap)


def _encode_permits_op(op, valmap) -> Tuple[int, int, int]:
    """Semaphore permit ops: a = client index.  The state enumeration
    (multisets of ≤ n_permits client ids) lives in host tables built by
    the dense kernel (ops/dense.py permits_tables); no branchless step
    function exists, so the spec is dense_only."""
    client = _owner_client(op)
    cid = _pm_client_id(client, valmap)
    if op.f == "acquire":
        return F_PACQUIRE, cid, 0
    if op.f == "release":
        return F_PRELEASE, cid, 0
    raise ValueError(f"acquired-permits cannot encode op f={op.f!r}")


def _permits_init(model, valmap) -> int:
    if model.acquired:
        # a non-empty initial multiset needs the global state
        # enumeration, which depends on the final client count the
        # encoder can't know yet — oracle fallback
        raise ValueError("acquired-permits kernel needs an empty start")
    return 0


def _no_step(state, f, a, b):  # pragma: no cover — gated by dense_only
    raise NotImplementedError(
        "acquired-permits has no frontier step; dense_only batches "
        "outside the dense envelope must go to the oracle"
    )


def _encode_owner_mutex_op(op, valmap) -> Tuple[int, int, int]:
    """The owner-aware mutex IS a cas-register in disguise: state =
    holder ("free" is its own value id), acquire(c) = cas(free → c),
    release(c) = cas(c → free) — so the whole cas-register kernel
    family (dense subset automaton included) applies unchanged.  Client
    identities ride the value-id map like register values."""
    client = _owner_client(op)
    free = _value_id("__free__", valmap)
    cid = _value_id(("client", client), valmap)
    if op.f == "acquire":
        return F_CAS, free, cid
    if op.f == "release":
        return F_CAS, cid, free
    raise ValueError(f"owner-mutex cannot encode op f={op.f!r}")


def _owner_mutex_init(model, valmap) -> int:
    if model.owner is None:
        return _value_id("__free__", valmap)
    return _value_id(("client", model.owner), valmap)


def _register_init(model, valmap) -> int:
    return _value_id(model.value, valmap)


def _mr_reg_id(k, valmap: Dict[Any, int]) -> int:
    """Register index for key k; at most MR_REGISTERS distinct keys."""
    key = ("mrreg", k)
    r = valmap.get(key)
    if r is None:
        r = valmap.get("__mr_nreg__", 0)
        if r >= MR_REGISTERS:
            raise ValueError("too many registers for the packed kernel")
        valmap[key] = r
        valmap["__mr_nreg__"] = r + 1
    return r


def _mr_value_id(reg: int, v, valmap: Dict[Any, int]) -> int:
    """Per-register value ids so each stays within MR_VALUE_BITS."""
    if v is None:
        return V_UNKNOWN
    key = ("mrval", reg, v)
    vid = valmap.get(key)
    if vid is None:
        nkey = ("mrn", reg)
        vid = valmap.get(nkey, 0) + 1
        if vid > MR_MAX_VALUE_ID:
            raise ValueError("too many distinct values for one register")
        valmap[key] = vid
        valmap[nkey] = vid
    return vid


def _encode_multi_register_op(op, valmap) -> Tuple[int, int, int]:
    """Single-mop [(f, k, v)] transactions; multi-mop ones fall back to
    the oracle (models.MultiRegister handles arbitrary mop lists)."""
    mops = list(op.value or [])
    if not mops:
        return F_READ_ANY, 0, 0
    if len(mops) != 1:
        raise ValueError("multi-mop transactions ride the oracle")
    mf, k, v = mops[0]
    reg = _mr_reg_id(k, valmap)
    if mf in ("w", "write"):
        if v is None:
            raise ValueError("write of nil is never linearizable")
        return F_WRITE, _mr_value_id(reg, v, valmap), reg
    if mf in ("r", "read"):
        if v is None:
            return F_READ_ANY, 0, reg
        return F_READ, _mr_value_id(reg, v, valmap), reg
    raise ValueError(f"multi-register cannot encode mop f={mf!r}")


def _mr_init(model, valmap) -> int:
    state = 0
    for k, v in dict(model.values).items():
        reg = _mr_reg_id(k, valmap)
        vid = _mr_value_id(reg, v, valmap)
        state |= vid << (reg * MR_VALUE_BITS)
    return state


def _uq_value_id(v, valmap: Dict[Any, int]) -> int:
    """Namespaced ids with their own counter (like _mr_value_id) —
    sharing _value_id's len(valmap)-based counter would double-count
    the bookkeeping keys below and halve the usable envelope."""
    if v is None:
        raise ValueError("queue op with unknown value rides the oracle")
    key = ("uqval", v)
    vid = valmap.get(key)
    if vid is None:
        vid = valmap.get("__uq_n__", 0) + 1
        if vid > UQ_MAX_VALUES:
            raise ValueError(
                "too many distinct values for the bitset kernel"
            )
        valmap[key] = vid
        valmap["__uq_n__"] = vid
    return vid


def _encode_unordered_queue_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "enqueue":
        vid = _uq_value_id(op.value, valmap)
        key = ("uq-enq", vid)
        if valmap.get(key):
            raise ValueError(
                "value enqueued more than once; multiset histories ride "
                "the oracle"
            )
        valmap[key] = 1
        return F_ENQUEUE, vid, 0
    if op.f == "dequeue":
        return F_DEQUEUE, _uq_value_id(op.value, valmap), 0
    raise ValueError(f"unordered-queue cannot encode op f={op.f!r}")


def _uq_init(model, valmap) -> int:
    state = 0
    for v, count in dict(model.items).items():
        if count != 1:
            raise ValueError("initial multiplicities >1 ride the oracle")
        vid = _uq_value_id(v, valmap)
        valmap[("uq-enq", vid)] = 1  # counts against the once-only rule
        state |= 1 << (vid - 1)
    return state


SPECS: Dict[type, ModelSpec] = {
    m.Register: ModelSpec(
        name="register",
        step=register_step,
        encode_op=_encode_register_op,
        init_state=_register_init,
        pure_fs=("read",),
    ),
    m.CASRegister: ModelSpec(
        name="cas-register",
        step=cas_register_step,
        encode_op=_encode_cas_op,
        init_state=_register_init,
        pure_fs=("read",),
    ),
    m.Mutex: ModelSpec(
        name="mutex",
        step=mutex_step,
        encode_op=_encode_mutex_op,
        init_state=lambda model, valmap: 1 if model.locked else 0,
        pure_fs=(),
    ),
    m.MultiRegister: ModelSpec(
        name="multi-register",
        step=multi_register_step,
        encode_op=_encode_multi_register_op,
        init_state=_mr_init,
        pure_fs=(),
    ),
    m.UnorderedQueue: ModelSpec(
        name="unordered-queue",
        step=unordered_queue_step,
        encode_op=_encode_unordered_queue_op,
        init_state=_uq_init,
        pure_fs=(),
    ),
    # the owner-aware mutex reduces to cas-register ops at encode time
    # (_encode_owner_mutex_op) and reuses that step function, so the
    # whole kernel family — including the overflow-free dense subset
    # automaton — applies without a new device step.  The name stays
    # unique (wgl resolves specs BY name).  The fenced/reentrant/
    # permit flavors carry state the value automaton can't express
    # (global fence monotonicity, hold counts, multisets) and stay
    # oracle-checked.
    m.OwnerMutex: ModelSpec(
        name="owner-mutex",
        step=cas_register_step,
        encode_op=_encode_owner_mutex_op,
        init_state=_owner_mutex_init,
        pure_fs=(),
    ),
    # reentrant owner-aware mutex (hold bound 2): its own step algebra
    # over state ids {0, 2c-1, 2c}; the state DOMAIN is 2·N+1 for N
    # clients — check_batch widens n_values accordingly.  The fenced
    # flavors stay oracle-only (global fence monotonicity over
    # unbounded tokens has no small value automaton).
    m.ReentrantMutex: ModelSpec(
        name="reentrant-mutex",
        step=reentrant_mutex_step,
        encode_op=_encode_reentrant_mutex_op,
        init_state=_reentrant_mutex_init,
        pure_fs=(),
    ),
    # semaphore permits: a multiset of ≤ n_permits client ids — the
    # state enumeration comes from host-precomputed transition tables
    # (ops/dense.py permits_tables), so only the dense automaton
    # exists; past its envelope the oracle takes the batch
    m.AcquiredPermits: ModelSpec(
        name="acquired-permits",
        step=_no_step,
        encode_op=_encode_permits_op,
        init_state=_permits_init,
        pure_fs=(),
        dense_only=True,
    ),
}


def spec_for(model: m.Model) -> Optional[ModelSpec]:
    return SPECS.get(type(model))
