"""Branchless model step kernels for the TPU linearizability search.

Each supported model (see jepsen_tpu.models for the CPU oracles these are
differentially tested against) gets:

- an integer encoding of its state (one int32),
- an op encoding ``(f, a, b)`` of int32s, and
- a pure, branchless ``step(state, f, a, b) -> (state', ok)`` built from
  jnp.where/select so it vectorizes over (frontier × candidate) lanes and
  compiles into the surrounding scan without data-dependent control flow.

Covers the knossos.model set the reference's linearizable checker uses
(jepsen/src/jepsen/checker.clj:19-26,185-216): register, cas-register,
mutex.  Richer-state models (queues) stay on the CPU oracle path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp

from .. import models as m

# Op function codes shared by the register-family kernels.
F_READ = 0        # a = expected value id (observed at completion)
F_WRITE = 1       # a = written value id
F_CAS = 2         # a = expected old value id, b = new value id
F_READ_ANY = 3    # read with unknown value: always ok, no state change
F_ACQUIRE = 4     # mutex
F_RELEASE = 5     # mutex

#: Value id reserved for "unknown/None". Known values are 1-based.
V_UNKNOWN = 0


def register_step(state, f, a, b):
    """Read/write register.  (oracle: models.Register)"""
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_read_any = f == F_READ_ANY
    ok = is_write | is_read_any | (is_read & (state == a))
    state2 = jnp.where(is_write, a, state)
    return state2, ok


def cas_register_step(state, f, a, b):
    """Read/write/compare-and-set register.  (oracle: models.CASRegister)"""
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    is_read_any = f == F_READ_ANY
    cas_ok = is_cas & (state == a)
    ok = is_write | is_read_any | (is_read & (state == a)) | cas_ok
    state2 = jnp.where(is_write, a, jnp.where(cas_ok, b, state))
    return state2, ok


def mutex_step(state, f, a, b):
    """Lock: state 0 = free, 1 = held.  (oracle: models.Mutex)"""
    is_acq = f == F_ACQUIRE
    is_rel = f == F_RELEASE
    ok = (is_acq & (state == 0)) | (is_rel & (state == 1))
    state2 = jnp.where(is_acq, 1, jnp.where(is_rel, 0, state)).astype(state.dtype)
    return state2, ok


@dataclass(frozen=True)
class ModelSpec:
    """Host-side description of how a model maps onto the kernel."""

    name: str
    step: Callable  # (state, f, a, b) -> (state', ok), broadcastable
    #: encode an op (with completion value already propagated) into
    #: (f, a, b) int codes, given a mutable value→id map
    encode_op: Callable[[Any, Dict[Any, int]], Tuple[int, int, int]]
    #: initial kernel state from the oracle model instance
    init_state: Callable[[m.Model, Dict[Any, int]], int]
    #: fs that never change state — indeterminate ones are stripped
    pure_fs: Tuple[str, ...]


def _value_id(value, valmap: Dict[Any, int]) -> int:
    if value is None:
        return V_UNKNOWN
    vid = valmap.get(value)
    if vid is None:
        vid = len(valmap) + 1  # ids are 1-based; 0 is V_UNKNOWN
        valmap[value] = vid
    return vid


def _encode_register_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "write":
        return F_WRITE, _value_id(op.value, valmap), 0
    if op.f == "read":
        if op.value is None:
            return F_READ_ANY, 0, 0
        return F_READ, _value_id(op.value, valmap), 0
    raise ValueError(f"register cannot encode op f={op.f!r}")


def _encode_cas_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "cas":
        if op.value is None:
            raise ValueError("cas with nil value is never linearizable")
        old, new = op.value
        return F_CAS, _value_id(old, valmap), _value_id(new, valmap)
    return _encode_register_op(op, valmap)


def _encode_mutex_op(op, valmap) -> Tuple[int, int, int]:
    if op.f == "acquire":
        return F_ACQUIRE, 0, 0
    if op.f == "release":
        return F_RELEASE, 0, 0
    raise ValueError(f"mutex cannot encode op f={op.f!r}")


def _register_init(model, valmap) -> int:
    return _value_id(model.value, valmap)


SPECS: Dict[type, ModelSpec] = {
    m.Register: ModelSpec(
        name="register",
        step=register_step,
        encode_op=_encode_register_op,
        init_state=_register_init,
        pure_fs=("read",),
    ),
    m.CASRegister: ModelSpec(
        name="cas-register",
        step=cas_register_step,
        encode_op=_encode_cas_op,
        init_state=_register_init,
        pure_fs=("read",),
    ),
    m.Mutex: ModelSpec(
        name="mutex",
        step=mutex_step,
        encode_op=_encode_mutex_op,
        init_state=lambda model, valmap: 1 if model.locked else 0,
        pure_fs=(),
    ),
}


def spec_for(model: m.Model) -> Optional[ModelSpec]:
    return SPECS.get(type(model))
