"""Dense subset-automaton linearizability kernel for register-family
models — the TPU-first fast path.

The generic WGL kernel (jepsen_tpu.ops.wgl) keeps an explicit frontier of
``(state, linset)`` configs and pays a sort-based dedup/compaction on
every closure step; its capacity F can overflow, degrading to "unknown".
For models whose state enumerates to a small integer domain — read/write
registers, CAS registers, mutexes (the knossos models the reference's
linearizable checker actually runs, jepsen/src/jepsen/checker.clj:19-26)
— there is a representation that maps far better onto a vector machine:

    D[v, s] = 1  iff some linearization order of the ops in subset ``s``
              (of the ≤C currently-open slots) takes the register from
              the promoted prefix to value id ``v``.

``D`` is a *dense* boolean tensor over (value id × linset subset), bit-
packed along the subset axis into uint32 words.  Every WGL operation
becomes a static, branch-free tensor op:

- *value transition*: per event a [C, V, V] one-hot transition matrix is
  built from the candidate op codes (read keeps one value row, write
  folds every row into one, cas moves row a to row b, mutex ops are cas
  in disguise); applying it is a short OR-tree of selects.
- *closure* (linearize open op j): the subset map ``s → s | bit_j`` is,
  on the packed axis, a masked word shift for j < 5 and a static word
  permutation for j ≥ 5 — all C slots advance in ONE vectorized step per
  pass.  No sort, no dedup (the set representation dedups for free), and
  **overflow cannot happen**.
- *completion of slot s* (filter configs that linearized s, promote it):
  the inverse map ``s' → s' \\ bit_s``, a masked shift/permutation again,
  selected among C static variants by the completing slot id.

Per event the closure runs to fixpoint in ≤C passes (a chain linearizes
each open op at most once), so ``lax.while_loop`` capped at C+2 is exact
— there is no truncation/"unknown" path at all.  The whole search is a
``lax.scan`` over events, vmapped over histories, sharded over the
device mesh like the generic kernel.

Cost per event is a handful of fused vector ops on [C, V, 2^C/32]
uint32 tensors — for the practical C ≤ 12, V ≤ 32 envelope a few KB per
history — versus the generic kernel's two O((F + F·C) log) sorts per
closure pass.  Measured on one TPU chip this is orders of magnitude
faster (see bench.py).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .step_kernels import (
    F_READ,
    F_WRITE,
    F_CAS,
    F_READ_ANY,
    F_ACQUIRE,
    F_RELEASE,
    F_ENQUEUE,
    F_DEQUEUE,
    F_RACQUIRE,
    F_RRELEASE,
    F_PACQUIRE,
    F_PRELEASE,
)

#: specs whose state is exactly "current value id" (mutex: 0=free
#: 1=held; owner-mutex: 0=free, else holder's client id — its ops
#: arrive as cas codes from the encoder; reentrant-mutex: 0=free,
#: 2c-1/2c = client c holding once/twice)
DENSE_SPECS = (
    "register", "cas-register", "mutex", "owner-mutex", "reentrant-mutex"
)

#: dense envelope: beyond these the generic frontier kernel takes over
MAX_C = 12   # 2^12 subsets = 128 packed words
MAX_V = 32

#: multi-register composite-state cap: the K-register automaton runs
#: dense over S = Vr^K states (digit per register), e.g. a V^4 map at
#: V ≤ 3, a 2-key map at V ≤ 11 — the small per-key value domains the
#: causal/monotonic-style workloads produce.  Per-event cost scales
#: with S², so past this point the frontier kernel's config-adaptive
#: search wins even though larger S still compiles.
MR_MAX_STATES = 128


def mr_shape_probe(init_state, cand_a, cand_b) -> tuple:
    """(Vr, K) composite shape of an encoded multi-register batch:
    a = per-register value id, b = register index, init packs one
    byte-wide value id per register (step_kernels.py:74-94).  A raw max
    over the PACKED init would wildly overestimate the domain."""
    from .step_kernels import MR_REGISTERS, MR_VALUE_BITS

    init = np.asarray(init_state)
    mask = (1 << MR_VALUE_BITS) - 1
    dig_max = [
        int(((init >> (MR_VALUE_BITS * k)) & mask).max())
        for k in range(MR_REGISTERS)
    ]
    kreg = max(
        int(np.asarray(cand_b).max()) + 1,
        max((k + 1 for k in range(MR_REGISTERS) if dig_max[k] > 0),
            default=1),
    )
    vr = 1 + max(int(np.asarray(cand_a).max()), max(dig_max))
    return vr, kreg


def permits_tables(N: int, P: int):
    """Host-side state enumeration + transition tables for the permit
    (semaphore) automaton: states are multisets of ≤ P client ids
    (1-based, N clients).  Returns (S, acq, rel) with acq/rel of shape
    [N+1, S] mapping (client, state) → state' (or -1 = invalid move:
    acquiring past P total permits, releasing a permit not held)."""
    states = [()]
    if P >= 1:
        states += [(c,) for c in range(1, N + 1)]
    if P >= 2:
        states += [
            (c, d) for c in range(1, N + 1) for d in range(c, N + 1)
        ]
    if P > 2:
        raise ValueError("permit tables support n_permits <= 2")
    index = {st: i for i, st in enumerate(states)}
    S = len(states)
    acq = np.full((N + 1, S), -1, np.int32)
    rel = np.full((N + 1, S), -1, np.int32)
    for i, st in enumerate(states):
        for c in range(1, N + 1):
            if len(st) < P:
                acq[c, i] = index[tuple(sorted(st + (c,)))]
            if c in st:
                out = list(st)
                out.remove(c)
                rel[c, i] = index[tuple(out)]
    return S, acq, rel


def applicable(spec_name: str, C: int, V) -> bool:
    """``V`` is the value-domain size for the register family, or a
    ``(Vr, K)`` pair (per-register domain, register count) for
    multi-register."""
    if spec_name == "unordered-queue":
        # the queue kernel has no V dimension: its state is a pure
        # function of the linset (unique-value ops commute), so only C
        # bounds it — value ids are capped by the encoder at 31 anyway
        return C <= MAX_C
    if spec_name == "multi-register":
        if not isinstance(V, tuple):
            return False
        vr, k = V
        return C <= MAX_C and vr ** k <= MR_MAX_STATES
    if spec_name == "acquired-permits":
        if not isinstance(V, tuple):
            return False
        n_clients, p = V
        if p > 2:
            return False
        S = 1 + n_clients + (
            n_clients * (n_clients + 1) // 2 if p >= 2 else 0
        )
        return C <= MAX_C and S <= MR_MAX_STATES
    return spec_name in DENSE_SPECS and C <= MAX_C and V <= MAX_V


#: _LOMASK[j]: bits of a 32-subset word whose subset index has bit j clear
_LOMASK = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)


def _n_words(C: int) -> int:
    return max(1, (1 << C) // 32)


def _subset_maps(C: int):
    """Static per-slot tables for the packed-axis subset maps.

    union (``s → s | bit_j``, image restricted to s ∋ j):
        out[k] = (x[uidx[j,k]] & umask[j,k]) << ushl[j]
    drop (``s → s \\ bit_j``, image restricted to s ∌ j):
        out[k] = (x[didx[j,k]] >> dshr[j]) & dmask[j,k]

    For j < 5 the map moves bits inside a word (mask + shift); for j ≥ 5
    it permutes whole words (static gather + output mask).
    """
    W = _n_words(C)
    k = np.arange(W)
    uidx = np.zeros((C, W), np.int32)
    umask = np.zeros((C, W), np.uint32)
    ushl = np.zeros((C,), np.uint32)
    didx = np.zeros((C, W), np.int32)
    dmask = np.zeros((C, W), np.uint32)
    dshr = np.zeros((C,), np.uint32)
    for j in range(C):
        if j < 5:
            uidx[j] = k
            umask[j] = _LOMASK[j]
            ushl[j] = 1 << j
            didx[j] = k
            dmask[j] = _LOMASK[j]
            dshr[j] = 1 << j
        else:
            wb = 1 << (j - 5)
            uidx[j] = k ^ wb
            umask[j] = np.where((k & wb) != 0, 0xFFFFFFFF, 0)
            didx[j] = k | wb
            dmask[j] = np.where((k & wb) == 0, 0xFFFFFFFF, 0)
    return (
        jnp.asarray(uidx),
        jnp.asarray(umask),
        jnp.asarray(ushl),
        jnp.asarray(didx),
        jnp.asarray(dmask),
        jnp.asarray(dshr),
    )


def _subset_perms(C: int):
    """One-hot word-permutation matrices for the ``union="matmul"``
    lowering: ``Pu[j, w, k] = 1`` iff the union map's word ``k`` reads
    word ``w`` (``w = k ^ wb`` for j ≥ 5, identity below — the j < 5
    maps move bits inside a word, which no matmul over the packed axis
    can do), and ``Pd`` likewise for the drop map's ``k | wb``.  Each
    column holds exactly one 1, so the uint32 matmul is exact: every
    output word is a single product, never a sum that could wrap."""
    W = _n_words(C)
    k = np.arange(W)
    Pu = np.zeros((C, W, W), np.uint32)
    Pd = np.zeros((C, W, W), np.uint32)
    for j in range(C):
        if j < 5:
            Pu[j, k, k] = 1
            Pd[j, k, k] = 1
        else:
            wb = 1 << (j - 5)
            Pu[j, k ^ wb, k] = 1
            Pd[j, k | wb, k] = 1
    return jnp.asarray(Pu), jnp.asarray(Pd)


#: boolean lanes carried per packed adjacency word — the uint32 word
#: width the cycle kernels' ``packed32`` closure and the host
#: ``np.packbits`` fallback both pack to (doc/checker-engines.md
#: "Word-packed closure")
WORD_LANES = 32


def word_count(n: int) -> int:
    """uint32 words needed to carry ``n`` boolean lanes (≥ 1) — the
    ``W`` of the ``(B, n, W)`` packed adjacency layout and the unit
    the word-packed budget math prices rows in
    (:func:`jepsen_tpu.ops.cycles.cycles_max_dispatch`)."""
    return max(1, -(-n // WORD_LANES))


def pack_words_np(bits: np.ndarray) -> np.ndarray:
    """Host word-packing: ``(..., n) bool → (..., W) uint32`` with lane
    ``j`` stored at word ``j // 32``, bit position ``j % 32`` (little
    bit order — the layout ``np.packbits(bitorder="little")`` emits,
    and the one the device-side
    :func:`jepsen_tpu.ops.cycles._pack_words` reproduces bit-for-bit;
    the round-trip property tests pin the two layouts equal)."""
    bits = np.asarray(bits, bool)
    n = bits.shape[-1]
    W = word_count(n)
    pad = W * WORD_LANES - n
    if pad:
        bits = np.concatenate(
            [bits, np.zeros(bits.shape[:-1] + (pad,), bool)], axis=-1
        )
    by = np.packbits(bits, axis=-1, bitorder="little").astype(np.uint32)
    by = by.reshape(bits.shape[:-1] + (W, 4))
    return (by[..., 0]
            | (by[..., 1] << np.uint32(8))
            | (by[..., 2] << np.uint32(16))
            | (by[..., 3] << np.uint32(24)))


def unpack_words_np(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`pack_words_np`: ``(..., W) uint32 → (..., n)``
    bool — lanes past ``n`` are word-floor padding and are dropped."""
    words = np.asarray(words, np.uint32)
    shifts = np.arange(WORD_LANES, dtype=np.uint32)
    lanes = (words[..., None] >> shifts) & np.uint32(1)
    return lanes.reshape(words.shape[:-1] + (-1,))[..., :n].astype(bool)


VALID_UNIONS = ("unroll", "gather", "matmul")


def _check_union(union: str) -> None:
    if union not in VALID_UNIONS:
        raise ValueError(f"unknown dense union lowering {union!r}")


def _xor_permute(x, wb: int):
    """x[..., k] → x[..., k ^ wb] along the last axis, as reshape +
    flip (wb a power of two) — a layout shuffle XLA cannot mistake for
    a data-dependent gather."""
    shape = x.shape
    W = shape[-1]
    xr = x.reshape(*shape[:-1], W // (2 * wb), 2, wb)
    return xr[..., ::-1, :].reshape(*shape)


def _or_select(x, wb: int):
    """x[..., k] → x[..., k | wb]: both halves of each 2·wb block read
    the high half."""
    shape = x.shape
    W = shape[-1]
    xr = x.reshape(*shape[:-1], W // (2 * wb), 2, wb)
    hi = xr[..., 1:2, :]
    return jnp.concatenate([hi, hi], axis=-2).reshape(*shape)


#: subset-map implementation for the dense kernels: "unroll" (default,
#: per-slot static shuffles — reshape/flip for the j≥5 word
#: permutations, pure mask/shift below), "gather" (take_along_axis
#: over constant index tensors), or "matmul" (the j≥5 word
#: permutations as ONE one-hot batched uint32 matmul over the packed
#: axis — _subset_perms — so the union/drop maps ride the same
#: matrix-unit path the closure kernels do).  Same results
#: bit-for-bit (differentially tested).  The on-chip A/B that settled
#: the default
#: (2026-07-31 window, B=16384 L=1000 flagship): unroll 21,299 h/s vs
#: gather 13,451 h/s — the gather lowering dominated the closure cost
#: exactly as the roofline model predicted (benchmarks/RESULTS.md,
#: dense-kernel roofline; BENCH_tpu_windows.jsonl rows 18:15/18:17Z).
#: the default subset-union lowering — the ONE definition every
#: consumer (kernel build, bench diag reporting, headline-artifact
#: gating) reads, so a future default flip can't silently mislabel
#: bench windows or misroute the headline artifact
DEFAULT_UNION = "unroll"


def _union_mode() -> str:
    """Resolved subset-union lowering: ``JEPSEN_TPU_DENSE_UNION`` >
    active calibration (doc/tuning.md — ``jepsen_tpu tune``
    re-measures the unroll/gather/matmul gap per chip) >
    :data:`DEFAULT_UNION`.  The mode is part of the kernel cache key,
    so flipping it can never serve a stale lowering."""
    from ..tune import artifact as _cal

    return _cal.resolve_knob(
        "JEPSEN_TPU_DENSE_UNION",
        lambda v: v.strip() or None,
        lambda cal: cal.union_mode(),
        DEFAULT_UNION,
    )


def _subset_has(C: int):
    """has[j]: [W] uint32 mask of packed bits whose subset index has
    bit j SET — the "configs that linearized slot j" selector."""
    W = _n_words(C)
    k = np.arange(W)
    has = np.zeros((C, W), np.uint32)
    for j in range(C):
        if j < 5:
            has[j] = np.uint32(0xFFFFFFFF ^ _LOMASK[j])
        else:
            has[j] = np.where((k & (1 << (j - 5))) != 0, 0xFFFFFFFF, 0)
    return jnp.asarray(has)


def _or_fold(terms):
    """Tree-OR a static list of equal-shaped uint32 arrays."""
    terms = list(terms)
    while len(terms) > 1:
        terms = [
            terms[i] | terms[i + 1] if i + 1 < len(terms) else terms[i]
            for i in range(0, len(terms), 2)
        ]
    return terms[0]


def build_dense(
    spec_name: str, E: int, C: int, V, mr_shape=None, permits_shape=None,
    union: str = "gather",
):
    """Build the (unjitted) vmapped dense checker for fixed shapes.
    Signature matches wgl.build_batched's result: ``fn(init_state,
    ev_slot, cand_slot, cand_f, cand_a, cand_b) -> (ok, failed_at,
    overflow)`` — with ``overflow`` identically False.

    For ``multi-register`` pass ``mr_shape=(Vr, K)``: the automaton
    then runs over the COMPOSITE state space S = Vr^K (one digit per
    register) with transitions built from the per-register mop codes
    (a = value id, b = register index, step_kernels.py:81-94); V is
    ignored and S takes its place."""
    multi = spec_name == "multi-register"
    reentrant = spec_name == "reentrant-mutex"
    permits = spec_name == "acquired-permits"
    if permits:
        if permits_shape is None:
            raise ValueError("acquired-permits needs permits_shape=(N, P)")
        n_clients, n_permits = permits_shape
        V, acq_np, rel_np = permits_tables(int(n_clients), int(n_permits))
        pm_acq = jnp.asarray(acq_np)  # [N+1, S]
        pm_rel = jnp.asarray(rel_np)
    if multi:
        if mr_shape is None:
            raise ValueError("multi-register needs mr_shape=(Vr, K)")
        vr, kreg = mr_shape
        V = int(vr) ** int(kreg)
        # static digit tables: digit[s, k] of composite state s, and
        # same-except-one-register masks for write transitions
        s_ids = np.arange(V)
        digits_np = np.stack(
            [(s_ids // (vr ** k)) % vr for k in range(kreg)], axis=1
        )  # [S, K]
        same_ex_np = np.zeros((kreg, V, V), bool)  # [K, s', s]
        for k in range(kreg):
            others = np.delete(digits_np, k, axis=1)
            same_ex_np[k] = (others[:, None, :] == others[None, :, :]).all(
                axis=2
            )
        digits_T = jnp.asarray(digits_np.T)  # [K, S]
        same_ex = jnp.asarray(same_ex_np)
        eye_ss = jnp.asarray(np.eye(V, dtype=bool))
        mr_pow = jnp.asarray([vr ** k for k in range(kreg)], jnp.int32)
    elif spec_name not in DENSE_SPECS and not permits:
        raise ValueError(f"no dense kernel for spec {spec_name!r}")
    W = _n_words(C)
    max_closure = C + 2  # ≤C passes reach fixpoint; headroom is free
    uidx, umask, ushl, didx, dmask, dshr = _subset_maps(C)
    uidx_b = jnp.broadcast_to(uidx[:, None, :], (C, V, W))
    didx_b = jnp.broadcast_to(didx[:, None, :], (C, V, W))
    _check_union(union)
    union_unroll = union == "unroll"
    union_matmul = union == "matmul"
    if union_matmul:
        Pu, Pd = _subset_perms(C)

    def check_one(init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b):
        if multi:
            # packed byte-per-register int32 → composite state id
            from .step_kernels import MR_VALUE_BITS

            digs = (
                init_state >> (MR_VALUE_BITS * jnp.arange(kreg))
            ) & ((1 << MR_VALUE_BITS) - 1)
            init_state = jnp.sum(digs.astype(jnp.int32) * mr_pow)
        D0 = jnp.zeros((V, W), jnp.uint32)
        # one config: prefix value = init, empty linset (subset 0, bit 0)
        D0 = lax.dynamic_update_index_in_dim(
            D0, jnp.zeros((W,), jnp.uint32).at[0].set(1), init_state, 0
        )

        def event_body(carry, ev):
            D, done, failed_at, idx = carry
            e_slot, c_slot, c_f, c_a, c_b = ev
            is_pad = e_slot < 0
            c_f = c_f.astype(jnp.int32)
            c_a = c_a.astype(jnp.int32)
            c_b = c_b.astype(jnp.int32)

            # regroup candidate lanes by SLOT id (lanes are sorted by op
            # id, so slot j can sit at any lane; at most one lane holds
            # it) — the packed subset maps need the slot as the index
            eq = c_slot[None, :] == jnp.arange(C, dtype=c_slot.dtype)[:, None]
            active_s = eq.any(axis=1)
            f_s = jnp.sum(jnp.where(eq, c_f[None, :], 0), axis=1)
            a_s = jnp.sum(jnp.where(eq, c_a[None, :], 0), axis=1)
            b_s = jnp.sum(jnp.where(eq, c_b[None, :], 0), axis=1)

            if multi:
                # T[j, s', s] from per-register mop codes: a = value
                # id, b = register index.  write: every digit but reg b
                # unchanged, digit b of s' equals a.  read: s' == s and
                # digit b of s equals a.  read-any: s' == s.
                reg = jnp.clip(b_s, 0, kreg - 1)
                se = jnp.take(same_ex, reg, axis=0)  # [C, S, S]
                d_b = jnp.take(digits_T, reg, axis=0)  # [C, S]
                is_write = f_s == F_WRITE
                is_ra = f_s == F_READ_ANY
                am = a_s[:, None, None]
                T = jnp.where(
                    is_write[:, None, None],
                    se & (d_b[:, :, None] == am),
                    jnp.where(
                        is_ra[:, None, None],
                        eye_ss[None],
                        eye_ss[None] & (d_b[:, None, :] == am),  # read
                    ),
                ) & active_s[:, None, None]
            else:
                # per-slot [C, V, V] transition matrix T[j, v', v]: does
                # linearizing slot j move value v to v'?  (mutex ops are
                # cas in disguise: acquire=cas(0,1), release=cas(1,0))
                is_acq = f_s == F_ACQUIRE
                is_rel = f_s == F_RELEASE
                a_eff = jnp.where(is_acq, 0, jnp.where(is_rel, 1, a_s))
                b_eff = jnp.where(is_acq, 1, jnp.where(is_rel, 0, b_s))
                is_write = f_s == F_WRITE
                is_ra = f_s == F_READ_ANY
                cas_like = (f_s == F_CAS) | is_acq | is_rel
                vp = jnp.arange(V, dtype=jnp.int32)[None, :, None]  # v'
                vv = jnp.arange(V, dtype=jnp.int32)[None, None, :]  # v
                am = a_eff[:, None, None]
                bm = b_eff[:, None, None]
                if permits:
                    # table-driven transitions: tbl[a, s] names the one
                    # target state; -1 (invalid move) can never equal a
                    # state id, so no extra validity mask is needed
                    is_pacq = f_s == F_PACQUIRE
                    a_idx = jnp.clip(a_s, 0, pm_acq.shape[0] - 1)
                    acq_t = jnp.take(pm_acq, a_idx, axis=0)  # [C, S]
                    rel_t = jnp.take(pm_rel, a_idx, axis=0)
                    tbl = jnp.where(is_pacq[:, None], acq_t, rel_t)
                    T = (tbl[:, None, :] == vp) & active_s[:, None, None]
                elif reentrant:
                    # two-pair transitions over state ids {0 free,
                    # 2c-1 once, 2c twice} (a = client id c); a
                    # reentrant batch carries ONLY racq/rrel codes, so
                    # the register nest below never applies — gated at
                    # trace time to keep it out of the flagship path
                    is_racq = f_s == F_RACQUIRE
                    once = (2 * a_s - 1)[:, None, None]
                    twice = (2 * a_s)[:, None, None]
                    racq_T = ((vv == 0) & (vp == once)) | (
                        (vv == once) & (vp == twice)
                    )
                    rrel_T = ((vv == twice) & (vp == once)) | (
                        (vv == once) & (vp == 0)
                    )
                    T = jnp.where(
                        is_racq[:, None, None], racq_T, rrel_T
                    ) & active_s[:, None, None]
                else:
                    T = jnp.where(
                        is_write[:, None, None],
                        vp == am,
                        jnp.where(
                            is_ra[:, None, None],
                            vp == vv,
                            jnp.where(
                                cas_like[:, None, None],
                                (vp == bm) & (vv == am),
                                (vp == am) & (vv == am),  # read
                            ),
                        ),
                    ) & active_s[:, None, None]

            # --- closure: linearize open ops until fixpoint; every slot
            # advances in one vectorized pass ---
            def cond(c):
                _, changed, i = c
                return changed & (i < max_closure)

            def body(c):
                Dc, _, i = c
                # X[j, v', w] = OR_v (T[j, v', v] & Dc[v, w])
                X = _or_fold(
                    jnp.where(T[:, :, v, None], Dc[v][None, None, :], jnp.uint32(0))
                    for v in range(V)
                )
                # subset-union map s → s | bit_j, packed axis
                if union_unroll:
                    add = _or_fold(
                        ((X[j] if j < 5 else _xor_permute(X[j], 1 << (j - 5)))
                         & umask[j][None, :]) << ushl[j]
                        for j in range(C)
                    )
                elif union_matmul:
                    # every slot's word permutation as one batched
                    # one-hot uint32 matmul over the packed axis
                    U = jnp.einsum("jvw,jwk->jvk", X, Pu)
                    U = (U & umask[:, None, :]) << ushl[:, None, None]
                    add = _or_fold(U[j] for j in range(C))
                else:
                    U = jnp.take_along_axis(X, uidx_b, axis=2)
                    U = (U & umask[:, None, :]) << ushl[:, None, None]
                    add = _or_fold(U[j] for j in range(C))
                Dn = Dc | add
                changed = (Dn != Dc).any()
                return (Dn, changed, i + 1)

            Dc, _, _ = lax.while_loop(
                cond, body, (D, jnp.bool_(True), jnp.int32(0))
            )

            # --- completion: keep configs that linearized e_slot, then
            # promote it out of the linset (slot frees for reuse) ---
            if union_unroll:
                Dvar = jnp.stack(
                    [
                        ((Dc if j < 5 else _or_select(Dc, 1 << (j - 5)))
                         >> dshr[j]) & dmask[j][None, :]
                        for j in range(C)
                    ]
                )
            elif union_matmul:
                Ds = jnp.einsum("vw,jwk->jvk", Dc, Pd)
                Dvar = (Ds >> dshr[:, None, None]) & dmask[:, None, :]
            else:
                Ds = jnp.take_along_axis(
                    jnp.broadcast_to(Dc[None], (C, V, W)), didx_b, axis=2
                )
                Dvar = (Ds >> dshr[:, None, None]) & dmask[:, None, :]
            onehot = (e_slot == jnp.arange(C))[:, None, None]
            Df = _or_fold(
                jnp.where(onehot[j], Dvar[j], jnp.uint32(0)) for j in range(C)
            )
            empty = ~(Df != 0).any()

            done2 = done | (~is_pad & empty)
            # dead rows park on an empty frontier: the closure on zeros
            # converges in one pass, so finished histories stop dragging
            # the batch-synchronized while_loop
            D2 = jnp.where(
                done2, jnp.uint32(0), jnp.where(is_pad, D, Df)
            )
            failed_at2 = jnp.where(done | is_pad | ~empty, failed_at, idx)
            return (D2, done2, failed_at2, idx + 1), None

        carry0 = (D0, jnp.bool_(False), jnp.int32(-1), jnp.int32(0))
        (_, done, failed_at, _), _ = lax.scan(
            event_body,
            carry0,
            (ev_slot, cand_slot, cand_f, cand_a, cand_b),
        )
        return ~done, failed_at, jnp.bool_(False)

    return jax.vmap(check_one)


def build_dense_queue(E: int, C: int, union: str = "gather"):
    """Dense unordered-queue kernel: unique-value enqueues/dequeues
    commute, so a config's multiset state is a pure function of its
    linset — the search state collapses to ONE packed bitset over the
    2^C subsets (the register kernel with its value axis removed), plus
    two carried uint32 value-bitsets for the promoted prefix:

        enqC bit v: v was enqueued by a completed op (or initially)
        deqC bit v: v was dequeued by a completed op

    Per candidate the legal-source-subset mask is static algebra:
    enqueues are always legal; a dequeue of v may linearize from
    subsets where v is present — (enq completed, or the open enqueue's
    slot bit is set) and no other open dequeue of v's bit is set and v
    wasn't already dequeued by the prefix.  Closure/completion are the
    same masked-shift subset maps as the register kernel; no sorts,
    no overflow."""
    W = _n_words(C)
    max_closure = C + 2
    uidx, umask, ushl, didx, dmask, dshr = _subset_maps(C)
    _check_union(union)
    union_unroll = union == "unroll"
    union_matmul = union == "matmul"
    if union_matmul:
        Pu, Pd = _subset_perms(C)
    has = _subset_has(C)
    ones = jnp.full((W,), 0xFFFFFFFF, jnp.uint32)
    zeros = jnp.zeros((W,), jnp.uint32)

    def check_one(init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b):
        D0 = jnp.zeros((W,), jnp.uint32).at[0].set(1)  # empty linset
        enqC0 = init_state.astype(jnp.uint32)  # initial contents bitset
        deqC0 = jnp.uint32(0)

        def event_body(carry, ev):
            D, enqC, deqC, done, failed_at, idx = carry
            e_slot, c_slot, c_f, c_a, c_b = ev
            is_pad = e_slot < 0

            # regroup candidate lanes by slot id (cf. register kernel)
            eq = c_slot[None, :] == jnp.arange(C, dtype=c_slot.dtype)[:, None]
            active_s = eq.any(axis=1)
            f_s = jnp.sum(jnp.where(eq, c_f[None, :], 0), axis=1)
            a_s = jnp.sum(jnp.where(eq, c_a[None, :], 0), axis=1)

            is_enq = active_s & (f_s == F_ENQUEUE)
            is_deq = active_s & (f_s == F_DEQUEUE)
            # value ids are 1-based; clamp inactive lanes' shift to 0
            shift = jnp.where(active_s, a_s - 1, 0).astype(jnp.uint32)
            vbit = jnp.where(
                active_s, jnp.uint32(1) << shift, jnp.uint32(0)
            )

            # per-slot-pair value match: does slot k hold the open
            # enqueue (resp. another open dequeue) of slot j's value?
            same_val = a_s[:, None] == a_s[None, :]
            enq_at = same_val & is_enq[None, :] & is_deq[:, None]
            other_deq = (
                same_val & is_deq[None, :] & is_deq[:, None]
                & ~jnp.eye(C, dtype=bool)
            )
            # [C, W] masks via one-hot folds over the static has-table
            e_mask = _or_fold(
                jnp.where(enq_at[:, k, None], has[k][None, :], jnp.uint32(0))
                for k in range(C)
            )
            forbid = _or_fold(
                jnp.where(
                    other_deq[:, k, None], has[k][None, :], jnp.uint32(0)
                )
                for k in range(C)
            )

            enq_done = (enqC & vbit) != 0   # [C] per-slot: v in prefix
            deq_done = (deqC & vbit) != 0
            enq_part = jnp.where(
                enq_done[:, None], ones[None, :], e_mask
            )
            valid = jnp.where(
                is_deq[:, None],
                jnp.where(
                    deq_done[:, None], zeros[None, :], enq_part & ~forbid
                ),
                jnp.where(is_enq[:, None], ones[None, :], zeros[None, :]),
            )

            # --- closure to fixpoint ---
            def cond(c):
                _, changed, i = c
                return changed & (i < max_closure)

            def body(c):
                Dc, _, i = c
                X = Dc[None, :] & valid           # [C, W] legal sources
                if union_unroll:
                    add = _or_fold(
                        ((X[j] if j < 5 else _xor_permute(X[j], 1 << (j - 5)))
                         & umask[j]) << ushl[j]
                        for j in range(C)
                    )
                elif union_matmul:
                    U = jnp.einsum("jw,jwk->jk", X, Pu)
                    U = (U & umask) << ushl[:, None]
                    add = _or_fold(U[j] for j in range(C))
                else:
                    U = jnp.take_along_axis(X, uidx, axis=1)
                    U = (U & umask) << ushl[:, None]
                    add = _or_fold(U[j] for j in range(C))
                Dn = Dc | add
                return (Dn, (Dn != Dc).any(), i + 1)

            Dc, _, _ = lax.while_loop(
                cond, body, (D, jnp.bool_(True), jnp.int32(0))
            )

            # --- completion: filter + promote e_slot ---
            if union_unroll:
                Dvar = jnp.stack(
                    [
                        ((Dc if j < 5 else _or_select(Dc, 1 << (j - 5)))
                         >> dshr[j]) & dmask[j]
                        for j in range(C)
                    ]
                )
            elif union_matmul:
                Ds = jnp.einsum("w,jwk->jk", Dc, Pd)
                Dvar = (Ds >> dshr[:, None]) & dmask
            else:
                Ds = jnp.take_along_axis(
                    jnp.broadcast_to(Dc[None], (C, W)), didx, axis=1
                )
                Dvar = (Ds >> dshr[:, None]) & dmask
            onehot = e_slot == jnp.arange(C)
            Df = _or_fold(
                jnp.where(onehot[j], Dvar[j], jnp.uint32(0)) for j in range(C)
            )
            empty = ~(Df != 0).any()

            # bake the completing op's effect into the prefix bitsets
            comp_enq = (onehot & is_enq).any()
            comp_deq = (onehot & is_deq).any()
            comp_vbit = jnp.sum(jnp.where(onehot, vbit, jnp.uint32(0)))
            enqC2 = jnp.where(~is_pad & comp_enq, enqC | comp_vbit, enqC)
            deqC2 = jnp.where(~is_pad & comp_deq, deqC | comp_vbit, deqC)

            done2 = done | (~is_pad & empty)
            D2 = jnp.where(done2, jnp.uint32(0), jnp.where(is_pad, D, Df))
            failed_at2 = jnp.where(done | is_pad | ~empty, failed_at, idx)
            return (D2, enqC2, deqC2, done2, failed_at2, idx + 1), None

        carry0 = (
            D0, enqC0, deqC0, jnp.bool_(False), jnp.int32(-1), jnp.int32(0)
        )
        (_, _, _, done, failed_at, _), _ = lax.scan(
            event_body,
            carry0,
            (ev_slot, cand_slot, cand_f, cand_a, cand_b),
        )
        return ~done, failed_at, jnp.bool_(False)

    return jax.vmap(check_one)


def make_dense_fn(spec_name: str, E: int, C: int, V):
    """Jitted, cached dense checker (same contract as wgl.make_check_fn).
    The queue kernel has no value axis, so V is normalized out of its
    cache key — otherwise every distinct value-domain (and any initial
    bitset contents, whose numeric max can be huge) would re-jit a
    byte-identical kernel.  For multi-register, V is the (Vr, K)
    composite-shape pair; for acquired-permits the (N, P) client/permit
    pair."""
    if spec_name == "unordered-queue":
        V = 0
    # the union-map mode is part of the cache key: flipping
    # JEPSEN_TPU_DENSE_UNION must rebuild, not hit the old lowering
    union = _union_mode()
    fn = _make_dense_fn_cached(spec_name, E, C, V, union)
    from . import wgl as wgl_mod

    if not hasattr(fn, "safe_dispatch"):
        # dense kernels are overflow-free with no crash-calibrated
        # footprint ceiling (B=16384 runs clean, wgl.py calibration
        # notes), so they carry the full default cap — every dispatch
        # site (check_batch, the pipelined engine) reads ONE
        # ``fn.safe_dispatch`` attribute instead of special-casing
        # engines.  Like the frontier caps this is a PER-CHIP number:
        # on a mesh the engine dispatches n_devices × this many rows
        # per chunk through the fn's shard_map variant
        # (parallel.mesh.shard_fn), each chip holding exactly one cap
        # worth (doc/checker-engines.md "Slice-native dispatch")
        fn.safe_dispatch = wgl_mod.DEFAULT_MAX_DISPATCH
    if wgl_mod.count_kernel_build(fn):
        # engine telemetry: a fresh build means a new (shape, lowering)
        # variant — the jit trace + XLA compile lands on its first
        # dispatch (wgl._timed_run_chunked records it as compile time)
        from .. import obs

        obs.count(
            "jepsen_kernel_builds_total", engine="dense", union=union,
            spec=spec_name,
        )
    return fn


@lru_cache(maxsize=64)
def _make_dense_fn_cached(spec_name: str, E: int, C: int, V, union="gather"):  # jt: allow[budget-missing-cap] — capped by the make_dense_fn wrapper (stamps wgl.DEFAULT_MAX_DISPATCH)  jt: jaxpr(dot_generals<=2*E, dtype=uint32)
    if spec_name == "unordered-queue":
        fn = jax.jit(build_dense_queue(E, C, union=union))
    elif spec_name == "multi-register":
        fn = jax.jit(build_dense(spec_name, E, C, 0, mr_shape=V,
                                 union=union))
    elif spec_name == "acquired-permits":
        fn = jax.jit(build_dense(spec_name, E, C, 0, permits_shape=V,
                                 union=union))
    else:
        fn = jax.jit(build_dense(spec_name, E, C, V, union=union))
    fn.union_mode = union  # rides the mesh shard_fn cache key
    return fn
