"""Dense subset-automaton linearizability kernel for register-family
models — the TPU-first fast path.

The generic WGL kernel (jepsen_tpu.ops.wgl) keeps an explicit frontier of
``(state, linset)`` configs and pays a sort-based dedup/compaction on
every closure step; its capacity F can overflow, degrading to "unknown".
For models whose state enumerates to a small integer domain — read/write
registers, CAS registers, mutexes (the knossos models the reference's
linearizable checker actually runs, jepsen/src/jepsen/checker.clj:19-26)
— there is a representation that maps far better onto a vector machine:

    D[v, s] = 1  iff some linearization order of the ops in subset ``s``
              (of the ≤C currently-open slots) takes the register from
              the promoted prefix to value id ``v``.

``D`` is a *dense* boolean tensor over (value id × linset subset), bit-
packed along the subset axis into uint32 words.  Every WGL operation
becomes a static, branch-free tensor op:

- *value transition*: per event a [C, V, V] one-hot transition matrix is
  built from the candidate op codes (read keeps one value row, write
  folds every row into one, cas moves row a to row b, mutex ops are cas
  in disguise); applying it is a short OR-tree of selects.
- *closure* (linearize open op j): the subset map ``s → s | bit_j`` is,
  on the packed axis, a masked word shift for j < 5 and a static word
  permutation for j ≥ 5 — all C slots advance in ONE vectorized step per
  pass.  No sort, no dedup (the set representation dedups for free), and
  **overflow cannot happen**.
- *completion of slot s* (filter configs that linearized s, promote it):
  the inverse map ``s' → s' \\ bit_s``, a masked shift/permutation again,
  selected among C static variants by the completing slot id.

Per event the closure runs to fixpoint in ≤C passes (a chain linearizes
each open op at most once), so ``lax.while_loop`` capped at C+2 is exact
— there is no truncation/"unknown" path at all.  The whole search is a
``lax.scan`` over events, vmapped over histories, sharded over the
device mesh like the generic kernel.

Cost per event is a handful of fused vector ops on [C, V, 2^C/32]
uint32 tensors — for the practical C ≤ 12, V ≤ 32 envelope a few KB per
history — versus the generic kernel's two O((F + F·C) log) sorts per
closure pass.  Measured on one TPU chip this is orders of magnitude
faster (see bench.py).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .step_kernels import (
    F_READ,
    F_WRITE,
    F_CAS,
    F_READ_ANY,
    F_ACQUIRE,
    F_RELEASE,
)

#: specs whose state is exactly "current value id" (mutex: 0=free 1=held)
DENSE_SPECS = ("register", "cas-register", "mutex")

#: dense envelope: beyond these the generic frontier kernel takes over
MAX_C = 12   # 2^12 subsets = 128 packed words
MAX_V = 32

#: _LOMASK[j]: bits of a 32-subset word whose subset index has bit j clear
_LOMASK = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)


def applicable(spec_name: str, C: int, V: int) -> bool:
    return spec_name in DENSE_SPECS and C <= MAX_C and V <= MAX_V


def _n_words(C: int) -> int:
    return max(1, (1 << C) // 32)


def _subset_maps(C: int):
    """Static per-slot tables for the packed-axis subset maps.

    union (``s → s | bit_j``, image restricted to s ∋ j):
        out[k] = (x[uidx[j,k]] & umask[j,k]) << ushl[j]
    drop (``s → s \\ bit_j``, image restricted to s ∌ j):
        out[k] = (x[didx[j,k]] >> dshr[j]) & dmask[j,k]

    For j < 5 the map moves bits inside a word (mask + shift); for j ≥ 5
    it permutes whole words (static gather + output mask).
    """
    W = _n_words(C)
    k = np.arange(W)
    uidx = np.zeros((C, W), np.int32)
    umask = np.zeros((C, W), np.uint32)
    ushl = np.zeros((C,), np.uint32)
    didx = np.zeros((C, W), np.int32)
    dmask = np.zeros((C, W), np.uint32)
    dshr = np.zeros((C,), np.uint32)
    for j in range(C):
        if j < 5:
            uidx[j] = k
            umask[j] = _LOMASK[j]
            ushl[j] = 1 << j
            didx[j] = k
            dmask[j] = _LOMASK[j]
            dshr[j] = 1 << j
        else:
            wb = 1 << (j - 5)
            uidx[j] = k ^ wb
            umask[j] = np.where((k & wb) != 0, 0xFFFFFFFF, 0)
            didx[j] = k | wb
            dmask[j] = np.where((k & wb) == 0, 0xFFFFFFFF, 0)
    return (
        jnp.asarray(uidx),
        jnp.asarray(umask),
        jnp.asarray(ushl),
        jnp.asarray(didx),
        jnp.asarray(dmask),
        jnp.asarray(dshr),
    )


def _or_fold(terms):
    """Tree-OR a static list of equal-shaped uint32 arrays."""
    terms = list(terms)
    while len(terms) > 1:
        terms = [
            terms[i] | terms[i + 1] if i + 1 < len(terms) else terms[i]
            for i in range(0, len(terms), 2)
        ]
    return terms[0]


def build_dense(spec_name: str, E: int, C: int, V: int):
    """Build the (unjitted) vmapped dense checker for fixed shapes.
    Signature matches wgl.build_batched's result: ``fn(init_state,
    ev_slot, cand_slot, cand_f, cand_a, cand_b) -> (ok, failed_at,
    overflow)`` — with ``overflow`` identically False."""
    if spec_name not in DENSE_SPECS:
        raise ValueError(f"no dense kernel for spec {spec_name!r}")
    W = _n_words(C)
    max_closure = C + 2  # ≤C passes reach fixpoint; headroom is free
    uidx, umask, ushl, didx, dmask, dshr = _subset_maps(C)
    uidx_b = jnp.broadcast_to(uidx[:, None, :], (C, V, W))
    didx_b = jnp.broadcast_to(didx[:, None, :], (C, V, W))

    def check_one(init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b):
        D0 = jnp.zeros((V, W), jnp.uint32)
        # one config: prefix value = init, empty linset (subset 0, bit 0)
        D0 = lax.dynamic_update_index_in_dim(
            D0, jnp.zeros((W,), jnp.uint32).at[0].set(1), init_state, 0
        )

        def event_body(carry, ev):
            D, done, failed_at, idx = carry
            e_slot, c_slot, c_f, c_a, c_b = ev
            is_pad = e_slot < 0
            c_f = c_f.astype(jnp.int32)
            c_a = c_a.astype(jnp.int32)
            c_b = c_b.astype(jnp.int32)

            # regroup candidate lanes by SLOT id (lanes are sorted by op
            # id, so slot j can sit at any lane; at most one lane holds
            # it) — the packed subset maps need the slot as the index
            eq = c_slot[None, :] == jnp.arange(C, dtype=c_slot.dtype)[:, None]
            active_s = eq.any(axis=1)
            f_s = jnp.sum(jnp.where(eq, c_f[None, :], 0), axis=1)
            a_s = jnp.sum(jnp.where(eq, c_a[None, :], 0), axis=1)
            b_s = jnp.sum(jnp.where(eq, c_b[None, :], 0), axis=1)

            # per-slot [C, V, V] transition matrix T[j, v', v]: does
            # linearizing slot j move value v to v'?  (mutex ops are cas
            # in disguise: acquire=cas(0,1), release=cas(1,0))
            is_acq = f_s == F_ACQUIRE
            is_rel = f_s == F_RELEASE
            a_eff = jnp.where(is_acq, 0, jnp.where(is_rel, 1, a_s))
            b_eff = jnp.where(is_acq, 1, jnp.where(is_rel, 0, b_s))
            is_write = f_s == F_WRITE
            is_ra = f_s == F_READ_ANY
            cas_like = (f_s == F_CAS) | is_acq | is_rel
            vp = jnp.arange(V, dtype=jnp.int32)[None, :, None]  # v'
            vv = jnp.arange(V, dtype=jnp.int32)[None, None, :]  # v
            am = a_eff[:, None, None]
            bm = b_eff[:, None, None]
            T = jnp.where(
                is_write[:, None, None],
                vp == am,
                jnp.where(
                    is_ra[:, None, None],
                    vp == vv,
                    jnp.where(
                        cas_like[:, None, None],
                        (vp == bm) & (vv == am),
                        (vp == am) & (vv == am),  # read
                    ),
                ),
            ) & active_s[:, None, None]

            # --- closure: linearize open ops until fixpoint; every slot
            # advances in one vectorized pass ---
            def cond(c):
                _, changed, i = c
                return changed & (i < max_closure)

            def body(c):
                Dc, _, i = c
                # X[j, v', w] = OR_v (T[j, v', v] & Dc[v, w])
                X = _or_fold(
                    jnp.where(T[:, :, v, None], Dc[v][None, None, :], jnp.uint32(0))
                    for v in range(V)
                )
                # subset-union map s → s | bit_j, packed axis
                U = jnp.take_along_axis(X, uidx_b, axis=2)
                U = (U & umask[:, None, :]) << ushl[:, None, None]
                add = _or_fold(U[j] for j in range(C))
                Dn = Dc | add
                changed = (Dn != Dc).any()
                return (Dn, changed, i + 1)

            Dc, _, _ = lax.while_loop(
                cond, body, (D, jnp.bool_(True), jnp.int32(0))
            )

            # --- completion: keep configs that linearized e_slot, then
            # promote it out of the linset (slot frees for reuse) ---
            Ds = jnp.take_along_axis(
                jnp.broadcast_to(Dc[None], (C, V, W)), didx_b, axis=2
            )
            Dvar = (Ds >> dshr[:, None, None]) & dmask[:, None, :]
            onehot = (e_slot == jnp.arange(C))[:, None, None]
            Df = _or_fold(
                jnp.where(onehot[j], Dvar[j], jnp.uint32(0)) for j in range(C)
            )
            empty = ~(Df != 0).any()

            done2 = done | (~is_pad & empty)
            # dead rows park on an empty frontier: the closure on zeros
            # converges in one pass, so finished histories stop dragging
            # the batch-synchronized while_loop
            D2 = jnp.where(
                done2, jnp.uint32(0), jnp.where(is_pad, D, Df)
            )
            failed_at2 = jnp.where(done | is_pad | ~empty, failed_at, idx)
            return (D2, done2, failed_at2, idx + 1), None

        carry0 = (D0, jnp.bool_(False), jnp.int32(-1), jnp.int32(0))
        (_, done, failed_at, _), _ = lax.scan(
            event_body,
            carry0,
            (ev_slot, cand_slot, cand_f, cand_a, cand_b),
        )
        return ~done, failed_at, jnp.bool_(False)

    return jax.vmap(check_one)


@lru_cache(maxsize=64)
def make_dense_fn(spec_name: str, E: int, C: int, V: int):
    """Jitted, cached dense checker (same contract as wgl.make_check_fn)."""
    return jax.jit(build_dense(spec_name, E, C, V))
