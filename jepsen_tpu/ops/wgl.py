"""The TPU linearizability search: batched bitset frontier expansion.

Implements the same event-driven just-in-time linearization as the CPU
oracle (jepsen_tpu.checker.linear, the knossos.wgl equivalent consumed by
the reference at jepsen/src/jepsen/checker.clj:199-203), recast for SIMD:

- A *config* is ``(state:int32, linset:uint32)`` — model state plus a
  bitset of linearized-but-not-returned ops, addressed by transient slot
  ids (see jepsen_tpu.ops.encode for why one word suffices).
- The *frontier* is a fixed-capacity array of F configs with a validity
  mask.  All frontier × candidate expansions happen in one broadcast
  step-kernel call; dedup/compaction in the hot path is an O(K)
  scatter-hash-table pass plus a prefix-sum gather (no sorts — see
  ``_compact_hash``), so cost scales linearly with frontier capacity.
  An exact ``lax.sort``-based variant (``_compact_sort``) backs the
  provably-lossless escalation rung.
- Each *ok* event runs a closure loop (``lax.while_loop``, converging
  when the config count stops growing) then filters configs that
  linearized the completing op and promotes it into the common prefix.
- The whole per-history search is a ``lax.scan`` over events, ``vmap``-ed
  over a batch of histories; batches shard across a device mesh on the
  history axis (jepsen_tpu.parallel.mesh).

Frontier overflow is tracked and reported as ``"unknown"`` rather than
silently dropping configs — the same honesty contract as the reference's
check-safe (checker.clj:74-85).
"""

from __future__ import annotations

import threading
from functools import lru_cache, partial
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..history import History
from .. import models as m
from .. import obs
from . import encode as encode_mod
from .step_kernels import ModelSpec, spec_for

DEFAULT_FRONTIER = 128
DEFAULT_SLOT_CAP = encode_mod.DEFAULT_SLOT_CAP

#: plain int, converted at trace time — a module-level jnp scalar would
#: initialize the device backend at IMPORT, hanging on a wedged tunnel
_INVALID_KEY = 0xFFFFFFFF


def supported(model: m.Model) -> bool:
    return spec_for(model) is not None


def _hash_cfg(state, words):
    """31-bit mix of (state, *linset words); 0xFFFFFFFF is reserved for
    invalid lanes."""
    h = state.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    h = h ^ (h >> 16)
    for w in words:
        h = h + w * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
    return h & jnp.uint32(0x7FFFFFFF)


def _compact_sort(states, words, valid, F, n_old):
    """Exact dedup + compact K candidate configs down to F slots.
    ``words`` is the tuple of linset words (one uint32 array per 32
    slots); lanes < ``n_old`` are the incoming frontier, lanes ≥ it the
    newly-expanded candidates.  Returns
    (states[F], words[F]×W, valid[F], grew?, overflowed?) where *grew*
    is True iff a lane from the new region survived dedup — i.e. a
    config not present in the old region exists (the sort is stable, so
    within a duplicate class the earliest lane survives, and an old
    twin always precedes its new copies).

    One multi-operand sort groups duplicates (invalid lanes sort to the
    end via the reserved key); survivors are then compacted by *rank*:
    the j-th output slot gathers the entry whose survivor-prefix-count
    equals j — a [F, K] compare-reduce plus one gather.  Dedup here is
    EXACT (every duplicate is removed), which is what makes the
    sufficient-frontier escalation rung lossless by construction — but
    the sort plus the rank matrix cost O(K log K + F·K), superlinear in
    F, so the hot path uses ``_compact_hash`` instead."""
    K = states.shape[0]
    key = jnp.where(valid, _hash_cfg(states, words), jnp.uint32(_INVALID_KEY))
    lane = jnp.arange(K, dtype=jnp.int32)
    # the FULL config is part of the sort key (not just its 31-bit
    # hash): with a hash-only key, two identical configs separated by a
    # hash-colliding distinct config are non-adjacent and the
    # neighbor-compare would miss the duplicate — breaking the "every
    # duplicate removed" contract the sufficient rung rests on.  lane
    # stays a payload so stability keeps old twins before new copies.
    sorted_ops = lax.sort(
        (key, states) + tuple(words) + (lane,), num_keys=2 + len(words)
    )
    key_s, st_s = sorted_ops[0], sorted_ops[1]
    ws_s, lane_s = sorted_ops[2:-1], sorted_ops[-1]
    same = (key_s[1:] == key_s[:-1]) & (st_s[1:] == st_s[:-1])
    for w in ws_s:
        same = same & (w[1:] == w[:-1])
    dup = jnp.concatenate([jnp.zeros((1,), bool), same])
    v2 = (key_s != jnp.uint32(_INVALID_KEY)) & ~dup
    grew = (v2 & (lane_s >= n_old)).any()
    out_states, out_words, out_valid, ovf = _rank_gather(st_s, ws_s, v2, F)
    return out_states, out_words, out_valid, grew, ovf


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


#: independent Fibonacci-style multipliers, one scatter table per probe
_PROBE_MULTS = (0x9E3779B1, 0x85EBCA77)


def _probe_dedup(states, words, valid):
    """Best-effort duplicate removal via scatter-min probe tables:
    returns the post-dedup validity mask ``v2`` (see _compact_hash's
    docstring for the survivor-minimum argument).  Shared by the hash
    and gather compactions — the 'gather is hash with only the
    compaction lowering swapped' equivalence depends on the two modes
    running this exact dedup, so it exists once."""
    K = states.shape[0]
    T = _next_pow2(2 * K)  # load factor ≤ 0.5 keeps foreign collisions rare
    shift = jnp.uint32(32 - (T - 1).bit_length())
    h0 = _hash_cfg(states, words)
    lane = jnp.arange(K, dtype=jnp.int32)
    lane_or_big = jnp.where(valid, lane, K)
    dup = jnp.zeros((K,), bool)
    for mult in _PROBE_MULTS:
        hx = ((h0 * jnp.uint32(mult)) >> shift).astype(jnp.int32)
        tbl = jnp.full((T,), K, jnp.int32).at[hx].min(lane_or_big)
        w = tbl[hx]
        w_safe = jnp.minimum(w, K - 1)
        same = states[w_safe] == states
        for wd in words:
            same = same & (wd[w_safe] == wd)
        dup = dup | (valid & (w < lane) & same)
    return valid & ~dup


def _compact_hash(states, words, valid, F, n_old):
    """Best-effort dedup + compact via scatter hash tables and a
    prefix-sum gather — O(K) work, no sorts, so cost scales *linearly*
    with frontier capacity (raising F to cut overflow no longer slows
    the kernel superlinearly the way the sort compaction did).

    Each probe table scatters lane ids by config hash with a
    min-reduce; a lane whose slot *winner* is an earlier lane holding
    an identical config is a duplicate and drops out.  The MINIMUM lane
    of every identical-config class always survives (any equal-config
    winner is in the class, hence ≥ the class minimum, so the minimum's
    winner can only be itself) — so a dropped lane always leaves an
    earlier identical survivor, and old-frontier lanes (< ``n_old``)
    are never displaced by their new copies.  Distinct configs sharing
    a slot both survive — missed dedup costs capacity, never
    correctness, and the only lossy event remains compaction overflow
    (survivors > F), which is reported as "unknown" exactly as before.
    Two independent probe tables catch most duplicates one misses.

    Returns (states[F], words[F]×W, valid[F], grew?, overflowed?).
    *grew* is True iff any lane ≥ ``n_old`` survived dedup.  Dropping
    is driven by EXACT config equality with the winner, so every
    dropped new lane provably duplicates an old-region config (or an
    earlier new lane, transitively): grew == False is an exact
    certificate that the closure reached its fixpoint, even though
    dedup itself is best-effort (a missed duplicate only makes grew
    True spuriously — one wasted iteration, never a wrong verdict).

    Compaction goes through :func:`_rank_gather` — the ONE code path
    every mode compacts through, so the "same survivor order across
    lowerings" invariant lives in one place (it used to carry an
    inline scatter copy of the prefix-sum compaction; equivalence with
    that lowering is pinned by a regression test).  This makes "hash"
    and "gather" the same lowering — both names stay accepted by the
    A/B env switch."""
    K = states.shape[0]
    v2 = _probe_dedup(states, words, valid)
    lane = jnp.arange(K, dtype=jnp.int32)
    grew = (v2 & (lane >= n_old)).any()
    out_states, out_words, out_valid, ovf = _rank_gather(states, words, v2, F)
    return out_states, out_words, out_valid, grew, ovf


def _rank_gather(states, words, v2, F):
    """Compact the surviving lanes into F slots by *rank* — the j-th
    output slot gathers the lane whose survivor-prefix-count equals j —
    as a [F, K] compare-reduce plus gathers.  This is the scatter-free
    lowering of the prefix-sum compaction: scatters serialize badly on
    TPU (they lower to sorted per-element updates), while the rank
    matrix is plain VPU broadcast work and the gathers are contiguous.
    Survivor order is lane order, identical to the scatter compaction,
    so verdicts cannot depend on which lowering ran.  Out-of-range
    slots gather a clamped lane and are masked invalid."""
    K = states.shape[0]
    prefix = jnp.cumsum(v2.astype(jnp.int32))
    count = prefix[-1]
    j = jnp.arange(F, dtype=jnp.int32)
    src = jnp.sum(prefix[None, :] <= j[:, None], axis=1, dtype=jnp.int32)
    src = jnp.minimum(src, K - 1)
    return (
        states[src],
        tuple(w[src] for w in words),
        j < count,
        count > F,
    )


#: "gather" was the hash-probe dedup with the scatter compaction
#: replaced by the rank-matrix gather; since _compact_hash itself now
#: compacts through _rank_gather the two modes are the SAME lowering —
#: the name stays accepted so pinned A/B configs keep working
_compact_gather = _compact_hash


#: [K, K] equality matrices get big; cap the per-dispatch rows so the
#: all-pairs mode's broadcast intermediates stay within a bounded HBM
#: footprint (elements, i.e. K*K booleans per batch row)
ALLPAIRS_ELEM_BUDGET = 128_000_000


def _compact_allpairs(states, words, valid, F, n_old):
    """EXACT dedup + compact with zero scatter ops: an all-pairs
    [K, K] config-equality matrix marks every lane that duplicates an
    earlier valid lane, then the rank-matrix gather compacts.  O(K²)
    work — asymptotically worse than the hash tables — but every
    operation is a broadcast compare / reduction / gather, the shapes
    XLA tiles best on TPU, and there is no hash-collision best-effort
    caveat: like the sort mode, every duplicate is removed, so the
    sufficient-frontier escalation rung's lossless-by-construction
    argument holds, and ``grew`` is an exact fixpoint certificate with
    no spurious extra iterations.  Intended for small frontiers
    (K = F·(C+1) up to a few hundred), where K² stays cheaper than the
    serialized scatters it replaces; ``make_check_fn`` shrinks the
    safe dispatch cap accordingly (ALLPAIRS_ELEM_BUDGET)."""
    K = states.shape[0]
    lane = jnp.arange(K, dtype=jnp.int32)
    eq = states[:, None] == states[None, :]
    for w in words:
        eq = eq & (w[:, None] == w[None, :])
    earlier = valid[None, :] & (lane[None, :] < lane[:, None])
    dup = (eq & earlier).any(axis=1)
    v2 = valid & ~dup
    grew = (v2 & (lane >= n_old)).any()
    out_states, out_words, out_valid, ovf = _rank_gather(states, words, v2, F)
    return out_states, out_words, out_valid, grew, ovf


_COMPACTIONS = {
    "hash": _compact_hash,
    "sort": _compact_sort,
    "gather": _compact_gather,
    "allpairs": _compact_allpairs,
}

#: compaction modes whose dedup removes EVERY duplicate — the property
#: the sufficient-frontier escalation rung's lossless claim rests on
EXACT_COMPACTIONS = frozenset({"sort", "allpairs"})


def _get_bit(words, slot_u):
    """Extract the linset bit for uint32 slot ids; ``words[w]`` holds
    slots [32w, 32w+32).  Broadcasting follows the operands'."""
    sh = slot_u & jnp.uint32(31)
    word_ix = slot_u >> jnp.uint32(5)
    bit = jnp.zeros_like(words[0] >> sh)
    for w, word in enumerate(words):
        bit = jnp.where(word_ix == w, (word >> sh) & jnp.uint32(1), bit)
    return bit


def _set_bit(words, slot_u):
    """Return words with the bit for each slot id set."""
    sh = slot_u & jnp.uint32(31)
    word_ix = slot_u >> jnp.uint32(5)
    mask = jnp.uint32(1) << sh
    return tuple(
        jnp.where(word_ix == w, word | mask, word)
        for w, word in enumerate(words)
    )


def _clear_bit(words, slot_u):
    sh = slot_u & jnp.uint32(31)
    word_ix = slot_u >> jnp.uint32(5)
    mask = ~(jnp.uint32(1) << sh)
    return tuple(
        jnp.where(word_ix == w, word & mask, word)
        for w, word in enumerate(words)
    )


def build_batched(
    spec_name: str,
    E: int,
    C: int,
    F: int,
    max_closure: int,
    compaction: str = "hash",
):
    """Build the (unjitted) vmapped checker for fixed shapes; jit it
    yourself or use make_check_fn for the cached jitted version.
    ``compaction``: "hash" (default — O(K) scatter dedup, best-effort)
    or "sort" (exact dedup; what the sufficient-frontier rung's
    lossless guarantee rests on)."""
    spec = next(s for s in _all_specs() if s.name == spec_name)
    step = spec.step
    compact = _COMPACTIONS[compaction]
    W = (C + 31) // 32  # linset words: one uint32 per 32 open-op slots

    def check_one(init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b):
        states0 = jnp.zeros((F,), jnp.int32).at[0].set(init_state)
        words0 = tuple(jnp.zeros((F,), jnp.uint32) for _ in range(W))
        valid0 = jnp.zeros((F,), bool).at[0].set(True)

        def event_body(carry, ev):
            states, words, valid, done, failed_at, overflow, idx = carry
            e_slot, c_slot, c_f, c_a, c_b = ev
            is_pad = e_slot < 0

            # --- closure expansion (inline while_loop) ---
            # convergence is certified by ``grew`` from the compaction:
            # no new-region lane survived exact-equality dedup ⇒ every
            # expanded config already exists ⇒ fixpoint.  (A survivor
            # *count* comparison is only sound under exact dedup; with
            # the best-effort hash dedup a missed duplicate could mask
            # a genuinely new config at equal count.)
            def cond(c):
                _, _, _, changed, ovf, i = c
                return changed & ~ovf & (i < max_closure)

            def body(c):
                st, ws, vl, _, ovf, i = c
                active = c_slot >= 0
                slot_safe = jnp.where(active, c_slot, 0).astype(jnp.uint32)
                ws_b = tuple(w[:, None] for w in ws)  # [F,1] vs [1,C]
                already = _get_bit(ws_b, slot_safe[None, :])
                st2, ok2 = step(
                    st[:, None], c_f[None, :], c_a[None, :], c_b[None, :]
                )
                st2 = jnp.broadcast_to(st2, (F, C)).astype(jnp.int32)
                ok2 = jnp.broadcast_to(ok2, (F, C))
                nv = vl[:, None] & active[None, :] & (already == 0) & ok2
                nws = tuple(
                    jnp.broadcast_to(w, (F, C))
                    for w in _set_bit(ws_b, slot_safe[None, :])
                )
                all_st = jnp.concatenate([st, st2.reshape(-1)])
                all_ws = tuple(
                    jnp.concatenate([w, nw.reshape(-1)])
                    for w, nw in zip(ws, nws)
                )
                all_vl = jnp.concatenate([vl, nv.reshape(-1)])
                s3, w3, v3, grew, o3 = compact(all_st, all_ws, all_vl, F, F)
                return (s3, w3, v3, grew, ovf | o3, i + 1)

            init = (
                states,
                words,
                valid,
                jnp.bool_(True),
                jnp.bool_(False),
                0,
            )
            st_c, ws_c, vl_c, chg_c, ovf_c, it_c = lax.while_loop(
                cond, body, init
            )
            # exiting on the iteration cap while still growing means the
            # closure was truncated: that MUST surface as overflow
            # ("unknown"), never as a definite verdict
            ovf_c = ovf_c | (chg_c & (it_c >= max_closure))

            # --- filter on the completing op; promote it ---
            slot_u = jnp.where(is_pad, 0, e_slot).astype(jnp.uint32)
            has_bit = _get_bit(ws_c, slot_u) == 1
            vl_f = vl_c & has_bit
            ws_f = _clear_bit(ws_c, slot_u)
            empty = ~vl_f.any()

            # select: pad or already-done events pass through unchanged
            skip = is_pad | done
            states2 = jnp.where(skip, states, st_c)
            words2 = tuple(
                jnp.where(skip, w0, wf) for w0, wf in zip(words, ws_f)
            )
            valid2 = jnp.where(skip, valid, vl_f)
            done2 = done | (~is_pad & empty)
            failed_at2 = jnp.where(
                done | is_pad | ~empty, failed_at, idx
            )
            overflow2 = overflow | (~skip & ovf_c)
            return (states2, words2, valid2, done2, failed_at2, overflow2, idx + 1), None

        carry0 = (
            states0,
            words0,
            valid0,
            jnp.bool_(False),
            jnp.int32(-1),
            jnp.bool_(False),
            jnp.int32(0),
        )
        (states, words, valid, done, failed_at, overflow, _), _ = lax.scan(
            event_body,
            carry0,
            (ev_slot, cand_slot, cand_f, cand_a, cand_b),
        )
        return ~done, failed_at, overflow

    return jax.vmap(check_one)


def default_compaction() -> str:
    """Hot-path compaction mode: ``JEPSEN_TPU_FRONTIER_COMPACTION`` if
    set (the A/B switch the capture watcher flips), else "auto" —
    resolved per backend.  The 2026-07-31 on-chip grid
    (frontier_results_tpu.json compaction + mutex arms) showed the
    exact lax.sort compaction fastest at EVERY measured K from 136 to
    2304 — up to 25x over the scatter-hash lowering (TPU scatters
    serialize; the bitonic sort vectorizes) and ≥ the all-pairs mode
    past the smallest shapes — so accelerators get "sort", which also
    makes every rung exact (lossless escalation, exact fixpoint
    certificates, no hash-collision caveats).  The CPU backend keeps
    "hash": the round-4 CPU measurements showed the sort's cost
    growing superlinearly in F there, which is exactly why per-backend
    resolution exists instead of one pinned mode."""
    import os

    mode = os.environ.get("JEPSEN_TPU_FRONTIER_COMPACTION", "auto")
    if mode == "auto":
        import jax

        return "hash" if jax.default_backend() == "cpu" else "sort"
    if mode not in _COMPACTIONS:
        raise ValueError(
            f"unknown frontier compaction {mode!r}; "
            f"one of {sorted(_COMPACTIONS)} or auto"
        )
    return mode


def make_check_fn(
    spec_name: str,
    E: int,
    C: int,
    F: int,
    max_closure: int,
    compaction: Optional[str] = None,
):
    """Jitted, cached version of build_batched — repeat batches at the
    same bucket sizes reuse the compiled executable.  The returned fn
    carries its footprint-safe per-dispatch row cap as
    ``fn.safe_dispatch`` (see frontier_max_dispatch) so every dispatch
    site — library and benchmarks — reads the same safety bound instead
    of re-deriving (or forgetting) it.  ``compaction=None`` resolves
    through default_compaction() at call time."""
    if compaction is None:
        compaction = default_compaction()
    fn = _make_check_fn(spec_name, E, C, F, max_closure, compaction)
    if count_kernel_build(fn):
        obs.count(
            "jepsen_kernel_builds_total", engine="frontier",
            compaction=compaction, spec=spec_name,
        )
    return fn


@lru_cache(maxsize=64)
def _make_check_fn(spec_name, E, C, F, max_closure, compaction):  # jt: jaxpr(dot_generals<=0, budget=0.9..1.6)
    fn = jax.jit(build_batched(spec_name, E, C, F, max_closure, compaction))
    cap = frontier_max_dispatch(F, E, C)
    if compaction == "allpairs" and cap:
        # the [K, K] equality matrix dominates this mode's footprint;
        # the quotient hitting 0 must propagate — 0 is the documented
        # "do not dispatch even one row" signal every guard checks
        K = F * (C + 1)
        cap = min(cap, ALLPAIRS_ELEM_BUDGET // (K * K))
    fn.safe_dispatch = cap
    fn.compaction = compaction  # rides the mesh shard_fn cache key
    return fn


make_check_fn.cache_clear = _make_check_fn.cache_clear


_claim_lock = threading.Lock()


def _claim_once(fn, attr: str) -> bool:
    """Atomically claim a once-per-object flag on a compiled fn: True
    for exactly ONE caller across threads.  Parallel checkers (compose
    → real_pmap) share cached fns, so unlocked getattr-then-setattr
    would let two threads both claim (double-counted compiles/builds);
    an unmarkable fn type returns False — skip rather than recount."""
    with _claim_lock:
        if getattr(fn, attr, False):
            return False
        try:
            setattr(fn, attr, True)
        except AttributeError:
            return False
        return True


def count_kernel_build(fn) -> bool:
    """True exactly once per compiled-fn object (shared by the dense
    and frontier build sites): the cache returns one object per live
    variant, so marking the object counts distinct builds without the
    cache_info().misses before/after race that parallel checkers
    could double- or under-count."""
    return _claim_once(fn, "_obs_build_counted")


def _claim_shape(fn, shape) -> bool:
    """Atomically claim first-dispatch of ``fn`` at a batch shape; jit
    retraces per shape, so this — not a per-fn flag — is what separates
    compile-phase from execute-phase dispatches."""
    with _claim_lock:
        shapes = getattr(fn, "_obs_dispatched_shapes", None)
        if shapes is None:
            try:
                shapes = fn._obs_dispatched_shapes = set()
            except AttributeError:
                return False  # unmarkable fn type: never claim
        if shape in shapes:
            return False
        shapes.add(shape)
        return True


def _shape_dispatched(fn, shape) -> bool:
    shapes = getattr(fn, "_obs_dispatched_shapes", None)
    return shapes is not None and shape in shapes


#: single-lock model family whose frontier grows linearly in C — one
#: lock means at most one blocked acquire can linearize before the
#: next release completes, so the per-event search is cheap
#: SEQUENTIALLY and the memoized CPU oracle beats the entire device
#: ladder once the dense automaton's envelope is exceeded.  Measured
#: for mutex, 2026-07-31 18:45-18:49Z (frontier_results_tpu.json):
#: oracle 1,028-1,436 h/s vs check-batch-auto 210-300 h/s at
#: C ∈ {16, 24} — a ~5x oracle win even with allpairs compaction;
#: owner/reentrant share the one-lock structure (their step algebra
#: differs, not their frontier growth).  Routing them to the oracle is
#: the measured production choice, not a fallback — and for plain
#: mutex the routed path now decides by greedy alternation scheduling
#: in O(n log n) (checker/locks_direct.py: 23.5k h/s single-core,
#: 17.7x the search, no search at all), which widens the routing win
#: to ~67x.  NOT in the set: acquired-permits — a semaphore admits
#: n_permits concurrent holders (frontier not linear by this
#: argument), and as a dense_only spec it already takes the oracle
#: outside its envelope.
LINEAR_FRONTIER_SPECS = frozenset(
    {"mutex", "owner-mutex", "reentrant-mutex"}
)


#: specs the CPU direct checker beats EVERY device kernel on, even
#: inside the dense envelope: the unordered queue factors per value
#: into a greedy bipartite matching (checker/locks_direct.py,
#: _queue_check_events) measured at 34.8k h/s single-core on the
#: queue-bench corpus vs the dense bitset kernel's 7.5k at B=1024 —
#: 4.6x — and 204x the generic search.  Routing it off the device
#: entirely is the measured choice.
DIRECT_FIRST_SPECS = frozenset({"unordered-queue"})


def kernel_choice(spec_name: str, C: int, n_values) -> str:
    """Which engine check_batch routes this shape to — "oracle" for
    specs a CPU direct algorithm dominates outright
    (DIRECT_FIRST_SPECS) or for the linear-frontier lock family
    outside the dense envelope (LINEAR_FRONTIER_SPECS), "dense"
    (subset automaton, no sorts, no overflow), or "frontier" (generic
    compacted device search).  ``n_values`` is the value-domain bound,
    or a (Vr, K) pair for multi-register's composite automaton.
    Callers report this so a workload silently drifting between
    engines (e.g. "3n" concurrency pushing peak open ops past the
    dense slot cap) is visible in stats rather than a mystery
    slowdown."""
    from . import dense as dense_mod

    if spec_name in DIRECT_FIRST_SPECS:
        return "oracle"
    if n_values is not None:
        V = (
            tuple(n_values)
            if isinstance(n_values, (tuple, list))
            else encode_mod.round_up(n_values, 4)
        )
        if dense_mod.applicable(spec_name, C, V):
            return "dense"
    if spec_name in LINEAR_FRONTIER_SPECS:
        return "oracle"
    return "frontier"


def make_best_check_fn(
    spec_name: str,
    E: int,
    C: int,
    F: int,
    max_closure: int,
    n_values: Optional[int] = None,
):
    """Pick the fastest kernel for the shape: the dense subset-automaton
    (ops.dense — no sorts, no overflow) when the model's value domain and
    concurrency fit its envelope, else the generic frontier kernel.
    ``n_values`` is the exclusive upper bound on value ids (init/a/b).

    Returns ``None`` when :func:`kernel_choice` routes the shape to
    "oracle" (a CPU direct algorithm dominates, or a dense-only spec
    sits outside its envelope) — mirroring check_batch, which sends
    those batches down the oracle path with no device dispatch.
    Callers MUST check for None; handing back a compiled frontier fn
    here would silently give them the engine the routing decided
    against."""
    from . import dense as dense_mod

    choice = kernel_choice(spec_name, C, n_values)
    if choice == "oracle":
        return None
    if choice == "dense":
        V = (
            tuple(n_values)
            if isinstance(n_values, (tuple, list))
            else encode_mod.round_up(n_values, 4)
        )
        return dense_mod.make_dense_fn(spec_name, E, C, V)
    spec = next(s for s in _all_specs() if s.name == spec_name)
    if getattr(spec, "dense_only", False):
        # no frontier step exists (table-built automaton): outside the
        # dense envelope the caller must route the batch to the oracle
        return None
    return make_check_fn(spec_name, E, C, F, max_closure)


def _all_specs():
    from .step_kernels import SPECS

    return SPECS.values()


#: overflowed rows retry on-device at frontier × each factor before the
#: CPU oracle gets them — a device rerun is orders of magnitude cheaper
ESCALATION_FACTORS = (4,)

#: largest frontier the guaranteed-sufficient escalation may allocate;
#: above this the oracle takes the leftovers (K = F·(1+C) working lanes
#: per history bounds device memory)
MAX_SUFFICIENT_FRONTIER = 8192


def sufficient_frontier(
    n_values: int, C: int, spec_name: Optional[str] = None
) -> Optional[int]:
    """A frontier capacity that can NEVER overflow, when affordable.

    A config is (state, linset): for the register-family models state
    is a value id < n_values and linset ⊆ the C open-op slots, so at
    most n_values·2^C distinct configs exist — the exact space the
    dense kernel enumerates bit-packed.  For the unordered queue the
    bound is tighter still: unique-value enqueues/dequeues commute, so
    every surviving config's state is a pure function of its linset
    (completed ops are common to all survivors) and 2^C configs bound
    the space regardless of the value count.  A frontier that large
    makes the compaction lossless by construction, so one rerun at it
    resolves every overflow row on-device instead of handing the
    exponential search back to the CPU oracle.  Returns None when the
    bound is unaffordable.  For models whose state outgrows value ids
    (mutex held-state past n_values=1, multi-register packing) the
    bound is a heuristic only — overflow is still tracked on the
    rerun, so an undersized capacity just falls through to the oracle
    as before."""
    if C >= 31:
        return None
    if isinstance(n_values, (tuple, list)):  # multi-register (Vr, K)
        n_values = int(n_values[0]) ** int(n_values[1])
    if spec_name == "unordered-queue":
        bound = 1 << C
    else:
        bound = n_values << C
    if bound <= 0 or bound > MAX_SUFFICIENT_FRONTIER:
        return None
    # quantize to a power of two: the escalated checker is jit-compiled
    # per capacity, so a data-dependent F (n_values drifts per batch)
    # would mint a fresh executable every time — the ladder caps the
    # compile variants at log2(MAX_SUFFICIENT_FRONTIER)
    return 1 << (bound - 1).bit_length()


def _run_rows(fn, mesh, arrays):
    if mesh is not None:
        from ..parallel import mesh as mesh_mod

        return mesh_mod.sharded_check(fn, mesh, *arrays)
    return fn(*(jnp.asarray(a) for a in arrays))


#: largest row count per device dispatch — bounds HBM for huge
#: keyspaces (a [B, E, C] event tensor grows without limit otherwise);
#: the flagship bench shape (16384 × 1000-op histories) fits comfortably
DEFAULT_MAX_DISPATCH = 16384

#: Oversized frontier-kernel dispatches crash the axon TPU worker
#: outright.  Calibration points, in B × F·(C+1) × ceil(E/32) words
#: (the closure expansion's live footprint):
#:   SAFE  9.3M — cas E≈2000 C=8  F=64  B=256  (B=512 = 18.6M kills)
#:   CRASH 8.9M — cas E=64   C=16 F=256 B=1024 (2026-07-31 18:40Z;
#:                 its 16K-entry hash tables push the true footprint
#:                 past the word count, hence crashing below 9.3M)
#:   SAFE  3.3M — mutex E=64 C=24 F=64 B=1024
#: 4M sits ≥2× under both crash points while keeping every proven-good
#: single-dispatch shape un-chunked; dense-kernel dispatches are
#: unaffected (B=16384 runs clean).
FRONTIER_DISPATCH_BUDGET = 4_000_000

#: budget for callers that pass NO candidate-slot count (C=0): the
#: frontier-only accounting can't see the F·(C+1) closure expansion, so
#: it keeps the previously pinned-safe 1M-word bound — at the cas
#: calibration shape (F=64, E≈2000) that caps shapeless dispatches at
#: ~248 rows, at-or-under the measured-safe B=256 (B=512 killed the
#: worker), where the 4M budget would have allowed ~992
FRONTIER_ONLY_DISPATCH_BUDGET = 1_000_000


def value_domain(spec_name: str, init_state, cand_a, cand_b) -> int:
    """Exclusive upper bound of the kernel state/value-id domain for a
    batch — the ONE place that knows spec-specific widenings (the
    reentrant-mutex automaton runs over {0, 2c-1, 2c}, wider than the
    raw client-id bound).  check_batch and the benchmarks both read
    this so they can never disagree about kernel shapes."""
    n_values = 1 + int(
        max(
            np.asarray(init_state).max(),
            np.asarray(cand_a).max(),
            np.asarray(cand_b).max(),
        )
    )
    if spec_name == "reentrant-mutex":
        n_values = max(n_values, 2 * (n_values - 1) + 1)
    return n_values


def frontier_max_dispatch(
    F: int, E: int, C: int = 0, max_dispatch: int = DEFAULT_MAX_DISPATCH
) -> int:
    """Largest safe per-dispatch row count for a frontier kernel of
    capacity ``F`` over ``E`` event slots with ``C`` candidate slots.
    The dominant live footprint is the closure expansion, K = F·(C+1)
    configs × ceil(E/32) bitset words per row — NOT the F-sized
    frontier itself: budgeting on F alone under-counted ~17× at
    C=16/F=256 and reproducibly crashed the axon TPU worker
    (2026-07-31 18:40Z sweep, frontier_results_tpu.json error rows).
    C=0 (unknown) keeps the old frontier-only accounting — against the
    tighter FRONTIER_ONLY_DISPATCH_BUDGET, so a shapeless caller stays
    at-or-under the previously measured-safe caps instead of getting
    the expansion-aware budget without the (C+1) expansion factor.
    Chunked dispatch reuses one
    executable, so a smaller cap costs extra dispatches, not extra
    compiles.  Returns 0 when even a single row exceeds the budget —
    callers must NOT dispatch that shape (check_batch skips the
    escalation rung; the oracle takes the rows instead)."""
    words = max(1, -(-E // 32))
    if C <= 0:
        per_row = F * words
        budget = FRONTIER_ONLY_DISPATCH_BUDGET
    else:
        per_row = F * (C + 1) * words
        budget = FRONTIER_DISPATCH_BUDGET
    if per_row > budget:
        return 0
    return max(1, min(max_dispatch, budget // per_row))


#: per-array pad fill for chunked dispatch — ev_slot/cand_slot use -1
#: as "padding", the same convention sharded_check pads with; shared by
#: _run_chunked and the telemetry head/tail split so both pad tails to
#: the same chunk shape (one executable, never a per-tail-size compile)
_PAD_FILLS = (0, -1, -1, 0, 0, 0)


def _run_chunked(fn, mesh, arrays, max_batch=DEFAULT_MAX_DISPATCH):
    """Dispatch a batch in ≤ max_batch row chunks, concatenating the
    per-chunk verdicts.  Every full-size chunk reuses one compiled
    executable; the tail chunk is padded UP to max_batch with neutral
    all-padding rows (ev_slot = -1) and sliced back, so a 100k-key
    batch costs exactly one compile, not one per tail size."""
    B = arrays[0].shape[0]
    if B <= max_batch:
        return _run_rows(fn, mesh, arrays)
    from ..parallel import mesh as mesh_mod

    fills = _PAD_FILLS
    outs = []
    for lo in range(0, B, max_batch):
        hi = min(lo + max_batch, B)
        n = hi - lo
        chunk = tuple(
            mesh_mod.pad_to_multiple(np.asarray(a[lo:hi]), max_batch, fill)
            for a, fill in zip(arrays, fills)
        )
        res = _run_rows(fn, mesh, chunk)
        # keep outputs on device (lazy slice): forcing to numpy here
        # would sync per chunk and leave the device idle while the host
        # pads the next chunk — dispatches pipeline instead, and one
        # materialization at the end forces them all
        outs.append(tuple(x[:n] for x in res))
    return tuple(
        np.concatenate([np.asarray(o[i]) for o in outs]) for i in range(3)
    )


def _timed_run_chunked(fn, mesh, arrays, disp, engine):
    """:func:`_run_chunked` with engine telemetry: one ``engine`` span
    per dispatch call, wall time split into *compile* (the first
    dispatch of this compiled fn — trace + XLA compile + execute) vs
    *execute* (every later dispatch, cache-hit).  The timed region
    forces host materialization so async dispatch can't under-report;
    check_batch materializes the outputs immediately after anyway, so
    this moves the sync point rather than adding one."""
    B = arrays[0].shape[0]
    # jit retraces PER INPUT SHAPE, not per fn: the dispatch shape is B
    # itself below the cap, else the disp-row chunk size (tails pad to
    # it) — so first-dispatch tracking must key on (fn, shape) or a
    # later new-batch-size compile would be mislabeled "execute".
    # Under a mesh the executable is the shard_map wrapper traced at
    # the per-shard shape, a different compile from the single-device
    # one — the key carries the mesh width so neither masks the other.
    disp_shape = B if B <= disp else disp
    if mesh is not None:
        disp_shape = (disp_shape, int(mesh.devices.size))
    if not obs.enabled():
        # still claim first-dispatch: the kernel compiles now either
        # way, and a later obs-ON run hitting the fn cache must record
        # its cache-hit dispatch as execute, not a phantom compile
        _claim_shape(fn, disp_shape)
        return _run_chunked(fn, mesh, arrays, disp)
    chunk_shape = disp if mesh is None else (disp, int(mesh.devices.size))
    if B > disp and not _shape_dispatched(fn, chunk_shape):
        # only the FIRST disp-row chunk traces+compiles; timing the
        # whole chunked call as "compile" would absorb every
        # steady-state dispatch after it and inflate the split the
        # metric exists to report.  The head chunk is full-size, so it
        # dispatches the same executable the chunked tail reuses —
        # and a short tail is padded to the SAME disp-row shape
        # (_PAD_FILLS, like _run_chunked's own tail) so the split
        # never mints a second per-tail-size executable.  (Peek
        # without claiming: the head recursion claims the compile
        # slot atomically below.)
        from ..parallel import mesh as mesh_mod

        n_tail = B - disp
        head = _timed_run_chunked(
            fn, mesh, tuple(a[:disp] for a in arrays), disp, engine
        )
        tail_arrays = tuple(
            mesh_mod.pad_to_multiple(np.asarray(a[disp:]), disp, fill)
            for a, fill in zip(arrays, _PAD_FILLS)
        )
        tail = _timed_run_chunked(fn, mesh, tail_arrays, disp, engine)
        return tuple(
            np.concatenate([np.asarray(h), np.asarray(t)[:n_tail]])
            for h, t in zip(head, tail)
        )
    # claim-before-dispatch under the lock: concurrent checkers
    # (compose → real_pmap) sharing one cached fn must record exactly
    # ONE compile-phase dispatch per shape, the rest execute
    first = _claim_shape(fn, disp_shape)
    phase = "compile" if first else "execute"
    with obs.span(
        "engine/dispatch", cat="engine",
        engine=engine, rows=B, phase=phase,
    ) as sp:
        out = tuple(
            np.asarray(x) for x in _run_chunked(fn, mesh, arrays, disp)
        )
    obs.observe(f"jepsen_kernel_{phase}_seconds", sp.duration_s(),
                engine=engine)
    # per device DISPATCH, not per call: one chunked call issues
    # ceil(B/disp) dispatches and the metric is documented as the
    # dispatch count
    obs.count(
        "jepsen_kernel_dispatches_total", max(1, -(-B // disp)),
        engine=engine, phase=phase,
    )
    return out


class BucketPlan:
    """The routing decision for one encoded ``[B, E, C]`` bucket: which
    kernel serves the shape, the compiled fn (None = oracle-routed or
    undispatchable), its safe per-dispatch row cap, and the shape
    facts (``mc``, ``n_values``) the escalation ladder needs.  Built by
    :func:`plan_bucket`; consumed by the pipelined engine
    (:mod:`jepsen_tpu.engine.pipeline`) and :func:`escalate_overflows`."""

    __slots__ = (
        "spec", "E", "C", "mc", "n_values", "kernel", "fn", "disp",
        "frontier",
    )

    def overflow_engine(self) -> str:
        # routed by choice (the oracle IS the fastest engine for this
        # shape) vs landed there by escalating off the device
        return (
            "oracle-routed" if self.kernel == "oracle" else "oracle-overflow"
        )


def plan_bucket(
    model: m.Model,
    spec,
    arrays,
    frontier: int = DEFAULT_FRONTIER,
    max_closure: Optional[int] = None,
    max_dispatch: int = DEFAULT_MAX_DISPATCH,
) -> BucketPlan:
    """Pick the kernel for one encoded bucket's arrays and emit the
    per-bucket routing telemetry.  ``arrays`` is the 6-tuple
    ``(init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b)`` with
    at least one row."""
    init_state, ev_slot, cand_slot, cand_f, cand_a, cand_b = arrays
    plan = BucketPlan()
    plan.spec = spec
    plan.frontier = frontier
    plan.E = E = ev_slot.shape[1]
    plan.C = C = cand_slot.shape[2]  # bucketed to actual concurrency
    # closure depth is bounded by the open-op count (<= C); +1 for the
    # fixpoint-confirming iteration, so legitimate closures are never
    # cut short and flagged unknown
    plan.mc = mc = max_closure if max_closure is not None else C + 1
    if spec.name == "acquired-permits":
        # (client count, permit count) drives the table-built
        # automaton; client ids are contiguous 1..N in cand_a.
        # N rounds up to a bucket of 4 so drifting per-batch client
        # counts don't mint a fresh executable each (oversized
        # tables are a harmless superset; real ids stay ≤ N)
        n_values = (
            encode_mod.round_up(int(max(cand_a.max(), 0)), 4),
            int(getattr(model, "n_permits", 2)),
        )
    elif spec.name == "multi-register":
        # the (Vr, K) composite pair drives the dense automaton
        from . import dense as dense_mod

        n_values = dense_mod.mr_shape_probe(init_state, cand_a, cand_b)
    else:
        n_values = value_domain(spec.name, init_state, cand_a, cand_b)
    plan.n_values = n_values
    if max_closure is None:
        kernel = kernel_choice(spec.name, C, n_values)
        # "oracle": the measured-fastest engine for this shape is
        # the CPU search (LINEAR_FRONTIER_SPECS outside the dense
        # envelope) — fn=None sends the whole bucket down the
        # oracle path with no device dispatches
        fn = (
            None
            if kernel == "oracle"
            else make_best_check_fn(spec.name, E, C, frontier, mc, n_values)
        )
    elif getattr(spec, "dense_only", False):
        # an explicit closure cap would force the frontier kernel,
        # which dense-only specs don't have: oracle takes the bucket
        fn = None
        kernel = "frontier"
    else:
        # an explicit closure cap asks for the generic kernel's
        # truncation semantics; the dense kernel has no such cap
        fn = make_check_fn(spec.name, E, C, frontier, mc)
        kernel = "frontier"
    plan.kernel = kernel
    plan.fn = fn
    # every compiled fn carries its footprint-safe per-dispatch cap
    # (make_check_fn derives it from the closure expansion; dense fns
    # pin the full default — overflow-free kernels have no crash shape)
    plan.disp = disp = (
        0 if fn is None
        else min(max_dispatch, getattr(fn, "safe_dispatch", max_dispatch))
    )
    if obs.enabled():
        B0 = arrays[0].shape[0]
        # a bucket only counts as device traffic when a kernel will
        # actually dispatch: fn=None (dense-only spec forced onto
        # the absent frontier path) or disp=0 (even one row would
        # bust the budget) both send every row to the oracle, and
        # the routing counter must say so — no phantom frontier
        # metrics for dispatches that never happen
        routed = kernel if fn is not None and disp > 0 else "oracle"
        obs.count(
            "jepsen_engine_routed_total", engine=routed, spec=spec.name
        )
        obs.count("jepsen_engine_batch_rows_total", B0, engine=routed)
        if routed == "frontier":
            # TPU-specific telemetry: frontier capacity high-water
            # and how much of the crash-calibrated dispatch budget
            # (FRONTIER_DISPATCH_BUDGET words) one dispatch uses
            words = max(1, -(-E // 32))
            per_row = frontier * (C + 1) * words
            obs.gauge_max("jepsen_frontier_high_water", frontier)
            obs.gauge_set("jepsen_frontier_safe_dispatch", disp)
            # high-water, not last-write: the run summary must show
            # the PEAK budget use, not whichever batch came last
            obs.gauge_max(
                "jepsen_frontier_dispatch_budget_used_ratio",
                per_row * min(B0, disp) / max(FRONTIER_DISPATCH_BUDGET, 1),
            )
    return plan


def escalate_overflows(
    plan: BucketPlan,
    arrays,
    ok: np.ndarray,
    failed_at: np.ndarray,
    overflow: np.ndarray,
    mesh=None,
    escalation=ESCALATION_FACTORS,
    sufficient_rung: bool = True,
    max_dispatch: int = DEFAULT_MAX_DISPATCH,
) -> None:
    """Retry overflowed rows on-device at growing frontier capacities,
    writing verdicts back into ``ok``/``failed_at``/``overflow`` in
    place.  Rows still overflowed afterwards are the oracle's.  The
    dispatch-and-sync here is the rare path, so the pipelined engine
    runs it inline at chunk-settle time."""
    spec = plan.spec
    # dense-only specs have no frontier kernel, so no escalation
    # rungs exist either — overflowed rows (all of them, when fn is
    # None) go straight to the oracle
    capacities = (
        [] if plan.fn is None or getattr(spec, "dense_only", False)
        else [plan.frontier * factor for factor in escalation]
    )
    # final escalation rung: the provably-sufficient capacity, when
    # affordable — a lossless-compaction rerun that settles the row
    # on-device instead of handing it to the exponential oracle.
    # The base pass (and intermediate rungs) use best-effort hash
    # dedup, which can overflow spuriously at ANY capacity — so the
    # guarantee requires one exact-sort rung at ≥ the sufficient
    # bound even when the base frontier already exceeds it.
    suff = (
        sufficient_frontier(plan.n_values, plan.C, spec.name)
        if sufficient_rung
        and plan.fn is not None
        and not getattr(spec, "dense_only", False)
        else None
    )
    if suff is not None and not any(c >= suff for c in capacities):
        capacities.append(max(suff, plan.frontier))
    for capacity in capacities:
        bad = np.flatnonzero(overflow)
        if bad.size == 0:
            break
        # pad the rerun batch to a bucket multiple with neutral rows
        # (all-padding events report valid) so the escalated checker
        # compiles once per bucket size, not once per overflow count
        n_bad = len(bad)
        n_pad = encode_mod.round_up(n_bad, 8) - n_bad
        idx = np.concatenate([bad, np.zeros((n_pad,), bad.dtype)])
        sub = tuple(a[idx] for a in arrays)
        if n_pad:
            sub[1][n_bad:] = -1  # ev_slot: every event padding
        # rungs at ≥ the sufficient capacity must use an EXACT
        # dedup (EXACT_COMPACTIONS): the lossless-by-construction
        # claim is "all distinct configs fit in F", which only
        # holds if every duplicate is actually removed.  Rungs
        # below it keep the configured fast compaction — a spurious
        # overflow there escalates to the next rung.
        mode = default_compaction()
        if suff is not None and capacity >= suff:
            mode = mode if mode in EXACT_COMPACTIONS else "sort"
        fn2 = make_check_fn(spec.name, plan.E, plan.C, capacity, plan.mc,
                            mode)
        # per-chip budget: safe_dispatch (and max_dispatch) bound the
        # rows ONE chip may hold; a mesh rerun shards its rows evenly,
        # so the global dispatch scales by the device count while each
        # chip stays at the crash-calibrated single-chip cap
        n_dev = 1 if mesh is None else int(mesh.devices.size)
        disp2 = min(max_dispatch, fn2.safe_dispatch) * n_dev
        if disp2 == 0:
            # a single row at this capacity would bust the safe
            # footprint: skip the rung, leave the rows overflowed
            continue
        obs.gauge_max("jepsen_frontier_high_water", capacity)
        obs.count(
            "jepsen_engine_escalations_total", n_bad,
            capacity=str(capacity),
        )
        ok2, failed2, ovf2 = (
            np.asarray(x)[:n_bad]
            for x in _timed_run_chunked(fn2, mesh, sub, disp2,
                                        "frontier-escalated")
        )
        ok[bad] = ok2
        failed_at[bad] = failed2
        overflow[bad] = ovf2


def check_batch(
    model: m.Model,
    histories: Sequence[History],
    frontier: int = DEFAULT_FRONTIER,
    slot_cap: int = DEFAULT_SLOT_CAP,
    max_closure: Optional[int] = None,
    mesh=None,
    escalation=ESCALATION_FACTORS,
    oracle_fallback: bool = True,
    sufficient_rung: bool = True,
    max_dispatch: int = DEFAULT_MAX_DISPATCH,
    oracle_budget_s: Optional[float] = None,
    window: Optional[int] = None,
    bucketed: Optional[bool] = None,
    decomposed: Optional[bool] = None,
) -> List[dict]:
    """Check a batch of histories on the accelerator; per-history result
    dicts in input order.  Pass a jax.sharding.Mesh to shard the batch
    over multiple devices — with ``mesh=None`` the engine resolves one
    itself whenever more than one accelerator device is attached
    (:func:`jepsen_tpu.parallel.mesh.engine_default_mesh`;
    ``JEPSEN_TPU_ENGINE_MESH=0`` disables, ``=1`` extends the default
    to virtual host devices).  Sharding never moves a verdict: every
    budget is per chip and padding rows are neutral (``make
    mesh-smoke`` pins byte-equality against the single-device run).
    Unencodable histories fall back to the CPU
    oracle; device-side overflows first retry on-device at
    frontier × each ``escalation`` factor, then — when
    ``sufficient_rung`` (default) and the model's config-space bound is
    affordable (see :func:`sufficient_frontier`: n_values·2^C for the
    register family, 2^C for the unordered queue) — once more at a
    provably-overflow-free capacity, and only then fall back to the
    oracle.  Pass ``escalation=()`` with
    ``sufficient_rung=False`` to disable device reruns entirely.  With
    ``oracle_fallback=False`` unresolved rows report ``"unknown"``
    instead — for callers (like the race-mode checker) already running
    the oracle themselves.  Batches larger than ``max_dispatch`` rows
    run as bounded chunks (one compile total; HBM use stays capped no
    matter how many keys the independent lift produces).

    The production path IS the pipelined engine
    (:mod:`jepsen_tpu.engine.pipeline`): histories are encoded into
    tight per-(E, C)-shape buckets, device dispatches ride a bounded
    in-flight ``window`` (default 4, ``JEPSEN_TPU_ENGINE_WINDOW``; 1 =
    strictly serial, dispatch-sync-dispatch), and CPU-oracle fallbacks
    run on a worker pool concurrently with device work.  Verdicts are
    independent of ``window`` and ``bucketed`` — those knobs only move
    wall time (``bucketed=False`` restores the historical one-padded-
    batch encode).

    Partitionable models (multi-register per key, multi-mutex per lock
    name, unordered queue per value — the partition protocol on
    :mod:`jepsen_tpu.models`) additionally decompose each history into
    per-partition sub-histories ahead of planning
    (:mod:`jepsen_tpu.engine.decompose`), with sub-verdicts ANDed at
    settle; ``decomposed`` overrides the
    ``JEPSEN_TPU_ENGINE_DECOMPOSE`` default (on).  Decomposition is
    verdict-preserving by the protocol's soundness contract — the
    failing partition is surfaced as ``failed-partition`` on False
    results."""
    from ..engine import pipeline as engine_pipeline
    from ..platform import ensure_usable_backend

    # guard at the dispatch layer so EVERY caller (checker algorithms,
    # batched_linearizable, library users) survives a wedged accelerator
    # tunnel: probe in a subprocess, pin CPU if the device is unusable.
    # Memoized; a no-op when the platform is already pinned.
    ensure_usable_backend()
    return engine_pipeline.run(
        model,
        histories,
        frontier=frontier,
        slot_cap=slot_cap,
        max_closure=max_closure,
        mesh=mesh,
        escalation=escalation,
        oracle_fallback=oracle_fallback,
        sufficient_rung=sufficient_rung,
        max_dispatch=max_dispatch,
        oracle_budget_s=oracle_budget_s,
        window=window,
        bucketed=bucketed,
        decomposed=decomposed,
    )


def batch_stats(results: Sequence[dict]) -> dict:
    """Engine breakdown for a check_batch result list — the
    overflow→oracle fallback rate the device path's throughput claims
    rest on (an "unknown"-heavy batch is oracle-bound regardless of
    kernel speed)."""
    counts: dict = {}
    kernels: dict = {}
    for r in results:
        counts[r.get("engine", "?")] = counts.get(r.get("engine", "?"), 0) + 1
        if r.get("engine") == "tpu":
            k = r.get("kernel", "?")
            kernels[k] = kernels.get(k, 0) + 1
    n = max(1, len(results))
    return {
        "engines": counts,
        "kernels": kernels,
        "device-rate": counts.get("tpu", 0) / n,
        "oracle-rate": sum(
            v for k, v in counts.items() if k.startswith("oracle")
        ) / n,
    }


def analysis(model: m.Model, history: History, **kw) -> dict:
    """Single-history entry point matching checker.linear.analysis."""
    return check_batch(model, [history], **kw)[0]
