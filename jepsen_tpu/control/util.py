"""Node-side helpers: daemons, downloads, archives, files.

(reference: jepsen/src/jepsen/control/util.clj — exists?/file ops :14-110,
cached-wget! :167-198, install-archive! :199-260, grepkill! :286-309,
start-daemon! :310-368, stop-daemon! :369-385, daemon-running? :386-398,
signal! :399-403, await-tcp-port :14-30.)
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional

from . import execute, su
from .core import Lit, RemoteError, escape, lit


def meh(thunk):
    """Run thunk, swallow exceptions, return result-or-None (the
    reference's `meh`)."""
    try:
        return thunk()
    except Exception:
        return None


def exists(path: str) -> bool:
    """(reference: control/util.clj exists?)"""
    try:
        execute("stat", path)
        return True
    except RemoteError:
        return False


def file_contents(path: str) -> str:
    return execute("cat", path)


def write_file(content: str, path: str) -> None:
    """Write a string to a remote file via stdin redirect.
    (reference: control/util.clj:88-110 write-file!)"""
    execute(lit(f"cat > {escape(path)}"), stdin=content)


def ls(path: str = ".") -> List[str]:
    out = execute("ls", "-1", path)
    return [l for l in out.splitlines() if l]


def ls_full(path: str) -> List[str]:
    """Fully-qualified paths of directory entries."""
    base = path if path.endswith("/") else path + "/"
    return [base + f for f in ls(path)]


def tmp_file(ext: str = "") -> str:
    return execute("mktemp", f"--suffix={ext}")


def tmp_dir() -> str:
    return execute("mktemp", "-d")


def await_tcp_port(port: int, host: str = "localhost", timeout_s: float = 60, interval_s: float = 0.5) -> None:
    """Block until a TCP port opens.
    (reference: control/util.clj:14-30)"""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            # /dev/tcp is a bash-ism; docker/k8s remotes run sh, so be
            # explicit about the shell
            execute(
                "bash", "-c",
                f"cat < /dev/null > /dev/tcp/{host}/{port}",
            )
            return
        except RemoteError:
            if time.monotonic() > deadline:
                raise
            time.sleep(interval_s)


def cached_wget(url: str, dest_dir: str = "/tmp/jepsen/wget", force: bool = False) -> str:
    """Download a URL once; reuse the cached copy on later calls.
    (reference: control/util.clj:167-198)"""
    name = url.rstrip("/").rsplit("/", 1)[-1]
    path = f"{dest_dir}/{name}"
    execute("mkdir", "-p", dest_dir)
    if force or not exists(path):
        execute("wget", "-O", path, url, check=True)
    return path


def install_archive(url: str, dest: str, force: bool = False) -> str:
    """Download (or copy file://) an archive and expand it into dest,
    stripping the wrapper directory if there is exactly one.
    (reference: control/util.clj:199-260)"""
    local = cached_wget(url, force=force) if "://" in url and not url.startswith("file://") else url.replace("file://", "")
    with su():
        execute("rm", "-rf", dest)
        execute("mkdir", "-p", dest)
        if local.endswith(".zip"):
            execute("unzip", "-d", dest, local)
        else:
            execute("tar", "-xf", local, "-C", dest)
        entries = ls_full(dest)
        if len(entries) == 1:
            inner = entries[0]
            execute(
                lit(
                    f"mv {escape(inner)}/* {escape(dest)}/ && rmdir {escape(inner)}"
                )
            )
    return dest


def grepkill(pattern: str, signal: Any = 9) -> None:
    """Kill processes matching a pattern (grep/awk, avoiding our own
    sudo bash wrapper).  (reference: control/util.clj:286-309)"""
    try:
        execute(
            lit(
                f"ps aux | grep {escape(pattern)} | grep -v grep "
                f"| awk '{{print $2}}' "
                f"| xargs --no-run-if-empty kill -{signal}"
            )
        )
    except RemoteError as e:
        if "No such process" in e.result.err:
            return
        if e.result.exit in (0, 123):
            return
        raise


def start_daemon(opts: Dict[str, Any], bin: str, *args: Any) -> str:
    """Start a daemon under start-stop-daemon, logging to opts["logfile"].
    Returns "started" or "already-running".
    (reference: control/util.clj:310-368)"""
    from .core import env as env_tokens

    logfile = opts.get("logfile")
    ssd: List[Any] = ["start-stop-daemon", "--start"]
    if opts.get("background?", True):
        ssd += ["--background", "--no-close"]
    if opts.get("pidfile") and opts.get("make-pidfile?", True):
        ssd += ["--make-pidfile"]
    if opts.get("match-executable?", True):
        ssd += ["--exec", opts.get("exec", bin)]
    if opts.get("match-process-name?", False):
        ssd += ["--name", opts.get("process-name", bin.rsplit("/", 1)[-1])]
    if opts.get("pidfile"):
        ssd += ["--pidfile", opts["pidfile"]]
    if opts.get("chdir"):
        ssd += ["--chdir", opts["chdir"]]
    ssd += ["--startas", bin, "--", *args]

    if logfile:
        execute(
            lit(
                "echo \"`date +'%Y-%m-%d %H:%M:%S'` Jepsen starting "
                + escape(" ".join(str(a) for a in (bin,) + args))
                + f"\" >> {escape(logfile)}"
            )
        )
    tokens = env_tokens(opts.get("env")) + [escape(a) for a in ssd]
    cmd = " ".join(tokens)
    if logfile:
        cmd += f" >> {escape(logfile)} 2>&1"
    try:
        execute(lit(cmd))
        return "started"
    except RemoteError as e:
        if e.result.exit == 1:
            return "already-running"
        raise


def stop_daemon(pidfile: Optional[str] = None, cmd: Optional[str] = None) -> None:
    """Kill a daemon by pidfile and/or command name; remove the pidfile.
    (reference: control/util.clj:369-385)"""
    if cmd is not None:
        meh(lambda: execute("killall", "-9", "-w", cmd))
        if pidfile:
            meh(lambda: execute("rm", "-rf", pidfile))
        return
    if pidfile is not None and exists(pidfile):
        pid = execute("cat", pidfile).strip()
        if pid:
            meh(lambda: execute("kill", "-9", pid))
        meh(lambda: execute("rm", "-rf", pidfile))


def daemon_running(pidfile: str) -> Optional[bool]:
    """True if pidfile exists and its process is alive; None if no
    pidfile; False if stale.  (reference: control/util.clj:386-398)"""
    pid = meh(lambda: execute("cat", pidfile))
    if not pid:
        return None
    try:
        execute("ps", "-o", "pid=", "-p", pid.strip())
        return True
    except RemoteError:
        return False


def signal(process_name: str, sig: Any) -> str:
    """(reference: control/util.clj:399-403)"""
    meh(lambda: execute("pkill", "--signal", str(sig), process_name))
    return "signaled"
