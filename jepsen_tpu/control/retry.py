"""Auto-retrying Remote decorator for flaky transports.

(reference: jepsen/src/jepsen/control/retry.clj — 5 tries, ~100 ms
backoff :16-22; reconnects the underlying remote between attempts
:36-72.)
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from .core import Command, Remote, RemoteError, Result

log = logging.getLogger("jepsen_tpu.control.retry")

RETRIES = 5
BACKOFF_SECONDS = 0.1


class RetryRemote(Remote):
    def __init__(self, remote: Remote, retries: int = RETRIES, backoff: float = BACKOFF_SECONDS):
        self.remote = remote
        self.retries = retries
        self.backoff = backoff
        # one RetryRemote per (node, worker): the connection and its
        # reconnect cycle live on that worker's thread, never shared
        self._node = None  # jt: guarded-by(owner-thread)
        self._test = None  # jt: guarded-by(owner-thread)
        self._conn: Optional[Remote] = None  # jt: guarded-by(owner-thread)

    def connect(self, node, test=None):
        r = RetryRemote(self.remote, self.retries, self.backoff)
        r._node = node
        r._test = test
        # initial connect: plain retries, no reconnect of a
        # not-yet-existing connection (and never on the prototype)
        r._conn = r._with_retries(
            lambda: self.remote.connect(node, test), reconnect=False
        )
        return r

    def disconnect(self):
        if self._conn is not None:
            self._conn.disconnect()

    def _reconnect(self):
        try:
            if self._conn is not None:
                self._conn.disconnect()
        except Exception:
            pass
        self._conn = self.remote.connect(self._node, self._test)

    def _with_retries(self, thunk, reconnect: bool = True):
        attempt = 0
        while True:
            attempt += 1
            try:
                return thunk()
            except RemoteError:
                raise  # command genuinely failed; don't mask semantics
            except Exception as e:
                if attempt >= self.retries:
                    raise
                from .. import obs

                obs.count(
                    "jepsen_remote_retries_total",
                    error=type(e).__name__,
                )
                log.warning(
                    "remote op failed (%s); retrying %d/%d",
                    e,
                    attempt,
                    self.retries,
                )
                time.sleep(self.backoff)
                if reconnect:
                    try:
                        self._reconnect()
                    except Exception:
                        pass

    def execute(self, command: Command) -> Result:
        return self._with_retries(lambda: self._conn.execute(command))

    def upload(self, local_paths, remote_path):
        return self._with_retries(
            lambda: self._conn.upload(local_paths, remote_path)
        )

    def download(self, remote_paths, local_path):
        return self._with_retries(
            lambda: self._conn.download(remote_paths, local_path)
        )


def retry(remote: Remote, **kw) -> RetryRemote:
    return RetryRemote(remote, **kw)
