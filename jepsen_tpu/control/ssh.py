"""SSH remote via the system ssh/scp binaries.

Replaces the reference's JSch/SSHJ library transports
(jepsen/src/jepsen/control/clj_ssh.clj, sshj.clj) with subprocess ssh
using ControlMaster connection sharing for session reuse, and scp for
file transfer (control/scp.clj).
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Any, Optional, Sequence, Union

from .core import Command, Remote, Result, effective_stdin, wrap_sudo


def _as_paths(paths) -> list:
    """Normalize one-or-many path arguments to a list of strings."""
    if isinstance(paths, (str, os.PathLike)):
        return [str(paths)]
    return [str(p) for p in paths]


def run_scp(ssh_args: list, sources: list, dest: str, env=None) -> None:
    """Run one scp transfer with ssh-style args (the ``-p`` port flag is
    rewritten to scp's ``-P``); raises RuntimeError on failure.  Shared
    by both SSH transports so fixes land in one place."""
    args = list(ssh_args)
    try:
        i = args.index("-p")
        args[i] = "-P"
    except ValueError:
        pass
    proc = subprocess.run(
        ["scp", "-r"] + args + list(sources) + [dest],
        capture_output=True,
        timeout=600,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"scp to {dest} failed: {proc.stderr.decode(errors='replace')}"
        )


class SSHRemote(Remote):
    """One connected SSH session per node, multiplexed over a
    ControlMaster socket so repeated execs don't re-handshake."""

    def __init__(
        self,
        username: str = "root",
        port: int = 22,
        private_key_path: Optional[str] = None,
        strict_host_key_checking: bool = False,
        connect_timeout: int = 10,
    ):
        # Key-based auth only: BatchMode=yes forbids password prompts.
        # sudo passwords flow through the command DSL (control.sudo),
        # not the transport.
        self.username = username
        self.port = port
        self.private_key_path = private_key_path
        self.strict = strict_host_key_checking
        self.connect_timeout = connect_timeout
        self.node: Optional[str] = None
        self._control_dir: Optional[str] = None

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_test(test: dict) -> "SSHRemote":
        ssh = test.get("ssh", {})
        return SSHRemote(
            username=ssh.get("username", "root"),
            port=ssh.get("port", 22),
            private_key_path=ssh.get("private-key-path"),
            strict_host_key_checking=ssh.get("strict-host-key-checking", False),
        )

    def _base_args(self) -> list:
        args = [
            "-p",
            str(self.port),
            "-o",
            f"ConnectTimeout={self.connect_timeout}",
            "-o",
            "BatchMode=yes",
        ]
        if not self.strict:
            args += [
                "-o",
                "StrictHostKeyChecking=no",
                "-o",
                "UserKnownHostsFile=/dev/null",
                "-o",
                "LogLevel=ERROR",
            ]
        if self.private_key_path:
            args += ["-i", self.private_key_path]
        if self._control_dir:
            args += [
                "-o",
                "ControlMaster=auto",
                "-o",
                f"ControlPath={self._control_dir}/%r@%h:%p",
                "-o",
                "ControlPersist=60",
            ]
        return args

    def connect(self, node, test=None):
        r = SSHRemote(
            self.username,
            self.port,
            self.private_key_path,
            self.strict,
            self.connect_timeout,
        )
        r.node = str(node)
        r._control_dir = tempfile.mkdtemp(prefix="jepsen-ssh-")
        return r

    def disconnect(self):
        if self._control_dir and self.node:
            subprocess.run(
                ["ssh"]
                + self._base_args()
                + ["-O", "exit", f"{self.username}@{self.node}"],
                capture_output=True,
                timeout=10,
            )
            import shutil

            shutil.rmtree(self._control_dir, ignore_errors=True)
            self._control_dir = None

    # -- operations --------------------------------------------------------

    def execute(self, command: Command) -> Result:
        import time as _time

        from .. import obs

        cmd = wrap_sudo(command)
        stdin = effective_stdin(command)
        t0 = _time.perf_counter()
        proc = subprocess.run(
            ["ssh"] + self._base_args() + [f"{self.username}@{self.node}", cmd],
            input=stdin.encode() if stdin else None,
            capture_output=True,
            timeout=600,
        )
        # transport-level latency (vs jepsen_control_exec_seconds at the
        # session seam, which also covers dummy/docker/k8s remotes)
        obs.observe(
            "jepsen_ssh_exec_seconds", _time.perf_counter() - t0,
            node=str(self.node),
        )
        return Result(
            cmd=cmd,
            exit=proc.returncode,
            out=proc.stdout.decode(errors="replace"),
            err=proc.stderr.decode(errors="replace"),
            node=self.node,
        )

    def upload(self, local_paths, remote_path):
        run_scp(
            self._base_args(),
            _as_paths(local_paths),
            f"{self.username}@{self.node}:{remote_path}",
        )

    def download(self, remote_paths, local_path):
        run_scp(
            self._base_args(),
            [f"{self.username}@{self.node}:{p}" for p in _as_paths(remote_paths)],
            str(local_path),
        )


def ssh(test: Optional[dict] = None) -> SSHRemote:
    """The default SSH remote (reference: control.clj:35-37)."""
    return SSHRemote.from_test(test or {})
