"""Remote protocol, shell escaping, sudo wrapping.

(reference: jepsen/src/jepsen/control/core.clj — Remote protocol :7-58,
lit :62-66, escape :67-110, env :112-140, wrap-sudo :142-153,
throw-on-nonzero-exit :155-171.)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union


class Lit:
    """A literal string, passed to the shell unescaped.
    (reference: control/core.clj:62-66)"""

    __slots__ = ("s",)

    def __init__(self, s: str):
        self.s = s

    def __str__(self):
        return self.s

    def __repr__(self):
        return f"lit({self.s!r})"


def lit(s: str) -> Lit:
    return Lit(s)


_SAFE = re.compile(r"^[a-zA-Z0-9_+./:=@%^,-]+$")


def escape(arg: Any) -> str:
    """Escape one shell token.  Sequences flatten to space-joined escaped
    tokens; Lits pass through.  (reference: control/core.clj:67-110)"""
    if isinstance(arg, Lit):
        return arg.s
    if isinstance(arg, (list, tuple)):
        return " ".join(escape(a) for a in arg)
    if isinstance(arg, bool):
        return "true" if arg else "false"
    s = str(arg)
    if s == "":
        return "''"
    if _SAFE.match(s):
        return s
    return "'" + s.replace("'", "'\\''") + "'"


def env(env_map: Optional[Dict[str, Any]]) -> List[str]:
    """k=v tokens for an environment prefix.
    (reference: control/core.clj:112-140)"""
    if not env_map:
        return []
    return [f"{k}={escape(v)}" for k, v in sorted(env_map.items())]


@dataclass
class Command:
    """An action to run on a remote node."""

    cmd: str
    stdin: Optional[str] = None
    sudo: Optional[str] = None
    dir: Optional[str] = None
    sudo_password: Optional[str] = None


def wrap_sudo(command: Command) -> str:
    """Wrap a command string in sudo -u / cd as needed.
    (reference: control/core.clj:142-153)"""
    cmd = command.cmd
    if command.dir:
        cmd = f"cd {escape(command.dir)}; {cmd}"
    if command.sudo:
        cmd = f"sudo -k -S -u {escape(command.sudo)} bash -c {escape(cmd)}"
    return cmd


def effective_stdin(command: Command) -> Optional[str]:
    """The stdin a transport should feed: sudo -S reads the password from
    the first stdin line, so prepend it ahead of any command stdin
    (reference semantics: control/core.clj:142-153 feeds *password*)."""
    if command.sudo and command.sudo_password is not None:
        return command.sudo_password + "\n" + (command.stdin or "")
    return command.stdin


@dataclass
class Result:
    cmd: str
    exit: int = 0
    out: str = ""
    err: str = ""
    node: Any = None


class RemoteError(Exception):
    def __init__(self, result: Result, msg: str = ""):
        self.result = result
        super().__init__(
            msg
            or f"Command on {result.node!r} returned exit status "
            f"{result.exit}\ncmd: {result.cmd}\nout: {result.out}\n"
            f"err: {result.err}"
        )


def throw_on_nonzero_exit(result: Result) -> Result:
    """(reference: control/core.clj:155-171)"""
    if result.exit != 0:
        raise RemoteError(result)
    return result


class Remote:
    """A transport for running commands and moving files.
    (reference: control/core.clj:7-58)

    connect returns a *connected* remote bound to one node; execute/
    upload/download run on that bound instance.
    """

    def connect(self, node: Any, test: Optional[dict] = None) -> "Remote":
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def execute(self, command: Command) -> Result:
        raise NotImplementedError

    def upload(self, local_paths: Union[str, Sequence[str]], remote_path: str) -> None:
        raise NotImplementedError

    def download(self, remote_paths: Union[str, Sequence[str]], local_path: str) -> None:
        raise NotImplementedError


class DummyRemote(Remote):
    """Performs no IO; records every command.  The reference's :dummy?
    mode (control.clj:40) — lets full tests run in-process.
    """

    def __init__(self, node: Any = None, log_: Optional[List[Command]] = None):
        self.node = node
        self.log = log_ if log_ is not None else []

    def connect(self, node, test=None):
        return DummyRemote(node, self.log)

    def execute(self, command: Command) -> Result:
        self.log.append((self.node, command))
        return Result(cmd=command.cmd, exit=0, out="", err="", node=self.node)

    def upload(self, local_paths, remote_path):
        self.log.append((self.node, ("upload", local_paths, remote_path)))

    def download(self, remote_paths, local_path):
        self.log.append((self.node, ("download", remote_paths, local_path)))
