"""Remote over ``docker exec`` (reference:
jepsen/src/jepsen/control/docker.clj — resolve-container-id :14-30,
exec/upload/download via the docker CLI)."""

from __future__ import annotations

import subprocess
from typing import Optional

from .core import Command, Remote, Result, effective_stdin, wrap_sudo


class DockerRemote(Remote):
    def __init__(self, container_id: Optional[str] = None):
        self.container_id = container_id

    def connect(self, node, test=None):
        return DockerRemote(container_id=self._resolve(str(node)))

    @staticmethod
    def _resolve(node: str) -> str:
        """Accept a container name or id; resolve names via docker ps.
        (reference: control/docker.clj:14-30)"""
        proc = subprocess.run(
            ["docker", "ps", "-q", "-f", f"name={node}"],
            capture_output=True,
            timeout=30,
        )
        out = proc.stdout.decode().strip()
        return out.splitlines()[0] if out else node

    def execute(self, command: Command) -> Result:
        cmd = wrap_sudo(command)
        argv = ["docker", "exec"]
        stdin = effective_stdin(command)
        if stdin:
            argv.append("-i")
        argv += [self.container_id, "sh", "-c", cmd]
        proc = subprocess.run(
            argv,
            input=stdin.encode() if stdin else None,
            capture_output=True,
            timeout=600,
        )
        return Result(
            cmd=cmd,
            exit=proc.returncode,
            out=proc.stdout.decode(errors="replace"),
            err=proc.stderr.decode(errors="replace"),
            node=self.container_id,
        )

    def upload(self, local_paths, remote_path):
        paths = [local_paths] if isinstance(local_paths, str) else list(local_paths)
        for p in paths:
            subprocess.run(
                ["docker", "cp", str(p), f"{self.container_id}:{remote_path}"],
                check=True,
                timeout=600,
            )

    def download(self, remote_paths, local_path):
        paths = [remote_paths] if isinstance(remote_paths, str) else list(remote_paths)
        for p in paths:
            subprocess.run(
                ["docker", "cp", f"{self.container_id}:{p}", str(local_path)],
                check=True,
                timeout=600,
            )


def docker() -> DockerRemote:
    return DockerRemote()
