"""Remote over ``kubectl exec`` (reference:
jepsen/src/jepsen/control/k8s.clj — exec :15-40, cp-based transfer)."""

from __future__ import annotations

import subprocess
from typing import Optional

from .core import Command, Remote, Result, effective_stdin, wrap_sudo


class K8sRemote(Remote):
    def __init__(self, namespace: str = "default", pod: Optional[str] = None):
        self.namespace = namespace
        self.pod = pod

    def connect(self, node, test=None):
        return K8sRemote(self.namespace, pod=str(node))

    def execute(self, command: Command) -> Result:
        cmd = wrap_sudo(command)
        argv = ["kubectl", "exec", "-n", self.namespace]
        stdin = effective_stdin(command)
        if stdin:
            argv.append("-i")
        argv += [self.pod, "--", "sh", "-c", cmd]
        proc = subprocess.run(
            argv,
            input=stdin.encode() if stdin else None,
            capture_output=True,
            timeout=600,
        )
        return Result(
            cmd=cmd,
            exit=proc.returncode,
            out=proc.stdout.decode(errors="replace"),
            err=proc.stderr.decode(errors="replace"),
            node=self.pod,
        )

    def upload(self, local_paths, remote_path):
        paths = [local_paths] if isinstance(local_paths, str) else list(local_paths)
        for p in paths:
            subprocess.run(
                [
                    "kubectl", "cp", "-n", self.namespace, str(p),
                    f"{self.pod}:{remote_path}",
                ],
                check=True,
                timeout=600,
            )

    def download(self, remote_paths, local_path):
        paths = [remote_paths] if isinstance(remote_paths, str) else list(remote_paths)
        for p in paths:
            subprocess.run(
                [
                    "kubectl", "cp", "-n", self.namespace,
                    f"{self.pod}:{p}", str(local_path),
                ],
                check=True,
                timeout=600,
            )


def k8s(namespace: str = "default") -> K8sRemote:
    return K8sRemote(namespace)
