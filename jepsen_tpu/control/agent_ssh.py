"""Alternative SSH transport with an sshj-style auth ladder.

The primary transport (:mod:`.ssh`) is key-only: ``BatchMode=yes``
refuses any interactive auth, so agent-forwarded identities and
password logins are out of reach.  This transport mirrors the
reference's experimental sshj remote (jepsen/src/jepsen/control/
sshj.clj:43-70 auth!), which tries, in order:

1. the explicitly configured private key (pinned via IdentitiesOnly),
2. the running ssh-agent's identities (SSH_AUTH_SOCK / IdentityAgent),
3. the default ~/.ssh identity files,
4. username + password.

Steps 1–3 ride normal ssh flags; step 4 uses an ``SSH_ASKPASS`` helper
(with ``SSH_ASKPASS_REQUIRE=force``) since the image has no sshpass.
The first rung that authenticates is remembered per connection so later
commands don't re-probe the whole ladder.
"""

from __future__ import annotations

import os
import stat
import subprocess
import tempfile
from typing import List, Optional, Tuple

from .core import Command, Remote, Result, effective_stdin, wrap_sudo
from .ssh import _as_paths, run_scp


class AgentSSHRemote(Remote):
    """Subprocess-ssh remote that can authenticate via agent or
    password, not just a pinned key."""

    def __init__(
        self,
        username: str = "root",
        password: Optional[str] = None,
        port: int = 22,
        private_key_path: Optional[str] = None,
        strict_host_key_checking: bool = False,
        connect_timeout: int = 10,
    ):
        self.username = username
        self.password = password
        self.port = port
        self.private_key_path = private_key_path
        self.strict = strict_host_key_checking
        self.connect_timeout = connect_timeout
        self.node: Optional[str] = None
        self._tmpdir: Optional[str] = None
        #: rungs of the auth ladder, tried lazily on first command
        self._auth: Optional[List[str]] = None

    @staticmethod
    def from_test(test: dict) -> "AgentSSHRemote":
        ssh = test.get("ssh", {})
        return AgentSSHRemote(
            username=ssh.get("username", "root"),
            password=ssh.get("password"),
            port=ssh.get("port", 22),
            private_key_path=ssh.get("private-key-path"),
            strict_host_key_checking=ssh.get("strict-host-key-checking", False),
        )

    # -- auth ladder -------------------------------------------------------

    def _common_args(self) -> list:
        args = [
            "-p", str(self.port),
            "-o", f"ConnectTimeout={self.connect_timeout}",
        ]
        if not self.strict:
            args += [
                "-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null",
                "-o", "LogLevel=ERROR",
            ]
        return args

    def _askpass_script(self) -> str:
        """An SSH_ASKPASS helper that prints the password.  0600, inside
        this connection's private tmpdir."""
        path = os.path.join(self._tmpdir, "askpass.sh")
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write(f"#!/bin/sh\nprintf '%s' {_sh_quote(self.password)}\n")
            os.chmod(path, stat.S_IRUSR | stat.S_IWUSR | stat.S_IXUSR)
        return path

    def auth_rungs(self) -> List[Tuple[list, dict]]:
        """The (extra ssh args, extra env) ladder, most-specific first.
        (reference: sshj.clj:43-70 auth!)"""
        rungs: List[Tuple[list, dict]] = []
        if self.private_key_path:
            rungs.append(
                (
                    ["-o", "IdentitiesOnly=yes", "-i", self.private_key_path,
                     "-o", "BatchMode=yes"],
                    {},
                )
            )
        if os.environ.get("SSH_AUTH_SOCK"):
            rungs.append(
                (
                    ["-o", f"IdentityAgent={os.environ['SSH_AUTH_SOCK']}",
                     "-o", "BatchMode=yes"],
                    {},
                )
            )
        # default ~/.ssh identities
        rungs.append((["-o", "BatchMode=yes"], {}))
        if self.password is not None and self._tmpdir:
            rungs.append(
                (
                    ["-o", "PreferredAuthentications=password,"
                           "keyboard-interactive",
                     "-o", "NumberOfPasswordPrompts=1"],
                    {
                        "SSH_ASKPASS": self._askpass_script(),
                        "SSH_ASKPASS_REQUIRE": "force",
                        # some ssh builds demand DISPLAY for askpass
                        "DISPLAY": os.environ.get("DISPLAY", "none:0"),
                    },
                )
            )
        return rungs

    def _run_ssh(self, args: list, env: dict, cmd: str, stdin) -> subprocess.CompletedProcess:
        full_env = {**os.environ, **env}
        return subprocess.run(
            ["ssh"] + self._common_args() + args
            + [f"{self.username}@{self.node}", cmd],
            input=stdin.encode() if stdin else None,
            capture_output=True,
            timeout=600,
            env=full_env,
        )

    def _authed(self) -> Tuple[list, dict]:
        """Probe the ladder once; remember the first rung that works."""
        if self._auth is not None:
            return self._auth
        last = None
        for args, env in self.auth_rungs():
            probe = self._run_ssh(args, env, "true", None)
            if probe.returncode == 0:
                self._auth = (args, env)
                return self._auth
            last = probe
        raise RuntimeError(
            f"every auth method failed for {self.username}@{self.node}: "
            + (last.stderr.decode(errors="replace") if last else "no rungs")
        )

    # -- Remote protocol ---------------------------------------------------

    def connect(self, node, test=None):
        r = AgentSSHRemote(
            self.username,
            self.password,
            self.port,
            self.private_key_path,
            self.strict,
            self.connect_timeout,
        )
        r.node = str(node)
        r._tmpdir = tempfile.mkdtemp(prefix="jepsen-assh-")
        return r

    def disconnect(self):
        if self._tmpdir:
            import shutil

            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None

    def execute(self, command: Command) -> Result:
        cmd = wrap_sudo(command)
        stdin = effective_stdin(command)
        args, env = self._authed()
        proc = self._run_ssh(args, env, cmd, stdin)
        return Result(
            cmd=cmd,
            exit=proc.returncode,
            out=proc.stdout.decode(errors="replace"),
            err=proc.stderr.decode(errors="replace"),
            node=self.node,
        )

    def upload(self, local_paths, remote_path):
        args, env = self._authed()
        run_scp(
            self._common_args() + args,
            _as_paths(local_paths),
            f"{self.username}@{self.node}:{remote_path}",
            env={**os.environ, **env},
        )

    def download(self, remote_paths, local_path):
        args, env = self._authed()
        run_scp(
            self._common_args() + args,
            [f"{self.username}@{self.node}:{p}" for p in _as_paths(remote_paths)],
            str(local_path),
            env={**os.environ, **env},
        )


def _sh_quote(s: Optional[str]) -> str:
    if s is None:
        return "''"
    return "'" + str(s).replace("'", "'\\''") + "'"
