"""SCP-subprocess file transfer decorator.

Wraps a command-capable Remote, overriding upload/download to shell out
to the system ``scp`` binary — library transports can be orders of
magnitude slower than scp for multi-GB files.
(reference: jepsen/src/jepsen/control/scp.clj:1-144)

When the transfer must land somewhere only another user can write (the
command context carries sudo), files route through a root-owned tmp file
and are chown/mv'd into place, mirroring scp.clj:95-140.
"""

from __future__ import annotations

import os
import random
import subprocess
from typing import Any, Optional, Sequence, Union

from .core import (
    Command,
    Remote,
    RemoteError,
    Result,
    escape,
    lit,
    throw_on_nonzero_exit,
)

TMP_DIR = "/tmp/jepsen/scp"
"""Remote staging directory for sudo'd transfers (scp.clj:12-15)."""


class SCPRemote(Remote):
    """Delegates execute to ``cmd_remote``; upload/download use scp.
    (reference: control/scp.clj:80-140)"""

    def __init__(
        self,
        cmd_remote: Remote,
        username: str = "root",
        port: int = 22,
        private_key_path: Optional[str] = None,
        sudo: Optional[str] = None,
        strict_host_key_checking: bool = False,
    ):
        self.cmd_remote = cmd_remote
        self.username = username
        self.port = port
        self.private_key_path = private_key_path
        self.sudo = sudo
        self.strict = strict_host_key_checking
        self.node: Optional[str] = None
        self._tmp_dir_ready = False

    def connect(self, node, test=None):
        ssh = (test or {}).get("ssh", {})
        r = SCPRemote(
            self.cmd_remote.connect(node, test),
            username=ssh.get("username", self.username),
            port=ssh.get("port", self.port),
            private_key_path=ssh.get("private-key-path", self.private_key_path),
            sudo=self.sudo,
            strict_host_key_checking=ssh.get(
                "strict-host-key-checking", self.strict
            ),
        )
        r.node = str(node)
        return r

    def disconnect(self):
        self.cmd_remote.disconnect()

    def execute(self, command: Command) -> Result:
        return self.cmd_remote.execute(command)

    # -- scp plumbing ------------------------------------------------------

    def _scp(self, sources: Sequence[str], dest: str) -> None:
        """Run one scp subprocess (reference: scp.clj:59-70)."""
        args = ["scp", "-rpC", "-P", str(self.port)]
        if self.private_key_path:
            args += ["-i", self.private_key_path]
        if not self.strict:
            args += [
                "-o",
                "StrictHostKeyChecking=no",
                "-o",
                "UserKnownHostsFile=/dev/null",
                "-o",
                "LogLevel=ERROR",
            ]
        args += ["-o", "BatchMode=yes"]
        proc = subprocess.run(
            args + [str(s) for s in sources] + [dest],
            capture_output=True,
            timeout=3600,
        )
        if proc.returncode != 0:
            raise RemoteError(
                Result(
                    cmd=" ".join(args),
                    exit=proc.returncode,
                    err=proc.stderr.decode(errors="replace"),
                    node=self.node,
                ),
                f"scp to/from {self.node} failed: "
                f"{proc.stderr.decode(errors='replace')}",
            )

    def _remote_path(self, path: str) -> str:
        """user@host:path string (reference: scp.clj:72-79)."""
        assert self.node, "No node given for remote-path!"
        prefix = f"{self.username}@" if self.username else ""
        return f"{prefix}{self.node}:{path}"

    def _exec_root(self, *tokens: Any) -> Result:
        """Run a root command through the wrapped remote
        (reference: scp.clj:17-27)."""
        cmd = " ".join(escape(t) for t in tokens)
        return throw_on_nonzero_exit(
            self.cmd_remote.execute(Command(cmd=cmd, sudo="root"))
        )

    def _tmp_file(self) -> str:
        """A random remote staging path; ensures TMP_DIR exists once per
        connection (reference: scp.clj:29-56)."""
        if not self._tmp_dir_ready:
            self._exec_root(
                lit(f"mkdir -p {escape(TMP_DIR)} && chmod a+rwx {escape(TMP_DIR)}")
            )
            self._tmp_dir_ready = True
        return f"{TMP_DIR}/{random.randrange(2**31)}"

    def _cleanup(self, tmp: str) -> None:
        """Best-effort staging cleanup: a node that just got partitioned
        must not let the rm failure mask the transfer error in flight."""
        try:
            self._exec_root("rm", "-rf", tmp)
        except Exception:
            pass

    # -- operations --------------------------------------------------------

    def upload(self, local_paths: Union[str, Sequence[str]], remote_path: str) -> None:
        paths = (
            [local_paths]
            if isinstance(local_paths, (str, os.PathLike))
            else list(local_paths)
        )
        if self.sudo is None or self.sudo == self.username:
            self._scp(paths, self._remote_path(remote_path))
            return
        # Becoming another user: stage via tmpfile, chown, mv
        # (reference: scp.clj:100-110).  A directory dest keeps each
        # source's basename; a file dest can only take one source.
        import posixpath

        dest_is_dir = (
            self.cmd_remote.execute(
                Command(cmd=f"test -d {escape(remote_path)}", sudo="root")
            ).exit
            == 0
        )
        if not dest_is_dir and len(paths) > 1:
            raise ValueError(
                f"cannot upload {len(paths)} files to single path {remote_path!r}"
            )
        for src in paths:
            tmp = self._tmp_file()
            dest = (
                posixpath.join(remote_path, posixpath.basename(str(src).rstrip("/")))
                if dest_is_dir
                else remote_path
            )
            try:
                self._scp([src], self._remote_path(tmp))
                self._exec_root("chown", "-R", self.sudo, tmp)
                self._exec_root("mv", tmp, dest)
            finally:
                self._cleanup(tmp)

    def download(self, remote_paths: Union[str, Sequence[str]], local_path: str) -> None:
        paths = (
            [remote_paths]
            if isinstance(remote_paths, (str, os.PathLike))
            else list(remote_paths)
        )
        if self.sudo is None or self.sudo == self.username:
            self._scp([self._remote_path(p) for p in paths], str(local_path))
            return
        # Copy anything we can't read directly into a readable staging
        # dir first (reference: scp.clj:112-140 — but via cp -r, never a
        # hardlink: chowning a hardlink would mutate the source inode's
        # ownership on the node).
        import posixpath

        for src in paths:
            readable = (
                self.cmd_remote.execute(Command(cmd=f"head -c 1 {escape(src)}")).exit
                == 0
            )
            if readable:
                self._scp([self._remote_path(src)], str(local_path))
                continue
            tmp = self._tmp_file()
            staged = posixpath.join(tmp, posixpath.basename(str(src).rstrip("/")))
            try:
                self._exec_root("mkdir", "-p", tmp)
                self._exec_root("cp", "-r", src, staged)
                self._exec_root("chown", "-R", self.username, tmp)
                self._scp([self._remote_path(staged)], str(local_path))
            finally:
                self._cleanup(tmp)


def remote(cmd_remote: Remote, **kw) -> SCPRemote:
    """Wrap a command remote so transfers go over scp
    (reference: scp.clj:141-144)."""
    return SCPRemote(cmd_remote, **kw)
