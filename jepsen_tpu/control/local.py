"""Local remote: run "remote" commands as subprocesses on the control
host itself.  Useful for single-machine tests and for exercising the
full control stack (daemon helpers, net, OS setup command paths) without
SSH.  No reference equivalent — the reference's closest mode is
:dummy? (control.clj:40), which performs no IO at all.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import Optional

from .core import Command, Remote, Result, effective_stdin, wrap_sudo


class LocalRemote(Remote):
    def __init__(self, node=None):
        self.node = node

    def connect(self, node, test=None):
        return LocalRemote(node)

    def execute(self, command: Command) -> Result:
        cmd = wrap_sudo(command)
        stdin = effective_stdin(command)
        proc = subprocess.run(
            ["bash", "-c", cmd],
            input=stdin.encode() if stdin else None,
            capture_output=True,
            timeout=600,
        )
        return Result(
            cmd=cmd,
            exit=proc.returncode,
            out=proc.stdout.decode(errors="replace"),
            err=proc.stderr.decode(errors="replace"),
            node=self.node,
        )

    def upload(self, local_paths, remote_path):
        paths = [local_paths] if isinstance(local_paths, str) else list(local_paths)
        for p in paths:
            shutil.copy(str(p), remote_path)

    def download(self, remote_paths, local_path):
        paths = [remote_paths] if isinstance(remote_paths, str) else list(remote_paths)
        for p in paths:
            shutil.copy(str(p), str(local_path))


def local() -> LocalRemote:
    return LocalRemote()
