"""Remote-command control plane.

The dynamic-context command DSL over pluggable Remote transports
(reference: jepsen/src/jepsen/control.clj:40-319 — dynamic vars, exec,
su/sudo/cd, upload/download, sessions, on-nodes).

This module holds the dynamic execution context (current node, session,
sudo/dir state, thread-local) and the session lifecycle; transports live
in submodules (``core`` for the Remote protocol and escaping, ``ssh``,
``docker``, ``k8s``, ``retry``, ``scp``, and a dummy remote mirroring the
reference's ``:dummy?`` mode, control.clj:40, used by in-process tests).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

from .core import Remote, DummyRemote, RemoteError, lit, escape  # noqa: F401

# The node binding is thread-local (each on-nodes worker thread binds its
# own node — the reference uses dynamic vars with binding conveyance,
# control.clj:40-53 + util.clj:65-83).  The session table is
# process-global: worker threads spawned by real_pmap must see it.
_local = threading.local()
_sessions_lock = threading.Lock()
_sessions: Dict[Any, Remote] = {}


@contextmanager
def with_session(test: dict, remote: Remote):
    """Open a session per node; body runs with sessions available.
    Sessions do not nest: one test's control plane at a time.
    (reference: core.clj:275-296 with-sessions + control.clj:226-266)"""
    sessions = {}
    try:
        for node in test["nodes"]:
            sessions[node] = remote.connect(node, test)
        with _sessions_lock:
            _sessions.update(sessions)
        try:
            yield sessions
        finally:
            with _sessions_lock:
                for node in sessions:
                    _sessions.pop(node, None)
    finally:
        for s in sessions.values():
            try:
                s.disconnect()
            except Exception:
                pass


@contextmanager
def dummy_session(test: dict):
    """All commands become no-ops that record themselves — the
    reference's :dummy? ssh mode (control.clj:40, cli.clj:85-86)."""
    remote = DummyRemote()
    with with_session(test, remote) as sessions:
        yield sessions


def with_node(node: Any, fn: Callable[[], Any]) -> Any:
    """Bind the dynamic node for this thread while running fn.
    (reference: control.clj:272-293 on/on-nodes)"""
    prev = getattr(_local, "node", None)
    _local.node = node
    try:
        return fn()
    finally:
        _local.node = prev


def current_node() -> Optional[Any]:
    return getattr(_local, "node", None)


def current_session() -> Optional[Remote]:
    node = current_node()
    if node is None:
        return None
    with _sessions_lock:
        return _sessions.get(node)


# ---------------------------------------------------------------------------
# Command DSL (reference: control.clj:138-218 exec/su/sudo/cd,
# :167-189 upload/download)
# ---------------------------------------------------------------------------


def _dyn(name: str, default=None):
    return getattr(_local, name, default)


@contextmanager
def sudo(user: str = "root", password: Optional[str] = None):
    """Run body's commands as `user` (optionally with a sudo password,
    fed on stdin via sudo -S).  (reference: control.clj:203-213)"""
    prev = _dyn("sudo")
    prev_pw = _dyn("sudo_password")
    _local.sudo = user
    if password is not None:
        _local.sudo_password = password
    try:
        yield
    finally:
        _local.sudo = prev
        _local.sudo_password = prev_pw


su = sudo  # reference aliases su to sudo-as-root

#: process-wide default for command tracing, the `*trace*` dynamic var
#: (reference: control.clj:43); the `trace` context manager overrides it
#: per thread.
TRACE = False


@contextmanager
def trace(enabled: bool = True):
    """Log every command (with its node) before it runs in the body.
    (reference: control.clj:43 *trace* + :115-119 wrap-trace)"""
    # restore by deletion when previously unset: leaving `None` behind
    # would shadow the module-level TRACE default on this thread
    had = hasattr(_local, "trace")
    prev = _dyn("trace")
    _local.trace = enabled
    try:
        yield
    finally:
        if had:
            _local.trace = prev
        else:
            del _local.trace


@contextmanager
def cd(dir: str):
    """Run body's commands within `dir`.  (reference: control.clj:214-218)"""
    prev = _dyn("dir")
    _local.dir = dir
    try:
        yield
    finally:
        _local.dir = prev


def execute(*args, stdin: Optional[str] = None, check: bool = True):
    """Build + run one shell command on the current node's session.
    Args are escaped (Lit passes raw).  Returns stdout (stripped), like
    the reference's exec (control.clj:138-157)."""
    from .core import Command, escape, throw_on_nonzero_exit

    session = current_session()
    if session is None:
        raise RuntimeError(
            f"no session bound for node {current_node()!r}; "
            "use with_session/on_nodes"
        )
    cmd = " ".join(escape(a) for a in args)
    if _dyn("trace", TRACE):
        import logging

        logging.getLogger(__name__).info(
            "Host: %s cmd: %s", current_node(), cmd
        )
    command = Command(
        cmd=cmd,
        stdin=stdin,
        sudo=_dyn("sudo"),
        dir=_dyn("dir"),
        sudo_password=_dyn("sudo_password"),
    )
    from .. import obs

    with obs.span("control/exec", cat="control") as sp:
        sp.set("node", current_node())
        result = session.execute(command)
    obs.observe("jepsen_control_exec_seconds", sp.duration_s())
    if check:
        throw_on_nonzero_exit(result)
    return result.out.strip()


# short name matching the reference's c/exec
exec_ = execute


def upload(local_path, remote_path):
    """(reference: control.clj:167-178)"""
    session = current_session()
    if session is None:
        raise RuntimeError("no session bound")
    session.upload(local_path, remote_path)


def download(remote_path, local_path):
    """(reference: control.clj:179-189)"""
    session = current_session()
    if session is None:
        raise RuntimeError("no session bound")
    session.download(remote_path, local_path)


def _binding_snapshot() -> dict:
    """Capture the caller's dynamic bindings so worker threads inherit
    them — the reference's binding conveyance (util.clj:65-83)."""
    return {
        "sudo": _dyn("sudo"),
        "dir": _dyn("dir"),
        "sudo_password": _dyn("sudo_password"),
        "trace": _dyn("trace", TRACE),
    }


@contextmanager
def _with_bindings(snapshot: dict):
    # restore-by-deletion for keys that weren't set: leaving e.g.
    # trace=None behind would shadow its module-level default (the
    # worker may be the calling thread itself when the pool runs a task
    # inline)
    had = {k: hasattr(_local, k) for k in snapshot}
    prev = {k: _dyn(k) for k in snapshot}
    for k, v in snapshot.items():
        setattr(_local, k, v)
    try:
        yield
    finally:
        for k in snapshot:
            if had[k]:
                setattr(_local, k, prev[k])
            else:
                delattr(_local, k)


def on_nodes(test: dict, fn_or_nodes, maybe_fn=None) -> Dict[Any, Any]:
    """Run (fn test node) on some (default: all) nodes concurrently, with
    the node binding set and the caller's sudo/cd bindings conveyed.
    Returns {node: result}.  (reference: control.clj:295-311)"""
    from ..util import real_pmap

    if maybe_fn is None:
        nodes, fn = test["nodes"], fn_or_nodes
    else:
        nodes, fn = fn_or_nodes, maybe_fn
    snapshot = _binding_snapshot()

    def run_one(node):
        with _with_bindings(snapshot):
            return with_node(node, lambda: fn(test, node))

    return dict(zip(nodes, real_pmap(run_one, list(nodes))))


def on_many(nodes, thunk: Callable[[], Any]) -> Dict[Any, Any]:
    """Run thunk bound to each node concurrently; {node: result}.
    Conveys the caller's sudo/cd bindings into the worker threads.
    (reference: control.clj:272-293 on-many)"""
    from ..util import real_pmap

    snapshot = _binding_snapshot()

    def run_one(node):
        with _with_bindings(snapshot):
            return with_node(node, thunk)

    return dict(zip(nodes, real_pmap(run_one, list(nodes))))


def with_test_nodes(test: dict, thunk: Callable[[], Any]) -> Dict[Any, Any]:
    """(reference: control.clj with-test-nodes)"""
    return on_many(test["nodes"], thunk)
