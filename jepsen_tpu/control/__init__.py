"""Remote-command control plane.

The dynamic-context command DSL over pluggable Remote transports
(reference: jepsen/src/jepsen/control.clj:40-319 — dynamic vars, exec,
su/sudo/cd, upload/download, sessions, on-nodes).

This module holds the dynamic execution context (current node, session,
sudo/dir state, thread-local) and the session lifecycle; transports live
in submodules (``core`` for the Remote protocol and escaping, ``ssh``,
``docker``, ``k8s``, ``retry``, ``scp``, and a dummy remote mirroring the
reference's ``:dummy?`` mode, control.clj:40, used by in-process tests).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

from .core import Remote, DummyRemote, RemoteError, lit, escape  # noqa: F401

# The node binding is thread-local (each on-nodes worker thread binds its
# own node — the reference uses dynamic vars with binding conveyance,
# control.clj:40-53 + util.clj:65-83).  The session table is
# process-global: worker threads spawned by real_pmap must see it.
_local = threading.local()
_sessions_lock = threading.Lock()
_sessions: Dict[Any, Remote] = {}


@contextmanager
def with_session(test: dict, remote: Remote):
    """Open a session per node; body runs with sessions available.
    Sessions do not nest: one test's control plane at a time.
    (reference: core.clj:275-296 with-sessions + control.clj:226-266)"""
    sessions = {}
    try:
        for node in test["nodes"]:
            sessions[node] = remote.connect(node, test)
        with _sessions_lock:
            _sessions.update(sessions)
        try:
            yield sessions
        finally:
            with _sessions_lock:
                for node in sessions:
                    _sessions.pop(node, None)
    finally:
        for s in sessions.values():
            try:
                s.disconnect()
            except Exception:
                pass


@contextmanager
def dummy_session(test: dict):
    """All commands become no-ops that record themselves — the
    reference's :dummy? ssh mode (control.clj:40, cli.clj:85-86)."""
    remote = DummyRemote()
    with with_session(test, remote) as sessions:
        yield sessions


def with_node(node: Any, fn: Callable[[], Any]) -> Any:
    """Bind the dynamic node for this thread while running fn.
    (reference: control.clj:272-293 on/on-nodes)"""
    prev = getattr(_local, "node", None)
    _local.node = node
    try:
        return fn()
    finally:
        _local.node = prev


def current_node() -> Optional[Any]:
    return getattr(_local, "node", None)


def current_session() -> Optional[Remote]:
    node = current_node()
    if node is None:
        return None
    with _sessions_lock:
        return _sessions.get(node)
