"""Linearizability checking — CPU oracle.

Event-driven just-in-time linearization (the knossos.linear / knossos.wgl
algorithm family the reference consumes at checker.clj:199-203, here
re-derived rather than ported):

A *configuration* is ``(model-state, linearized-set)`` where the
linearized-set holds ops that have been linearized but whose completion
event hasn't been reached yet.  Walking the history event by event:

- ``invoke i``: op i becomes *open* (callable).  No expansion yet —
  closure is deferred to the next filtering event, which is sound because
  closure only ever grows the config set.
- ``ok i``: first expand the closure — repeatedly linearize any open,
  not-yet-linearized op against every config (dropping inconsistent
  steps) until fixpoint — then keep only configs that linearized i, and
  remove i from their linearized-sets (it is now part of the common
  prefix).  An empty config set here means the history is not
  linearizable, and op i is the witness.
- ``info i``: op i stays open forever — it may linearize at any later
  point, or never (indeterminate ops are concurrent with everything after
  them; reference semantics per knossos).
- ``fail i``: op i never happened; it and its invocation are removed in
  preprocessing.

Real-time order is respected structurally: an op invoked after ``ok i``
only enters ``open`` after configs that failed to linearize i have been
discarded.

The TPU implementation in jepsen_tpu.ops.wgl runs this same search as a
vmapped bitset frontier expansion; this module is its differential-test
oracle and the fallback when no accelerator is present.
"""

from __future__ import annotations

import threading as _threading
from typing import Any, Dict, FrozenSet, Iterable, Optional, Set, Tuple

from ..history import History, INVOKE, OK, FAIL, INFO, Op
from ..models import Model

#: Bound on the config-set size before we give up with :unknown.  Mirrors
#: the reference's practice of truncating/giving-up on pathological
#: searches (checker.clj:213-216).
DEFAULT_MAX_CONFIGS = 100_000


class Analysis(dict):
    """Result dict with attribute sugar."""


def prepare(history: History, pure_fs: Iterable[Any] = ()) -> Tuple[list, list]:
    """Preprocess a raw history into (events, ops):

    events: [(kind, op_id)] with kind ∈ {invoke, ok, info};
    ops:    [Op] per op id, with completion values propagated onto the
            invocation (so a read's observed value is available when the
            op linearizes).

    Failed ops are dropped entirely; indeterminate ops whose :f is in
    pure_fs (state-preserving reads) are dropped too.

    One fused pass: pairing, failure/pure-read dropping, and value
    propagation together.  The returned ops ALIAS the caller's Op
    objects except where a completion changed the value (those are
    copied before mutation) — callers must treat them as read-only;
    anything needing to mutate must copy first.  The former
    copy-every-invocation pipeline dominated host encoding cost
    (SURVEY.md §7, host↔device feed rate).
    """
    pure = set(pure_fs)
    events: list = []
    ops: list = []
    open_by_process: Dict[Any, int] = {}
    dropped: set = set()
    def propagate(op_id, value):
        """Copy-on-write value propagation: the ops list holds the
        caller's Op objects until a completion actually changes one —
        unconditional copies dominated the host encode path (~30% of
        batch_encode, SURVEY §7 host↔device feed rate)."""
        if value is not None and ops[op_id].value != value:
            ops[op_id] = ops[op_id].copy()
            ops[op_id].value = value

    for op in history:
        p = op.process
        if not isinstance(p, int):
            continue
        t = op.type
        if t == INVOKE:
            op_id = len(ops)
            ops.append(op)
            open_by_process[p] = op_id
            events.append((INVOKE, op_id))
        elif t == OK:
            op_id = open_by_process.pop(p, None)
            if op_id is not None:
                propagate(op_id, op.value)
                events.append((OK, op_id))
        elif t == FAIL:
            op_id = open_by_process.pop(p, None)
            if op_id is not None:
                dropped.add(op_id)  # a failed op never took effect
        elif t == INFO:
            op_id = open_by_process.pop(p, None)
            if op_id is not None:
                if op.f in pure:
                    # a crashed pure read always linearizes and never
                    # changes state: drop it to shrink the search
                    dropped.add(op_id)
                else:
                    # an info completion may still carry payload the
                    # invocation lacked (e.g. lock clients stamp WHO
                    # acted on the way out); without it an owner-aware
                    # model could never linearize the op and would
                    # wrongly poison every later legitimate step
                    propagate(op_id, op.value)
                    events.append((INFO, op_id))
    # processes whose invoke never completed at all: same as info (open
    # forever)
    for op_id in open_by_process.values():
        events.append((INFO, op_id))
    if dropped:
        # compact ids so dropped ops vanish entirely (their values must
        # not leak into encoders' value maps or domain probes)
        remap: Dict[int, int] = {}
        kept: list = []
        for op_id, op in enumerate(ops):
            if op_id not in dropped:
                remap[op_id] = len(kept)
                kept.append(op)
        ops = kept
        events = [
            (k, remap[op_id]) for k, op_id in events if op_id not in dropped
        ]
    return events, ops


def _closure(
    configs: Set[Tuple[Model, FrozenSet[int]]],
    open_ops: Set[int],
    ops: list,
    max_configs: int,
    parents: Optional[Dict] = None,
    deadline: Optional[float] = None,
) -> Tuple[Set[Tuple[Model, FrozenSet[int]]], bool]:
    """Expand configs by linearizing open ops until fixpoint.
    Returns (configs, reason) with reason None (fixpoint reached),
    "configs" (max_configs blown), or "deadline" (budget blown).  When
    ``parents`` is given, each
    newly reached config records (parent-config, op-id) so a witness
    path can be reconstructed for failure reports.  A ``deadline``
    (time.monotonic timestamp) bounds WALL TIME the way max_configs
    bounds memory: blown budgets report overflowed, which the caller
    turns into an honest "unknown"."""
    import time as _time

    frontier = configs
    seen = set(configs)
    while frontier:
        if deadline is not None and _time.monotonic() > deadline:
            return seen, "deadline"
        new: Set[Tuple[Model, FrozenSet[int]]] = set()
        for model, linset in frontier:
            for op_id in open_ops:
                if op_id in linset:
                    continue
                op = ops[op_id]
                model2 = model.step(op)
                if model2.is_inconsistent:
                    continue
                cfg = (model2, linset | {op_id})
                if cfg not in seen:
                    seen.add(cfg)
                    new.add(cfg)
                    if parents is not None:
                        parents[cfg] = ((model, linset), op_id)
                    if len(seen) > max_configs:
                        return seen, "configs"
        frontier = new
    return seen, None


def _final_paths(
    configs: Set[Tuple[Model, FrozenSet[int]]],
    parents: Dict,
    ops: list,
    failing_op: Op,
    limit: int = 10,
) -> list:
    """Representative linearization paths (since the previous completed
    op) leading to each final config — the knossos-report
    ``:final-paths`` equivalent.  ``why`` records the model's exact
    complaint when the failing op steps from that config's state."""
    paths = []
    for cfg in sorted(configs, key=lambda c: repr(c))[:limit]:
        stepped = cfg[0].step(failing_op)
        why = (
            str(getattr(stepped, "msg", "inconsistent"))
            if stepped.is_inconsistent
            else "op not linearizable here"
        )
        steps = []
        cur = cfg
        while cur in parents:
            (pcfg, op_id) = parents[cur]
            steps.append(
                {
                    "op": ops[op_id].to_dict(),
                    "op-id": op_id,
                    "model": repr(cur[0]),
                }
            )
            cur = pcfg
        steps.reverse()
        paths.append(
            {
                "init": repr(cur[0]),
                "steps": steps,
                "pending": sorted(cfg[1]),
                "why": why,
            }
        )
    return paths


def _partition_by_key(model: Model, events: list, ops: list):
    """P-compositionality (knossos-style, arXiv:1504.00204), driven by
    the models' partition protocol (``partition_key`` /
    ``subhistory_model`` / ``partition_op`` — the same protocol the
    engine-side pass :mod:`jepsen_tpu.engine.decompose` consumes):
    a history whose every op touches exactly one partition is
    linearizable iff each partition's subhistory is linearizable
    against that partition's sub-model.  Returns
    [(submodel, events, ops)] per partition in first-seen order, or
    None when the model declares no partition or any op's partition is
    undeterminable.  The per-partition searches are exponentially
    smaller than the product search (the config set factors across
    partitions).  Ops here are post-``prepare`` (completion values
    propagated onto invocations), so a dequeue's value is resolved."""
    key_fn = getattr(model, "partition_key", None)
    if not callable(key_fn):
        return None
    op_key: list = []
    for op in ops:
        k = key_fn(op)
        if k is None:
            return None
        op_key.append(k)
    parts: Dict[Any, Tuple[list, list, Dict[int, int]]] = {}
    order: list = []
    for kind, op_id in events:
        k = op_key[op_id]
        if k not in parts:
            parts[k] = ([], [], {})
            order.append(k)
        ev_k, ops_k, remap = parts[k]
        if op_id not in remap:
            remap[op_id] = len(ops_k)
            ops_k.append(model.partition_op(ops[op_id], k))
        ev_k.append((kind, remap[op_id]))
    return [
        (model.subhistory_model(k), parts[k][0], parts[k][1]) for k in order
    ]


def _search_fast(
    model: Model,
    events: list,
    ops: list,
    max_configs: int,
    deadline: Optional[float],
    budget_s: Optional[float],
) -> dict:
    """The hot search core: states interned to ints, (state, op) steps
    memoized, linearized-sets as int bitmasks — configs are (int, int)
    tuples, so hashing and set algebra cost a fraction of the
    object-based path.  Mask bits are compact SLOTS recycled as ops
    complete (bounded by peak concurrency plus never-returning info
    ops), not global op ids — masks stay machine-word sized on long
    histories.  Same algorithm and verdicts as the witness path; the
    step memo is sound because Model.step is a pure function of
    (state value, op value)."""
    import time as _time

    states: list = [model]
    sids: Dict[Model, int] = {model: 0}
    step_memo: Dict[Tuple[int, int], int] = {}
    configs: Set[Tuple[int, int]] = {(0, 0)}
    open_ops: list = []
    slot_of: Dict[int, int] = {}
    slot_owner: Dict[int, int] = {}
    free_slots: list = []
    next_slot = 0

    def overflow_out(reason: str, op_id: int) -> dict:
        return {
            "valid?": "unknown",
            "error": (
                f"oracle time budget ({budget_s}s) exceeded; "
                "aborting search"
                if reason == "deadline"
                else f"config set exceeded {max_configs}; aborting search"
            ),
            "op": ops[op_id].to_dict(),
        }

    def sample_configs(cfgs) -> list:
        out = []
        for sid, mask in list(cfgs)[:10]:
            pending = []
            m = mask
            while m:
                low = m & -m
                pending.append(slot_owner.get(low.bit_length() - 1))
                m ^= low
            out.append(
                {"model": repr(states[sid]), "pending": sorted(pending)}
            )
        return out

    for kind, op_id in events:
        if kind == INVOKE:
            open_ops.append(op_id)
            if free_slots:
                slot = free_slots.pop()
            else:
                slot = next_slot
                next_slot += 1
            slot_of[op_id] = slot
            slot_owner[slot] = op_id
        elif kind == OK:
            # closure to fixpoint, then filter on op_id's bit
            frontier = configs
            seen = set(configs)
            reason = None
            while frontier:
                if deadline is not None and _time.monotonic() > deadline:
                    reason = "deadline"
                    break
                new: Set[Tuple[int, int]] = set()
                for sid, mask in frontier:
                    for oid in open_ops:
                        bit = 1 << slot_of[oid]
                        if mask & bit:
                            continue
                        key = (sid, oid)
                        nsid = step_memo.get(key)
                        if nsid is None:
                            m2 = states[sid].step(ops[oid])
                            if m2.is_inconsistent:
                                nsid = -1
                            else:
                                nsid = sids.get(m2)
                                if nsid is None:
                                    nsid = len(states)
                                    sids[m2] = nsid
                                    states.append(m2)
                            step_memo[key] = nsid
                        if nsid < 0:
                            continue
                        cfg = (nsid, mask | bit)
                        if cfg not in seen:
                            seen.add(cfg)
                            new.add(cfg)
                            if len(seen) > max_configs:
                                reason = "configs"
                                break
                    if reason:
                        break
                if reason:
                    break
                frontier = new
            if reason:
                return overflow_out(reason, op_id)
            slot = slot_of[op_id]
            bit = 1 << slot
            survivors = {
                (sid, mask & ~bit) for sid, mask in seen if mask & bit
            }
            if not survivors:
                return {
                    "valid?": False,
                    "op": ops[op_id].to_dict(),
                    "configs": sample_configs(seen),
                }
            configs = survivors
            open_ops.remove(op_id)
            # no surviving mask holds the bit anymore: recycle the slot
            del slot_of[op_id]
            del slot_owner[slot]
            free_slots.append(slot)
        elif kind == INFO:
            pass

    return {
        "valid?": True,
        "configs": sample_configs(configs),
        "op-count": len(ops),
    }


#: worker-pool width for concurrent oracle searches
#: (``JEPSEN_TPU_ORACLE_WORKERS`` overrides).  The searches are pure
#: Python, so threads trade GIL slices among themselves — the win the
#: pipelined engine buys is overlap with DEVICE wall time (the kernel
#: computes while the interpreter grinds the fallback searches), which
#: needs only that the searches run concurrently with dispatch, not
#: that they parallelize each other.
DEFAULT_ORACLE_WORKERS = 4

# the guard must pre-exist the first caller: creating it lazily would
# itself race (two first callers, two locks, two leaked executors)
_pool_lock = _threading.Lock()
_pool = None  # jt: guarded-by(_pool_lock)


def oracle_workers() -> int:
    import os

    try:
        return max(
            1,
            int(os.environ.get("JEPSEN_TPU_ORACLE_WORKERS",
                               DEFAULT_ORACLE_WORKERS)),
        )
    except ValueError:
        return DEFAULT_ORACLE_WORKERS


def oracle_pool():
    """The shared bounded worker pool for oracle fallback searches —
    one per process, sized by :func:`oracle_workers`.  The pipelined
    engine (jepsen_tpu.engine.pipeline) submits fallback analyses here
    so ``_search_fast`` runs concurrently with in-flight device
    dispatches instead of after the last one settles."""
    import concurrent.futures

    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=oracle_workers(),
                thread_name_prefix="jepsen-oracle",
            )
        return _pool


def analysis_async(
    model: Model,
    history: History,
    pure_fs: Iterable[Any] = (),
    max_configs: int = DEFAULT_MAX_CONFIGS,
    witness: bool = False,
    budget_s: Optional[float] = None,
):
    """:func:`analysis` submitted to the shared oracle worker pool;
    returns a ``concurrent.futures.Future``.  Safe because the search
    is a pure function of its arguments (interned states and memos are
    all call-local) and the obs hooks are thread-aware."""
    return oracle_pool().submit(
        analysis, model, history, pure_fs, max_configs, witness, budget_s
    )


def analysis(
    model: Model,
    history: History,
    pure_fs: Iterable[Any] = (),
    max_configs: int = DEFAULT_MAX_CONFIGS,
    witness: bool = False,
    budget_s: Optional[float] = None,
) -> dict:
    """Telemetry wrapper over :func:`_analysis_impl` (the documented
    entry point — same signature, same result): each oracle run gets an
    ``engine`` span plus counters/latency so runs report how much work
    the CPU search absorbed (the fallback rate the device path's
    throughput claims rest on)."""
    from .. import obs

    if not obs.enabled():
        return _analysis_impl(
            model, history, pure_fs, max_configs, witness, budget_s
        )
    with obs.span("engine/oracle", cat="engine") as sp:
        r = _analysis_impl(
            model, history, pure_fs, max_configs, witness, budget_s
        )
        sp.set("valid", r.get("valid?"))
        sp.set("algorithm", r.get("algorithm", "search"))
        sp.set("op-count", r.get("op-count", ""))
    obs.observe("jepsen_oracle_seconds", sp.duration_s())
    obs.count(
        "jepsen_engine_analyses_total",
        engine="oracle",
        algorithm=str(r.get("algorithm", "search")),
    )
    return r


def _analysis_impl(
    model: Model,
    history: History,
    pure_fs: Iterable[Any] = (),
    max_configs: int = DEFAULT_MAX_CONFIGS,
    witness: bool = False,
    budget_s: Optional[float] = None,
) -> dict:
    """Check history against model. Returns
    {"valid?": True|False|"unknown", ...} with a witness :op on failure
    and sample :configs (truncated to 10, as the reference does at
    checker.clj:213-216).  ``witness=True`` additionally reconstructs
    ``final-paths`` (one linearization path per surviving config since
    the last completed op) and ``op-ids``/``ops`` context for the
    failure-witness renderer.

    Exception to the shape: plain-mutex histories decide via the
    search-free direct checker (``locks_direct``), whose results carry
    ``algorithm: "direct-mutex"`` and NO ``configs`` key (there is no
    config set to sample) — ``witness=True`` failures still re-search
    for the full report.  Treat ``configs`` as optional.

    ``budget_s`` bounds wall time: the exponential search (knossos
    class — its docs warn of runs taking hours) reports an honest
    "unknown" past the budget instead of hanging a whole analysis on
    one poisoned key.  None (the default) keeps the search unbounded."""
    import time as _time

    deadline = (
        _time.monotonic() + budget_s if budget_s is not None else None
    )
    events, ops = prepare(history, pure_fs)

    # Per-key decomposition first when the model factors (knossos-style
    # P-compositionality) — for BOTH paths: the fast search checks each
    # key, and a witness run then searches ONLY the failing key's
    # subhistory, so the witness report stays focused and the
    # object-based search never pays the whole-history state space.
    def witness_confirm(r, m, ev, op_l):
        """A fast-search failure re-searched with parent pointers so the
        report carries final-paths; the definite False is KEPT if the
        witness search cannot confirm within the remaining budget."""
        w = _search_witness(m, ev, op_l, max_configs, deadline, budget_s)
        return w if w.get("valid?") is False else r

    # Single-lock histories decide in O(n log n) with no search at all
    # (checker/locks_direct.py: plain mutex via greedy alternation
    # scheduling, owner-aware mutex via disjoint hold cores) — no
    # config space, no budget, no "unknown".  Witness requests still
    # re-search a failure so the final-paths report exists; the direct
    # verdict stands if the witness search blows its budget.  A None
    # return (uncovered model or structure) falls through to the
    # generic search.
    from . import locks_direct

    d = locks_direct.dispatch_events(model, events, ops)
    if d is not None:
        if d["valid?"] is False and witness:
            return witness_confirm(d, model, events, ops)
        return d

    parts = _partition_by_key(model, events, ops)
    if parts is not None and len(parts) > 1:
        worst = None
        for m_k, ev_k, ops_k in parts:
            # a partition's sub-model may itself have a direct checker
            # (multi-mutex → per-lock Mutex decides in O(n log n));
            # fall through to the fast search otherwise
            d_k = locks_direct.dispatch_events(m_k, ev_k, ops_k)
            r = d_k if d_k is not None else _search_fast(
                m_k, ev_k, ops_k, max_configs, deadline, budget_s
            )
            if r["valid?"] is False:
                if witness:
                    return witness_confirm(r, m_k, ev_k, ops_k)
                return r
            if r["valid?"] == "unknown":
                worst = r
        if worst is not None:
            return worst
        return {"valid?": True, "op-count": len(ops)}
    r = _search_fast(model, events, ops, max_configs, deadline, budget_s)
    if witness and r["valid?"] is False:
        return witness_confirm(r, model, events, ops)
    return r


def _search_witness(
    model: Model,
    events: list,
    ops: list,
    max_configs: int,
    deadline: Optional[float],
    budget_s: Optional[float],
) -> dict:
    """The object-based search with parent pointers: slower than
    :func:`_search_fast`, but a failure carries ``final-paths`` (one
    linearization path per surviving config since the last completed
    op) for the witness renderer."""
    configs: Set[Tuple[Model, FrozenSet[int]]] = {(model, frozenset())}
    open_ops: Set[int] = set()
    parents: Dict = {}

    for kind, op_id in events:
        if kind == INVOKE:
            open_ops.add(op_id)
        elif kind == OK:
            configs, overflow = _closure(
                configs, open_ops, ops, max_configs, parents, deadline
            )
            if overflow:
                return {
                    "valid?": "unknown",
                    "error": (
                        f"oracle time budget ({budget_s}s) exceeded; "
                        "aborting search"
                        if overflow == "deadline"
                        else f"config set exceeded {max_configs}; "
                        "aborting search"
                    ),
                    "op": ops[op_id].to_dict(),
                }
            # keep configs that linearized op_id; promote it into the prefix
            survivors = {
                (m, linset - {op_id}) for (m, linset) in configs if op_id in linset
            }
            if not survivors:
                out = {
                    "valid?": False,
                    "op": ops[op_id].to_dict(),
                    "configs": [
                        {"model": repr(m), "pending": sorted(linset)}
                        for m, linset in list(configs)[:10]
                    ],
                }
                out["final-paths"] = _final_paths(
                    configs, parents, ops, ops[op_id]
                )
                out["failed-op-id"] = op_id
                out["ops"] = [o.to_dict() for o in ops]
                out["open-ops"] = sorted(open_ops)
                return out
            configs = survivors
            parents = {}  # re-root paths at the new common prefix
            open_ops.discard(op_id)
        elif kind == INFO:
            # stays open forever; nothing to do
            pass

    return {
        "valid?": True,
        "configs": [
            {"model": repr(m), "pending": sorted(linset)}
            for m, linset in list(configs)[:10]
        ],
        "op-count": len(ops),
    }
