"""Clock-skew-over-time plot.

Any op carrying a ``clock-offsets`` map (node -> offset seconds, emitted
by the clock nemesis) contributes points; offsets render as step series
per node.  (reference: jepsen/src/jepsen/checker/clock.clj)
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from .. import store as store_mod
from ..history import History
from . import Checker, perf, svg


def history_to_datasets(history: History) -> Dict[Any, List[Tuple[float, float]]]:
    """node -> [t, offset] series, extended to the end of the history.
    (reference: clock.clj:13-34)"""
    series: Dict[Any, List[Tuple[float, float]]] = {}
    if not len(history):
        return series
    final_t = perf.nanos_to_secs(history[-1].time)
    for op in history:
        offsets = op.get("clock-offsets")
        if not offsets:
            continue
        t = perf.nanos_to_secs(op.time)
        for node, offset in offsets.items():
            series.setdefault(node, []).append((t, offset))
    for pts in series.values():
        pts.append((final_t, pts[-1][1]))
    return series


def short_node_names(nodes: List[str]) -> List[str]:
    """Strip a common domain suffix from node names.
    (reference: clock.clj:36-45)"""
    if not nodes:
        return []
    split = [str(n).split(".") for n in nodes]
    # find the longest common proper suffix
    min_len = min(len(s) for s in split)
    common = 0
    while common < min_len - 1 and len({tuple(s[len(s) - common - 1 :]) for s in split}) == 1:
        common += 1
    return [".".join(s[: len(s) - common]) for s in split]


def plot(test: dict, history: History, opts: dict) -> dict:
    """(reference: clock.clj:47-80)"""
    datasets = history_to_datasets(history)
    if datasets:
        nodes = sorted(datasets.keys(), key=str)
        names = short_node_names([str(n) for n in nodes])
        series = [
            svg.Series(name, datasets[node], mode="steps")
            for node, name in zip(nodes, names)
        ]
        svg.render(
            store_mod.path_(
                test, *opts.get("subdirectory", []), "clock-skew.svg"
            ),
            series,
            title=f"{test.get('name', 'test')} clock skew",
            ylabel="Skew (s)",
            regions=perf.nemesis_regions(test, history),
        )
    return {"valid?": True}


class _ClockPlot(Checker):
    def check(self, test, history, opts=None):
        if not test.get("store?", True):
            return {"valid?": True}
        return plot(test, history, opts or {})


def plotter() -> Checker:
    return _ClockPlot()
