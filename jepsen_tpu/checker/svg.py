"""A tiny self-contained SVG plot engine.

The reference shells out to gnuplot for latency/rate/clock plots
(jepsen/src/jepsen/checker/perf.clj via gnuplot.core); we render SVG
directly — no external binaries, works anywhere the framework runs.

A plot is: axes with ticks, optional log-y, shaded background regions
(nemesis activity), and a list of series, each drawn as points, a line,
or steps, with a legend.
"""

from __future__ import annotations

import math
import os
from typing import Any, List, Optional, Sequence, Tuple

#: Default categorical palette (dark-on-light friendly).
PALETTE = [
    "#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
    "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf",
]

#: Colors for op completion types (timeline + latency plots share these).
TYPE_COLORS = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA"}

WIDTH, HEIGHT = 900, 400
MARGIN_L, MARGIN_R, MARGIN_T, MARGIN_B = 70, 160, 40, 50


class Series:
    def __init__(
        self,
        title: str,
        points: Sequence[Tuple[float, float]],
        color: Optional[str] = None,
        mode: str = "line",  # line | points | steps
    ):
        self.title = title
        self.points = [(float(x), float(y)) for x, y in points]
        self.color = color
        self.mode = mode


class Region:
    """A shaded vertical band [x0, x1] with an optional label."""

    def __init__(self, x0: float, x1: float, color: str = "#000000", opacity: float = 0.07, label: str = ""):
        self.x0 = x0
        self.x1 = x1
        self.color = color
        self.opacity = opacity
        self.label = label


def _nice_ticks(lo: float, hi: float, n: int = 6) -> List[float]:
    if hi <= lo:
        hi = lo + 1.0
    span = hi - lo
    step = 10 ** math.floor(math.log10(span / max(n, 1)))
    for mult in (1, 2, 5, 10):
        if span / (step * mult) <= n:
            step *= mult
            break
    first = math.ceil(lo / step) * step
    ticks = []
    t = first
    while t <= hi + 1e-12:
        ticks.append(round(t, 10))
        t += step
    return ticks


def _log_ticks(lo: float, hi: float) -> List[float]:
    lo = max(lo, 1e-12)
    ticks = []
    e = math.floor(math.log10(lo))
    while 10**e <= hi * 1.0001:
        if 10**e >= lo * 0.9999:
            ticks.append(10**e)
        e += 1
    return ticks or [lo, hi]


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    a = abs(v)
    if a >= 1e6 or a < 1e-3:
        return f"{v:.0e}"
    if a >= 100:
        return f"{v:.0f}"
    if a >= 1:
        return f"{v:g}"
    return f"{v:g}"


def _esc(s: Any) -> str:
    return (
        str(s)
        .replace("&", "&amp;")
        .replace("<", "&lt;")
        .replace(">", "&gt;")
        .replace('"', "&quot;")
    )


def render(
    path: str,
    series: List[Series],
    title: str = "",
    xlabel: str = "Time (s)",
    ylabel: str = "",
    regions: Optional[List[Region]] = None,
    log_y: bool = False,
    x_range: Optional[Tuple[float, float]] = None,
    y_range: Optional[Tuple[float, float]] = None,
) -> Optional[str]:
    """Render series to an SVG file.  Returns the path, or None if there
    was nothing to draw."""
    pts = [p for s in series for p in s.points]
    if not pts:
        return None

    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    x_lo, x_hi = x_range or (min(xs + [0.0]), max(xs) or 1.0)
    if log_y:
        pos = [y for y in ys if y > 0]
        y_lo, y_hi = y_range or (min(pos) if pos else 1e-3, max(pos) if pos else 1.0)
        y_lo = max(y_lo, 1e-12)
    else:
        y_lo, y_hi = y_range or (min(ys + [0.0]), max(ys) or 1.0)
    if x_hi <= x_lo:
        x_hi = x_lo + 1.0
    if y_hi <= y_lo:
        y_hi = y_lo + 1.0

    plot_w = WIDTH - MARGIN_L - MARGIN_R
    plot_h = HEIGHT - MARGIN_T - MARGIN_B

    def sx(x: float) -> float:
        return MARGIN_L + (x - x_lo) / (x_hi - x_lo) * plot_w

    def sy(y: float) -> float:
        if log_y:
            y = max(y, y_lo)
            f = (math.log10(y) - math.log10(y_lo)) / (
                math.log10(y_hi) - math.log10(y_lo)
            )
        else:
            f = (y - y_lo) / (y_hi - y_lo)
        return MARGIN_T + plot_h * (1 - f)

    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{WIDTH}" height="{HEIGHT}" '
        f'viewBox="0 0 {WIDTH} {HEIGHT}" font-family="sans-serif" font-size="11">',
        f'<rect width="{WIDTH}" height="{HEIGHT}" fill="white"/>',
    ]

    # shaded regions (clipped to the plot area)
    for rg in regions or []:
        rx0, rx1 = max(x_lo, rg.x0), min(x_hi, rg.x1)
        if rx1 <= rx0:
            continue
        out.append(
            f'<rect x="{sx(rx0):.1f}" y="{MARGIN_T}" '
            f'width="{max(sx(rx1) - sx(rx0), 1):.1f}" height="{plot_h}" '
            f'fill="{rg.color}" opacity="{rg.opacity}"/>'
        )
        if rg.label:
            out.append(
                f'<text x="{sx(rx0) + 2:.1f}" y="{MARGIN_T + 10}" '
                f'font-size="9" fill="#555">{_esc(rg.label)}</text>'
            )

    # axes + ticks
    out.append(
        f'<rect x="{MARGIN_L}" y="{MARGIN_T}" width="{plot_w}" height="{plot_h}" '
        f'fill="none" stroke="#999"/>'
    )
    for t in _nice_ticks(x_lo, x_hi):
        if t < x_lo or t > x_hi:
            continue
        out.append(
            f'<line x1="{sx(t):.1f}" y1="{MARGIN_T + plot_h}" x2="{sx(t):.1f}" '
            f'y2="{MARGIN_T + plot_h + 4}" stroke="#999"/>'
            f'<text x="{sx(t):.1f}" y="{MARGIN_T + plot_h + 16}" '
            f'text-anchor="middle">{_fmt(t)}</text>'
        )
    yticks = _log_ticks(y_lo, y_hi) if log_y else _nice_ticks(y_lo, y_hi)
    for t in yticks:
        if t < y_lo * 0.999 or t > y_hi * 1.001:
            continue
        out.append(
            f'<line x1="{MARGIN_L - 4}" y1="{sy(t):.1f}" x2="{MARGIN_L}" '
            f'y2="{sy(t):.1f}" stroke="#999"/>'
            f'<line x1="{MARGIN_L}" y1="{sy(t):.1f}" x2="{MARGIN_L + plot_w}" '
            f'y2="{sy(t):.1f}" stroke="#eee"/>'
            f'<text x="{MARGIN_L - 7}" y="{sy(t) + 3:.1f}" '
            f'text-anchor="end">{_fmt(t)}</text>'
        )

    # series
    for i, s in enumerate(series):
        color = s.color or PALETTE[i % len(PALETTE)]
        if s.mode == "points":
            for x, y in s.points:
                out.append(
                    f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="1.6" '
                    f'fill="{color}" fill-opacity="0.65"/>'
                )
        else:
            coords = []
            prev = None
            for x, y in sorted(s.points):
                if s.mode == "steps" and prev is not None:
                    coords.append(f"{sx(x):.1f},{sy(prev):.1f}")
                coords.append(f"{sx(x):.1f},{sy(y):.1f}")
                prev = y
            out.append(
                f'<polyline points="{" ".join(coords)}" fill="none" '
                f'stroke="{color}" stroke-width="1.3"/>'
            )

    # labels + legend
    if title:
        out.append(
            f'<text x="{WIDTH / 2:.0f}" y="20" text-anchor="middle" '
            f'font-size="14">{_esc(title)}</text>'
        )
    out.append(
        f'<text x="{MARGIN_L + plot_w / 2:.0f}" y="{HEIGHT - 8}" '
        f'text-anchor="middle">{_esc(xlabel)}</text>'
    )
    if ylabel:
        out.append(
            f'<text x="14" y="{MARGIN_T + plot_h / 2:.0f}" text-anchor="middle" '
            f'transform="rotate(-90 14 {MARGIN_T + plot_h / 2:.0f})">'
            f"{_esc(ylabel)}</text>"
        )
    ly = MARGIN_T + 6
    for i, s in enumerate(series):
        color = s.color or PALETTE[i % len(PALETTE)]
        out.append(
            f'<rect x="{WIDTH - MARGIN_R + 10}" y="{ly - 8}" width="10" '
            f'height="10" fill="{color}"/>'
            f'<text x="{WIDTH - MARGIN_R + 24}" y="{ly + 1}">{_esc(s.title)}</text>'
        )
        ly += 16

    out.append("</svg>")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(out))
    return path
