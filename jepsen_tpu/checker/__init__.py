"""Checker protocol and the built-in O(n) checkers.

A checker validates a history against expectations, returning a dict with at
least ``{"valid?": True | False | "unknown"}``.  Checkers are pure functions
of (test, history, opts) and are the seam behind which the TPU analysis
plane plugs in (see jepsen_tpu.checker.linearizable).

Reference semantics: jepsen/src/jepsen/checker.clj —
merge-valid/valid-priorities (:29-50), Checker protocol (:52-67),
check-safe (:74-85), compose (:87-99), concurrency-limit (:101-116),
unbridled-optimism (:118), unhandled-exceptions (:124-151), stats
(:153-183), queue (:218-238), set (:240-291), set-full (:294-592),
total-queue (:594-687), unique-ids (:689-734), counter (:737-795),
log-file-pattern (:839-881).
"""

from __future__ import annotations

import os
import re
import threading
import traceback
from collections import Counter
from typing import Any, Callable, Dict, Optional

from ..history import History, Op, INVOKE, OK, FAIL, INFO
from ..util import integer_interval_set_str, real_pmap

UNKNOWN = "unknown"

#: Larger numbers dominate when merging composed verdicts.
#: (reference: checker.clj:29-34)
VALID_PRIORITIES = {True: 0, False: 1, UNKNOWN: 0.5}


def merge_valid(valids) -> Any:
    """Merge validity values; the highest-priority one wins.
    (reference: checker.clj:36-50)"""
    out = True
    for v in valids:
        if v not in VALID_PRIORITIES:
            raise ValueError(f"{v!r} is not a known valid? value")
        if VALID_PRIORITIES[v] > VALID_PRIORITIES[out]:
            out = v
    return out


class Checker:
    """Verify a history. Returns {"valid?": ...} plus details.

    opts keys include "subdirectory" — a directory within the test's store
    directory for output files.
    """

    def check(self, test: dict, history: History, opts: Optional[dict] = None) -> dict:
        raise NotImplementedError

    def __call__(self, test, history, opts=None) -> dict:
        return self.check(test, history, opts or {})


class FnChecker(Checker):
    """Adapt a plain function (test, history, opts) -> dict."""

    def __init__(self, fn: Callable[[dict, History, dict], dict], name: str = "fn"):
        self.fn = fn
        self.name = name

    def check(self, test, history, opts=None):
        return self.fn(test, history, opts or {})


def checker(fn: Callable) -> Checker:
    return FnChecker(fn, getattr(fn, "__name__", "fn"))


def checker_name(chk: Checker) -> str:
    """A human-readable name for spans/telemetry: the FnChecker's
    function name, else the class name without its leading underscore."""
    name = getattr(chk, "name", None)
    if name:
        return str(name)
    return type(chk).__name__.lstrip("_")


def check_safe(chk: Checker, test: dict, history: History, opts: Optional[dict] = None) -> dict:
    """Like check, but returns {"valid?": "unknown", "error": ...} on crash.
    (reference: checker.clj:74-85)

    The universal checker seam (core.analyze and compose both funnel
    through here), so each checker gets its own obs span."""
    from .. import obs

    try:
        with obs.span(
            f"checker/{checker_name(chk)}", cat="checker"
        ) as sp:
            result = chk.check(test, history, opts or {})
            if isinstance(result, dict):
                sp.set("valid", result.get("valid?"))
        return result if result is not None else {"valid?": True}
    except Exception:
        return {"valid?": UNKNOWN, "error": traceback.format_exc()}


class _Noop(Checker):
    def check(self, test, history, opts=None):
        return None


def noop() -> Checker:
    """(reference: checker.clj:68-72)"""
    return _Noop()


class _Compose(Checker):
    def __init__(self, checker_map: Dict[str, Checker]):
        self.checker_map = dict(checker_map)

    def check(self, test, history, opts=None):
        items = list(self.checker_map.items())
        results = real_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, history, opts)), items
        )
        out = dict(results)
        out["valid?"] = merge_valid(
            r.get("valid?") for r in out.values() if r is not None
        )
        return out


def compose(checker_map: Dict[str, Checker]) -> Checker:
    """Run a map of named checkers (in parallel); merged verdict.
    (reference: checker.clj:87-99)"""
    return _Compose(checker_map)


class _ConcurrencyLimit(Checker):
    def __init__(self, limit: int, chk: Checker):
        self.sem = threading.Semaphore(limit)
        self.chk = chk

    def check(self, test, history, opts=None):
        with self.sem:
            return self.chk.check(test, history, opts)


def concurrency_limit(limit: int, chk: Checker) -> Checker:
    """Bound concurrent executions of a memory-hungry checker.
    (reference: checker.clj:101-116)"""
    return _ConcurrencyLimit(limit, chk)


class _UnbridledOptimism(Checker):
    def check(self, test, history, opts=None):
        return {"valid?": True}


def unbridled_optimism() -> Checker:
    """Everything is awesome.  (reference: checker.clj:118-122)"""
    return _UnbridledOptimism()


class _UnhandledExceptions(Checker):
    def check(self, test, history, opts=None):
        infos = [
            op
            for op in history
            if op.type == INFO and op.extra.get("exception") is not None
        ]
        groups: Dict[Any, list] = {}
        for op in infos:
            groups.setdefault(op.extra.get("exception_class"), []).append(op)
        exes = [
            {
                "class": cls,
                "count": len(ops),
                "example": ops[0].to_dict(),
            }
            for cls, ops in sorted(
                groups.items(), key=lambda kv: len(kv[1]), reverse=True
            )
        ]
        out: dict = {"valid?": True}
        if exes:
            out["exceptions"] = exes
        return out


def unhandled_exceptions() -> Checker:
    """Frequency table of unhandled exceptions attached to :info ops.
    (reference: checker.clj:124-151)"""
    return _UnhandledExceptions()


def _stats_for(completions) -> dict:
    ok = sum(1 for op in completions if op.type == OK)
    fail = sum(1 for op in completions if op.type == FAIL)
    info = sum(1 for op in completions if op.type == INFO)
    return {
        "valid?": ok > 0,
        "count": ok + fail + info,
        "ok-count": ok,
        "fail-count": fail,
        "info-count": info,
    }


class _Stats(Checker):
    def check(self, test, history, opts=None):
        completions = [
            op
            for op in history
            if op.type != INVOKE and isinstance(op.process, int)
        ]
        by_f: Dict[Any, list] = {}
        for op in completions:
            by_f.setdefault(op.f, []).append(op)
        groups = {f: _stats_for(ops) for f, ops in sorted(by_f.items(), key=lambda kv: str(kv[0]))}
        out = _stats_for(completions)
        out["by-f"] = groups
        out["valid?"] = merge_valid(g["valid?"] for g in groups.values()) if groups else True
        return out


def stats() -> Checker:
    """Success/failure rates overall and by :f; valid iff every :f has some
    ok op.  (reference: checker.clj:153-183)"""
    return _Stats()


class _Queue(Checker):
    def __init__(self, model):
        self.model = model

    def check(self, test, history, opts=None):
        state = self.model
        for op in history:
            if op.f == "enqueue" and op.type == INVOKE:
                state = state.step(op)
            elif op.f == "dequeue" and op.type == OK:
                state = state.step(op)
            if state.is_inconsistent:
                return {"valid?": False, "error": state.msg}
        return {"valid?": True, "final-queue": repr(state)}


def queue(model) -> Checker:
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded, only OK dequeues succeeded, and reduce the model over
    that. O(n).  (reference: checker.clj:218-238)"""
    return _Queue(model)


class _SetChecker(Checker):
    def check(self, test, history, opts=None):
        attempts = {
            op.value for op in history if op.type == INVOKE and op.f == "add"
        }
        adds = {op.value for op in history if op.type == OK and op.f == "add"}
        final_read = None
        for op in history:
            if op.type == OK and op.f == "read":
                final_read = op.value
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "Set was never read"}
        final_read = set(final_read)
        ok = final_read & attempts
        unexpected = final_read - attempts
        lost = adds - final_read
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> Checker:
    """Adds followed by a final read: every acknowledged add must be
    present; nothing unattempted may appear.  (reference: checker.clj:240-291)"""
    return _SetChecker()


# ---------------------------------------------------------------------------
# set-full: per-element visibility state machine
# ---------------------------------------------------------------------------


class _SetFullElement:
    """Tracks one element's timeline.  (reference: checker.clj:294-407)"""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known: Optional[Op] = None       # completion proving existence
        self.last_present: Optional[Op] = None  # latest read invoke observing it
        self.last_absent: Optional[Op] = None   # latest read invoke missing it

    def on_add_ok(self, op: Op):
        if self.known is None:
            self.known = op

    def on_read_present(self, inv: Op, op: Op):
        if self.known is None:
            self.known = op
        if self.last_present is None or self.last_present.index < inv.index:
            self.last_present = inv

    def on_read_absent(self, inv: Op, op: Op):
        if self.last_absent is None or self.last_absent.index < inv.index:
            self.last_absent = inv

    def results(self) -> dict:
        idx = lambda op, d=-1: op.index if op is not None else d  # noqa: E731
        stable = bool(
            self.last_present is not None
            and idx(self.last_absent) < idx(self.last_present)
        )
        lost = bool(
            self.known is not None
            and self.last_absent is not None
            and idx(self.last_present) < idx(self.last_absent)
            and self.known.index < self.last_absent.index
        )
        known_time = self.known.time if self.known else None
        stable_time = (
            (self.last_absent.time + 1 if self.last_absent else 0) if stable else None
        )
        lost_time = (
            (self.last_present.time + 1 if self.last_present else 0) if lost else None
        )
        ns_to_ms = lambda ns: int(ns // 1_000_000)  # noqa: E731
        return {
            "element": self.element,
            "outcome": "stable" if stable else ("lost" if lost else "never-read"),
            "stable-latency": (
                ns_to_ms(max(0, stable_time - known_time)) if stable else None
            ),
            "lost-latency": (
                ns_to_ms(max(0, lost_time - known_time)) if lost else None
            ),
        }


def frequency_distribution(points, values) -> Optional[dict]:
    """Percentiles (0–1) of a collection.  (reference: checker.clj:409-420)"""
    ordered = sorted(values)
    if not ordered:
        return None
    n = len(ordered)
    return {p: ordered[min(n - 1, int(n * p))] for p in points}


class _SetFull(Checker):
    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None):
        elements: Dict[Any, _SetFullElement] = {}
        pending_reads: Dict[Any, Op] = {}
        dups: Dict[Any, int] = {}
        for op in history:
            if not isinstance(op.process, int):
                continue
            if op.f == "add":
                if op.type == INVOKE:
                    if op.value not in elements:
                        elements[op.value] = _SetFullElement(op.value)
                elif op.type == OK:
                    el = elements.get(op.value)
                    if el is not None:
                        el.on_add_ok(op)
            elif op.f == "read":
                if op.type == INVOKE:
                    pending_reads[op.process] = op
                elif op.type == FAIL:
                    pending_reads.pop(op.process, None)
                elif op.type == INFO:
                    pass
                elif op.type == OK:
                    inv = pending_reads.pop(op.process, op)
                    values = op.value or []
                    counts = Counter(values)
                    for v, c in counts.items():
                        if c > 1:
                            dups[v] = max(dups.get(v, 0), c)
                    vset = set(values)
                    for element, state in elements.items():
                        if element in vset:
                            state.on_read_present(inv, op)
                        else:
                            state.on_read_absent(inv, op)
        rs = [
            elements[k].results()
            for k in sorted(elements.keys(), key=lambda x: (str(type(x)), x))
        ]
        outcomes: Dict[str, list] = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"] and r["stable-latency"] > 0]
        worst_stale = sorted(stale, key=lambda r: r["stable-latency"], reverse=True)[:8]
        if lost:
            valid: Any = False
        elif not stable:
            valid = UNKNOWN
        elif self.linearizable and stale:
            valid = False
        else:
            valid = True
        if dups:
            valid = merge_valid([valid, False])
        out = {
            "valid?": valid,
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted(r["element"] for r in lost),
            "never-read-count": len(never_read),
            "never-read": sorted(r["element"] for r in never_read),
            "stale-count": len(stale),
            "stale": sorted(r["element"] for r in stale),
            "worst-stale": worst_stale,
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(), key=lambda kv: str(kv[0]))),
        }
        points = [0, 0.5, 0.95, 0.99, 1]
        sl = frequency_distribution(points, [r["stable-latency"] for r in rs if r["stable-latency"] is not None])
        ll = frequency_distribution(points, [r["lost-latency"] for r in rs if r["lost-latency"] is not None])
        if sl:
            out["stable-latencies"] = sl
        if ll:
            out["lost-latencies"] = ll
        return out


def set_full(linearizable: bool = False) -> Checker:
    """Rigorous set analysis: per-element stable/lost/never-read outcomes
    with stability latencies; stale reads fail linearizable sets.
    (reference: checker.clj:461-592)"""
    return _SetFull(linearizable=linearizable)


# ---------------------------------------------------------------------------
# queues, ids, counters
# ---------------------------------------------------------------------------


def expand_queue_drain_ops(history: History) -> History:
    """Expand ok :drain ops (value = list of elements) into dequeue
    invoke/ok pairs.  (reference: checker.clj:594-626)"""
    out = History()
    for op in history:
        if op.f != "drain":
            out.append(op)
        elif op.type in (INVOKE, FAIL):
            continue
        elif op.type == OK:
            for element in op.value or []:
                out.append(op.copy(type=INVOKE, f="dequeue", value=None))
                out.append(op.copy(type=OK, f="dequeue", value=element))
        else:
            raise ValueError(f"Not sure how to handle a crashed drain operation: {op!r}")
    return out


class _TotalQueue(Checker):
    def check(self, test, history, opts=None):
        history = expand_queue_drain_ops(history)
        attempts = Counter(
            op.value for op in history if op.type == INVOKE and op.f == "enqueue"
        )
        enqueues = Counter(
            op.value for op in history if op.type == OK and op.f == "enqueue"
        )
        dequeues = Counter(
            op.value for op in history if op.type == OK and op.f == "dequeue"
        )
        ok = dequeues & attempts
        unexpected = Counter(
            {v: c for v, c in dequeues.items() if v not in attempts}
        )
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


def total_queue() -> Checker:
    """What goes in must come out (assuming the history drains the queue).
    O(n).  (reference: checker.clj:628-687)"""
    return _TotalQueue()


class _UniqueIds(Checker):
    def check(self, test, history, opts=None):
        attempted = sum(
            1 for op in history if op.type == INVOKE and op.f == "generate"
        )
        acks = [op.value for op in history if op.type == OK and op.f == "generate"]
        counts = Counter(acks)
        dups = {k: v for k, v in counts.items() if v > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": dict(
                sorted(dups.items(), key=lambda kv: kv[1], reverse=True)[:48]
            ),
            "range": rng,
        }


def unique_ids() -> Checker:
    """A unique-id generator must emit distinct values.
    (reference: checker.clj:689-734)"""
    return _UniqueIds()


class _CounterChecker(Checker):
    def check(self, test, history, opts=None):
        lower = 0
        upper = 0
        pending_reads: Dict[Any, list] = {}
        reads = []
        completed = history.complete().without_failures()
        for op in completed:
            if op.f == "read":
                if op.type == INVOKE:
                    pending_reads[op.process] = [lower, op.value]
                elif op.type == OK:
                    r = pending_reads.pop(op.process, None)
                    if r is not None:
                        # observed value was propagated onto the invoke by
                        # complete(); prefer the completion's value
                        reads.append([r[0], op.value, upper])
            elif op.f == "add":
                if op.type == INVOKE:
                    if op.value is None or op.value < 0:
                        raise ValueError(f"counter add must be non-negative: {op!r}")
                    upper += op.value
                elif op.type == OK:
                    lower += op.value
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return {"valid?": not errors, "reads": reads, "errors": errors}


def counter() -> Checker:
    """Monotonically increasing counter: each read must fall within
    [sum of ok adds at invoke, sum of attempted adds at completion].
    (reference: checker.clj:737-795)"""
    return _CounterChecker()


#: after one race arm answers, how long a wedged straggler may hold up
#: an indefinite ("unknown") verdict before we settle for it
RACE_LOSER_WAIT_S = 60.0


class _Linearizable(Checker):
    def _oracle_analysis(self, history) -> dict:
        """One call: linear.analysis(witness=True) runs the fast
        interned-int search (per-key decomposed where the model
        factors) for every history and re-searches ONLY a failing
        history's failing partition with parent pointers, keeping the
        definite False even if the witness pass blows the shared
        budget — so valid verdicts ride the fast path, failures carry
        final-paths/ops, and total wall time stays bounded by
        oracle_budget_s."""
        from . import linear

        return linear.analysis(
            self.model, history, pure_fs=self.pure_fs, witness=True,
            budget_s=self.oracle_budget_s,
        )

    def _race(self, test, history) -> dict:
        """Run the device kernel and the CPU oracle concurrently; the
        first DEFINITE (non-unknown) verdict wins.  Both arms tag their
        result so the report says who won.  Arms run on daemon threads:
        a hung accelerator backend must never pin process exit (the
        atexit join in concurrent.futures would), and the loser's
        result is simply dropped."""
        import queue
        import threading

        from . import linear
        from ..ops import wgl

        def kernel():
            if not wgl.supported(self.model):
                return None
            from . import locks_direct

            d = locks_direct.analysis(self.model, history)
            if d is not None:
                # models a direct polynomial checker covers decide in
                # microseconds; a True verdict IS this arm's answer
                # (nothing to witness), while a False CONCEDES so the
                # oracle arm's witnessed report (final-paths for the
                # failure renderer) wins the race — encoding a device
                # batch either way would waste the arm
                if d["valid?"] is True:
                    d.setdefault("engine", "direct")
                    return d
                return None
            # oracle_fallback=False: unencodable/overflowing histories
            # come back "unknown" (conceding the race) instead of
            # silently duplicating the oracle arm's exponential search
            out = wgl.analysis(self.model, history, oracle_fallback=False)
            out.setdefault("engine", "tpu")
            return out

        def oracle():
            out = self._oracle_analysis(history)
            out["engine"] = "oracle"
            return out

        results: "queue.Queue" = queue.Queue()

        def run(arm):
            try:
                results.put(("ok", arm()))
            except Exception as e:  # noqa: BLE001 — other arm decides
                results.put(("err", e))

        n_arms = 2
        for arm in (kernel, oracle):
            threading.Thread(target=run, args=(arm,), daemon=True).start()
        last = None
        for i in range(n_arms):
            try:
                # the first answer may wait as long as it needs (with
                # both arms hung there is nothing better to return);
                # once one arm has spoken, a wedged straggler only gets
                # a bounded grace period before we settle for the
                # indefinite result we have
                status, out = results.get(
                    timeout=None if i == 0 else RACE_LOSER_WAIT_S
                )
            except queue.Empty:
                break
            if status == "err":
                last = {"valid?": "unknown", "error": repr(out)}
                continue
            if out is not None and out.get("valid?") != "unknown":
                return out
            last = out or last
        return last or {"valid?": "unknown", "error": "no arm finished"}

    def __init__(
        self,
        model,
        algorithm: str = "auto",
        pure_fs=("read",),
        oracle_budget_s=None,
    ):
        if model is None:
            raise ValueError(
                "The linearizable checker requires a model. It received None."
            )
        self.model = model
        self.algorithm = algorithm
        self.pure_fs = tuple(pure_fs)
        #: wall-time bound for the exponential CPU oracle search; past
        #: it the verdict is an honest "unknown" (check-safe semantics,
        #: checker.clj:74-85) instead of an analysis that hangs for
        #: hours on one poisoned key (the knossos blowup class)
        self.oracle_budget_s = oracle_budget_s

    def check(self, test, history, opts=None):
        from . import linear

        algorithm = self.algorithm
        if algorithm == "auto":
            from ..ops import wgl

            # wgl.check_batch itself guards against a wedged accelerator
            # tunnel (subprocess probe + CPU pin), covering every
            # dispatch path including explicit algorithm="tpu"
            if wgl.supported(self.model):
                # JEPSEN_TPU_SERVICE opts the fleet into the resident
                # checker daemon (jepsen_tpu.serve) without touching a
                # single test — the service path falls back to the
                # in-process engine when no daemon is reachable, so
                # "auto" stays safe to resolve this way
                from ..serve import client as serve_client

                algorithm = (
                    "service"
                    if serve_client.service_mode() != "off"
                    else "tpu"
                )
            else:
                algorithm = "oracle"
        if algorithm == "race":
            # knossos-style competition: device kernel and CPU oracle run
            # concurrently, first definite verdict wins (knossos.core
            # races its linear/wgl searches the same way; consumed by the
            # reference at checker.clj:199-203).  Worth it when histories
            # are small enough that jit compilation could lose to the
            # oracle, or models fall off the kernel's fast path.
            a = self._race(test, history)
        elif algorithm == "tpu":
            from ..ops import wgl
            from ..parallel import mesh as mesh_mod

            # routes through the pipelined engine (jepsen_tpu.engine):
            # test["engine-window"] (the CLI's --engine-window) bounds
            # its in-flight device dispatches; None takes the default.
            # An explicit test mesh (CLI --mesh / test["mesh"]) flows
            # through like the batched seam's; None lets the engine
            # auto-resolve the slice (doc/checker-engines.md
            # "Slice-native dispatch")
            a = wgl.analysis(
                self.model, history, oracle_budget_s=self.oracle_budget_s,
                window=(test or {}).get("engine-window"),
                mesh=mesh_mod.resolve_mesh(test or {}),
            )
        elif algorithm == "service":
            # the resident checker daemon (jepsen_tpu.serve) when one
            # is reachable, the in-process engine otherwise — the
            # serve.client seam does the fallback, so this branch can
            # never strand a verdict on a missing daemon.  Budgeted
            # searches stay in-process by construction (serve.client
            # refuses to ship oracle_budget_s — deadline semantics).
            from ..serve import client as serve_client

            a = serve_client.analysis(
                self.model, history,
                oracle_budget_s=self.oracle_budget_s,
                window=(test or {}).get("engine-window"),
            )
        else:
            a = self._oracle_analysis(history)
        # Failure witness: linear.svg with final configs/paths around the
        # non-linearizable op (reference: checker.clj:206-210, where
        # knossos.linear.report renders the same artifact).  Only when
        # the test has a real store identity — unit checks on bare test
        # maps should not litter the working directory.
        if (
            a.get("valid?") is False
            and test
            and test.get("name")
            and test.get("start-time")
        ):
            from .. import store as store_mod
            from . import linear_svg

            try:
                out = store_mod.path_(
                    test, *(opts or {}).get("subdirectory", []), "linear.svg"
                )
                if linear_svg.render_witness(
                    self.model, history, a, out, pure_fs=self.pure_fs,
                    budget_s=self.oracle_budget_s,
                ):
                    a["witness"] = out
            except Exception as e:  # noqa: BLE001 — never mask the verdict
                a["witness-error"] = repr(e)
        # Truncate potentially huge fields (reference: checker.clj:213-216)
        if "configs" in a:
            a["configs"] = a["configs"][:10]
        if "final-paths" in a:
            a["final-paths"] = a["final-paths"][:10]
        if "ops" in a:
            del a["ops"]  # witness-renderer context; huge on long tests
        return a


def linearizable(
    model,
    algorithm: str = "auto",
    pure_fs=("read",),
    oracle_budget_s=None,
) -> Checker:
    """Validate linearizability against a model.  algorithm: "auto"
    (TPU kernel when the model has one — via the resident checker
    service when ``JEPSEN_TPU_SERVICE`` opts in — else oracle), "tpu",
    "oracle", "service" (the jepsen_tpu.serve daemon, transparent
    in-process fallback; also exposed as ``serve.ServiceChecker``),
    or "race" (kernel vs oracle concurrently, first definite verdict
    wins — knossos's competition mode).  ``oracle_budget_s`` bounds the
    exponential CPU search's wall time; past it the verdict is an
    honest "unknown" (check-safe semantics, checker.clj:74-85) instead
    of an analysis hanging for hours on one poisoned key.
    (reference: checker.clj:185-216)"""
    return _Linearizable(model, algorithm, pure_fs, oracle_budget_s)


class _LogFilePattern(Checker):
    def __init__(self, pattern, filename: str):
        self.pattern = re.compile(pattern)
        self.filename = filename

    def check(self, test, history, opts=None):
        from .. import store as store_mod

        def search(node):
            path = store_mod.path(test, node, self.filename)
            if not os.path.exists(path):
                return []
            found = []
            with open(path, "r", errors="replace") as f:
                for line in f:
                    if self.pattern.search(line):
                        found.append({"node": node, "line": line.rstrip("\n")})
            return found

        matches = [
            m for ms in real_pmap(search, test.get("nodes", [])) for m in ms
        ]
        return {"valid?": not matches, "count": len(matches), "matches": matches}


def log_file_pattern(pattern, filename: str) -> Checker:
    """Search each node's downloaded log file for a pattern; matches fail
    the test.  (reference: checker.clj:839-881; uses Python re instead of
    shelling out to grep -P)"""
    return _LogFilePattern(pattern, filename)


# ---------------------------------------------------------------------------
# Graph checkers (SVG renderers; reference used gnuplot)
# ---------------------------------------------------------------------------


class _LatencyGraph(Checker):
    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        if not test.get("store?", True):
            return {"valid?": True}
        from . import perf as perf_mod

        o = {**self.opts, **(opts or {})}
        perf_mod.point_graph(test, history, o)
        perf_mod.quantiles_graph(test, history, o)
        return {"valid?": True}


def latency_graph(opts: Optional[dict] = None) -> Checker:
    """Plots latency raw + quantiles.  (reference: checker.clj:797-808)"""
    return _LatencyGraph(opts)


class _RateGraph(Checker):
    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        if not test.get("store?", True):
            return {"valid?": True}
        from . import perf as perf_mod

        perf_mod.rate_graph(test, history, {**self.opts, **(opts or {})})
        return {"valid?": True}


def rate_graph(opts: Optional[dict] = None) -> Checker:
    """Plots throughput over time.  (reference: checker.clj:810-820)"""
    return _RateGraph(opts)


def perf_checker(opts: Optional[dict] = None) -> Checker:
    """Composes latency + rate graphs.  (reference: checker.clj:822-829;
    named perf_checker because the submodule jepsen_tpu.checker.perf holds
    the plot functions)"""
    return compose(
        {"latency-graph": latency_graph(opts), "rate-graph": rate_graph(opts)}
    )


def clock_plot() -> Checker:
    """Plots clock offsets on all nodes.  (reference: checker.clj:831-837)"""
    from . import clock as clock_mod

    return clock_mod.plotter()
