"""Latency and throughput plots with nemesis-interval shading.

Native SVG renderings of the reference's gnuplot graphs
(jepsen/src/jepsen/checker/perf.clj: latencies->quantiles:63,
nemesis-regions:240, point-graph!:484, quantiles-graph!:513,
rate-graph!:559).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import store as store_mod
from ..history import History, OK, INVOKE
from ..util import history_latencies, nemesis_intervals
from . import svg

#: Standard latency quantiles.  (reference: perf.clj quantiles-graph!)
QUANTILES = (0.5, 0.95, 0.99, 1.0)


def nanos_to_secs(ns: int) -> float:
    return ns / 1e9


def nemesis_regions(test: dict, history: History) -> List[svg.Region]:
    """Shaded bands for nemesis activity intervals.
    (reference: perf.clj:240-283)"""
    plot = (test or {}).get("plot", {}) or {}
    specs = plot.get("nemeses") or [
        {"name": "nemesis", "start": ("start",), "stop": ("stop",)}
    ]
    regions = []
    end_time = nanos_to_secs(history[-1].time) if len(history) else 0.0
    palette = ["#bbbbbb", "#cc6666", "#6666cc", "#66aa66", "#aa66aa"]
    for i, spec in enumerate(specs):
        ivals = nemesis_intervals(
            history,
            fs_start=spec.get("start", ("start",)),
            fs_stop=spec.get("stop", ("stop",)),
        )
        color = spec.get("color") or palette[i % len(palette)]
        for start, stop in ivals:
            regions.append(
                svg.Region(
                    nanos_to_secs(start.time),
                    nanos_to_secs(stop.time) if stop is not None else end_time,
                    color=color,
                    opacity=0.15,
                    label=str(spec.get("name", "")),
                )
            )
    return regions


#: run-phase overlay shade (deliberately fainter than nemesis bands —
#: phases are context, faults are the story)
PHASE_COLOR = "#4477aa"


def phase_regions(test: dict, history: History) -> List[svg.Region]:
    """Shaded bands for run lifecycle phases (jepsen_tpu.obs spans of
    category "phase"), aligned with history time via the run anchor.
    Only phases intersecting the plotted axis [0, last-op-time] appear:
    setup/db phases straddling t=0 are clamped to it, and phases lying
    entirely after the history (save-history, analyze — which hasn't
    even finished when these graphs render) can't be drawn on this
    axis at all; the full set lives in the exported trace.json."""
    from .. import obs

    intervals = obs.phase_intervals()
    if not intervals or not len(history):
        return []
    end_time = nanos_to_secs(history[-1].time)
    regions = []
    for name, x0, x1 in intervals:
        if x1 <= 0 or x0 >= end_time:
            continue  # outside the plotted axis entirely
        regions.append(
            svg.Region(
                max(x0, 0.0), min(x1, end_time),
                color=PHASE_COLOR, opacity=0.05, label=str(name),
            )
        )
    return regions


def graph_regions(test: dict, history: History) -> List[svg.Region]:
    """Nemesis bands + the obs phase overlay — what every perf graph
    shades behind its series."""
    return nemesis_regions(test, history) + phase_regions(test, history)


def latencies_to_quantiles(
    dt: float, qs: Sequence[float], points: List[Tuple[float, float]]
) -> Dict[float, List[Tuple[float, float]]]:
    """Partition [t, latency] points into dt-second windows and take each
    quantile per window.  (reference: perf.clj:63-90)"""
    if not points:
        return {q: [] for q in qs}
    buckets: Dict[int, List[float]] = {}
    for t, lat in points:
        buckets.setdefault(int(t // dt), []).append(lat)
    out: Dict[float, List[Tuple[float, float]]] = {q: [] for q in qs}
    for b in sorted(buckets):
        lats = sorted(buckets[b])
        mid_t = (b + 0.5) * dt
        for q in qs:
            idx = min(len(lats) - 1, int(math.ceil(q * len(lats))) - 1)
            out[q].append((mid_t, lats[max(idx, 0)]))
    return out


def invokes_by_f(history: History) -> Dict[Any, List]:
    by_f: Dict[Any, List] = {}
    for op in history_latencies(history):
        if op.type != INVOKE or not isinstance(op.process, int):
            continue
        by_f.setdefault(op.f, []).append(op)
    return by_f


def point_graph(test: dict, history: History, opts: dict) -> Optional[str]:
    """Raw latency scatter, one series per (f, completion type).
    (reference: perf.clj:484-511)"""
    by_f = invokes_by_f(history)
    series = []
    for f, ops in sorted(by_f.items(), key=lambda kv: str(kv[0])):
        by_type: Dict[str, List[Tuple[float, float]]] = {}
        for op in ops:
            lat = op.get("latency")
            if lat is None:
                continue
            by_type.setdefault(op.get("completion_type", "info"), []).append(
                (nanos_to_secs(op.time), max(lat / 1e6, 1e-3))
            )
        for typ, pts in sorted(by_type.items()):
            series.append(
                svg.Series(
                    f"{f} {typ}",
                    pts,
                    color=svg.TYPE_COLORS.get(typ),
                    mode="points",
                )
            )
    return svg.render(
        store_mod.path_(
            test, *opts.get("subdirectory", []), "latency-raw.svg"
        ),
        series,
        title=f"{test.get('name', 'test')} latency (raw)",
        ylabel="Latency (ms)",
        log_y=True,
        regions=graph_regions(test, history),
    )


def quantiles_graph(test: dict, history: History, opts: dict) -> Optional[str]:
    """Latency quantiles over time, one series per (f, quantile).
    (reference: perf.clj:513-557)"""
    by_f = invokes_by_f(history)
    dt = opts.get("dt", 10.0)
    series = []
    for f, ops in sorted(by_f.items(), key=lambda kv: str(kv[0])):
        pts = [
            (nanos_to_secs(op.time), max(op["latency"] / 1e6, 1e-3))
            for op in ops
            if op.get("latency") is not None
        ]
        for q, qpts in latencies_to_quantiles(dt, QUANTILES, pts).items():
            if qpts:
                series.append(svg.Series(f"{f} p{q}", qpts, mode="line"))
    return svg.render(
        store_mod.path_(
            test, *opts.get("subdirectory", []), "latency-quantiles.svg"
        ),
        series,
        title=f"{test.get('name', 'test')} latency (quantiles)",
        ylabel="Latency (ms)",
        log_y=True,
        regions=graph_regions(test, history),
    )


def rate_graph(test: dict, history: History, opts: dict) -> Optional[str]:
    """Throughput (ops/sec in dt windows) per (f, completion type).
    (reference: perf.clj:559-599)"""
    dt = opts.get("dt", 10.0)
    counts: Dict[Tuple[Any, str], Dict[int, int]] = {}
    for op in history:
        if op.type == INVOKE or not isinstance(op.process, int):
            continue
        key = (op.f, op.type)
        counts.setdefault(key, {}).setdefault(int(nanos_to_secs(op.time) // dt), 0)
        counts[key][int(nanos_to_secs(op.time) // dt)] += 1
    series = []
    for (f, typ), buckets in sorted(counts.items(), key=lambda kv: str(kv[0])):
        pts = [((b + 0.5) * dt, c / dt) for b, c in sorted(buckets.items())]
        series.append(
            svg.Series(f"{f} {typ}", pts, color=svg.TYPE_COLORS.get(typ), mode="line")
        )
    return svg.render(
        store_mod.path_(test, *opts.get("subdirectory", []), "rate.svg"),
        series,
        title=f"{test.get('name', 'test')} rate",
        ylabel="Throughput (hz)",
        regions=graph_regions(test, history),
    )


def scatter_plot(
    test: dict,
    series_map: Dict[Any, List[Tuple[float, float]]],
    path_components: List[Any],
    title: str = "",
    ylabel: str = "",
    history: Optional[History] = None,
) -> Optional[str]:
    """General named-series scatter (used by e.g. the bank plotter)."""
    series = [
        svg.Series(str(k), pts, mode="points")
        for k, pts in sorted(series_map.items(), key=lambda kv: str(kv[0]))
    ]
    regions = (
        graph_regions(test, history) if history is not None and len(history) else []
    )
    return svg.render(
        store_mod.path_(test, *path_components),
        series,
        title=title,
        ylabel=ylabel,
        regions=regions,
    )
