"""HTML gantt timeline of operations per process.

(reference: jepsen/src/jepsen/checker/timeline.clj — op-limit 10000:12-14,
timescale 1e6 ns/px:23, pairs:37, html:180)
"""

from __future__ import annotations

import html as html_mod
import os
from typing import Any, Dict, List, Optional, Tuple

from .. import store as store_mod
from ..history import History, INVOKE, OK, FAIL, INFO
from . import Checker

#: Maximum operations to render.  (reference: timeline.clj:12-14)
OP_LIMIT = 10_000

TIMESCALE = 1e6  # nanoseconds per pixel (reference: timeline.clj:23)
COL_WIDTH = 100  # pixels
GUTTER_WIDTH = 106
HEIGHT = 16

STYLESHEET = """\
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.2); overflow: hidden; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
"""


def pairs(history: History) -> List[Tuple]:
    """[invoke, completion] / [op] pairs in completion order.
    (reference: timeline.clj:37-58)"""
    invocations: Dict[Any, Any] = {}
    out: List[Tuple] = []
    for op in history:
        if op.type == INVOKE:
            invocations[op.process] = op
        elif op.type == INFO and op.process not in invocations:
            out.append((op,))  # unmatched info (e.g. nemesis)
        else:
            inv = invocations.pop(op.process, None)
            if inv is not None:
                out.append((inv, op))
            else:
                out.append((op,))
    # still-open invocations render as half-pairs
    for inv in invocations.values():
        out.append((inv,))
    return out


def process_index(history: History) -> Dict[Any, int]:
    """Process -> render column, in order of first appearance."""
    index: Dict[Any, int] = {}
    for op in history:
        if op.process not in index:
            index[op.process] = len(index)
    return index


def _title(op, comp=None) -> str:
    lines = [f"{op.process} {op.f} {op.value!r}"]
    if comp is not None:
        lines.append(f"-> {comp.type} {comp.value!r}")
        if comp.error:
            lines.append(f"error: {comp.error}")
    return "\n".join(lines)


def pair_div(pair: Tuple, pindex: Dict[Any, int], t_end: int) -> str:
    op = pair[0]
    comp = pair[1] if len(pair) > 1 else None
    final = comp or op
    t0 = op.time
    t1 = comp.time if comp is not None else t_end
    left = GUTTER_WIDTH + pindex.get(op.process, 0) * (COL_WIDTH + 10)
    top = t0 / TIMESCALE
    height = max((t1 - t0) / TIMESCALE, HEIGHT)
    cls = final.type if final.type in (OK, FAIL, INFO) else "invoke"
    label = f"{op.f} {op.value!r}" if op.value is not None else f"{op.f}"
    return (
        f'<div class="op {cls}" id="op-{op.index}" '
        f'style="left:{left}px; top:{top:.0f}px; width:{COL_WIDTH}px; '
        f'height:{height:.0f}px" '
        f'title="{html_mod.escape(_title(op, comp))}">'
        f"{html_mod.escape(label)}</div>"
    )


class _TimelineHtml(Checker):
    def check(self, test, history, opts=None):
        opts = opts or {}
        if not test.get("store?", True):
            return {"valid?": True}
        ps = pairs(history)
        total_pairs = len(ps)
        truncated = total_pairs > OP_LIMIT
        ps = ps[:OP_LIMIT]
        pindex = process_index(history)
        t_end = history[-1].time if len(history) else 0
        key = opts.get("history-key")
        title = f"{test.get('name', 'test')}" + (
            f" key {key}" if key is not None else ""
        )
        body = [f"<h1>{html_mod.escape(title)}</h1>"]
        if truncated:
            body.append(
                f'<div class="truncation-warning">Showing only {OP_LIMIT} '
                f"of {total_pairs} operations in this history.</div>"
            )
        # column headers: process names
        for p, i in pindex.items():
            left = GUTTER_WIDTH + i * (COL_WIDTH + 10)
            body.append(
                f'<div style="position:absolute; left:{left}px; top:40px; '
                f'font-weight:bold">{html_mod.escape(str(p))}</div>'
            )
        body.append(
            '<div class="ops" style="top:60px; position:relative">'
            + "\n".join(pair_div(p, pindex, t_end) for p in ps)
            + "</div>"
        )
        doc = (
            "<html><head><style>"
            + STYLESHEET
            + "</style></head><body>"
            + "\n".join(body)
            + "</body></html>"
        )
        path = store_mod.path_(
            test, *opts.get("subdirectory", []), "timeline.html"
        )
        with open(path, "w") as f:
            f.write(doc)
        return {"valid?": True}


def html() -> Checker:
    """(reference: timeline.clj:180-209)"""
    return _TimelineHtml()
