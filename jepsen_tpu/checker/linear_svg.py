"""Failure-witness rendering for the linearizable checker.

When an analysis comes back invalid, render ``linear.svg``: a timeline
of the operations concurrent with the failure, plus every surviving
configuration's linearization path (state → op → state …) and the
reason the completing op could not be linearized from it.  This is the
role knossos.linear.report/render-analysis! plays for the reference
(jepsen/src/jepsen/checker.clj:206-210 writes it to
``<store>/linear.svg`` whenever the linearizable checker fails).

The layout is two stacked panels:

- **timeline**: one row per process, a bar per op spanning its
  invoke→complete events (index-compressed time), the failing op in red,
  still-open (info) ops ragged on the right.
- **paths**: one lane per final config — the chain of model states and
  linearized pending ops since the last completed op, ending in a red
  annotation explaining why stepping the failing op from that state is
  inconsistent.
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional

from ..history import History
from ..models import Model

FONT = "font-family='Helvetica,Arial,sans-serif'"
BAR_H = 22
ROW_GAP = 10
CHAR_W = 7.2


def _esc(s: Any) -> str:
    return html.escape(str(s), quote=True)


def _op_label(op: dict) -> str:
    v = op.get("value")
    return f"{op.get('f')} {v}" if v is not None else str(op.get("f"))


def _text(x, y, s, size=12, fill="#222", anchor="start", weight="normal"):
    return (
        f"<text x='{x:.1f}' y='{y:.1f}' font-size='{size}' fill='{fill}' "
        f"text-anchor='{anchor}' font-weight='{weight}' {FONT}>{_esc(s)}</text>"
    )


def render_witness(
    model: Model,
    history: History,
    result: dict,
    path: str,
    pure_fs=(),
    budget_s=None,
) -> Optional[str]:
    """Render the failure witness for an invalid analysis to ``path``.
    Reruns the CPU oracle with witness tracking when ``result`` lacks
    path data (the TPU kernel reports verdicts only) — under
    ``budget_s`` when given, so a kernel-found failure on an
    exponential-class history can't hang witness rendering.  Returns
    the path, or None when the analysis isn't a definite failure (or
    the budgeted rerun came back unknown)."""
    from . import linear

    if result.get("valid?") is not False:
        return None
    if "final-paths" not in result or "ops" not in result:
        result = linear.analysis(
            model, history, pure_fs=pure_fs, witness=True,
            budget_s=budget_s,
        )
        if result.get("valid?") is not False:
            return None  # oracle disagrees or budget blown — no witness

    ops: List[dict] = result["ops"]
    failed_id: int = result["failed-op-id"]
    paths: List[dict] = result.get("final-paths", [])[:10]
    open_ids = set(result.get("open-ops", []))

    # ---- timeline panel: ops overlapping the failing op -------------
    failed = ops[failed_id]
    # ops relevant to the shown paths come first; then a bounded sample
    # of the remaining open ops (a long run can hold thousands of
    # crashed-open ops — an uncapped window renders an unusably wide SVG)
    path_ids = {
        s["op-id"]
        for p in paths
        for s in p["steps"]
        if isinstance(s.get("op-id"), int)
    } | {i for p in paths for i in p.get("pending", []) if isinstance(i, int)}
    window_ids = {failed_id} | path_ids
    open_extra = sorted(open_ids - window_ids)
    n_hidden = max(0, len(open_extra) - 12)
    window_ids = sorted(window_ids | set(open_extra[:12]))
    # index-compressed x axis over the window's op order
    window_ids = [i for i in window_ids if 0 <= i < len(ops)][:24]
    procs = sorted({ops[i].get("process") for i in window_ids}, key=str)
    xw = max(160, 120 * len(window_ids))
    label_w = 70
    width = label_w + xw + 260
    y = 48

    body = [_text(12, 24, "Linearizability failure witness", 16, weight="bold")]
    body.append(
        _text(
            12,
            40,
            f"op {_op_label(failed)} (process {failed.get('process')}) "
            "could not be linearized",
            12,
            fill="#b91c1c",
        )
    )

    xs = {op_id: label_w + 20 + k * 120 for k, op_id in enumerate(window_ids)}
    rows = {p: y + i * (BAR_H + ROW_GAP) for i, p in enumerate(procs)}
    for op_id in window_ids:
        op = ops[op_id]
        ry = rows[op.get("process")]
        x0 = xs[op_id]
        is_failed = op_id == failed_id
        is_open = op_id in open_ids and not is_failed
        w = 108
        fill = "#fecaca" if is_failed else ("#fde68a" if is_open else "#bfdbfe")
        stroke = "#b91c1c" if is_failed else "#64748b"
        dash = " stroke-dasharray='4,3'" if is_open else ""
        body.append(
            f"<rect x='{x0}' y='{ry}' width='{w}' height='{BAR_H}' rx='4' "
            f"fill='{fill}' stroke='{stroke}'{dash}/>"
        )
        body.append(
            _text(x0 + w / 2, ry + BAR_H - 7, _op_label(op), 11, anchor="middle")
        )
    for p, ry in rows.items():
        body.append(_text(8, ry + BAR_H - 6, f"p{p}", 12, fill="#475569"))
    if n_hidden:
        body.append(
            _text(
                label_w + 20,
                y + len(procs) * (BAR_H + ROW_GAP) + 8,
                f"(+{n_hidden} more open ops not shown)",
                11,
                fill="#94a3b8",
            )
        )

    # ---- paths panel ------------------------------------------------
    py = y + len(procs) * (BAR_H + ROW_GAP) + 30
    body.append(
        _text(12, py, f"final configs ({len(paths)} shown)", 13, weight="bold")
    )
    py += 10
    max_x = width
    for p in paths:
        py += BAR_H + ROW_GAP
        x = 16
        chain = [("state", p["init"])]
        for s in p["steps"]:
            chain.append(("op", _op_label(s["op"])))
            chain.append(("state", s["model"]))
        for kind, label in chain:
            w = max(40, len(str(label)) * CHAR_W + 14)
            if kind == "state":
                body.append(
                    f"<rect x='{x}' y='{py - BAR_H + 6}' width='{w:.0f}' "
                    f"height='{BAR_H}' rx='10' fill='#e2e8f0' stroke='#64748b'/>"
                )
            else:
                body.append(
                    f"<rect x='{x}' y='{py - BAR_H + 6}' width='{w:.0f}' "
                    f"height='{BAR_H}' fill='#dbeafe' stroke='#2563eb'/>"
                )
            body.append(
                _text(x + w / 2, py, label, 11, anchor="middle")
            )
            x += w + 26
            body.append(
                f"<line x1='{x - 24:.0f}' y1='{py - 5}' x2='{x - 4:.0f}' "
                f"y2='{py - 5}' stroke='#94a3b8' marker-end='url(#arr)'/>"
            )
        # the failing step, annotated with the model's complaint
        # (computed by the oracle from the real config state)
        why = p.get("why", "inconsistent")
        lbl = f"✗ {_op_label(failed)}: {why}"
        w = len(lbl) * CHAR_W + 14
        body.append(
            f"<rect x='{x}' y='{py - BAR_H + 6}' width='{w:.0f}' "
            f"height='{BAR_H}' fill='#fee2e2' stroke='#b91c1c' "
            "stroke-dasharray='4,3'/>"
        )
        body.append(_text(x + w / 2, py, lbl, 11, "#b91c1c", anchor="middle"))
        max_x = max(max_x, x + w + 20)

    height = py + BAR_H + 20
    svg = (
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{max_x:.0f}' "
        f"height='{height:.0f}' viewBox='0 0 {max_x:.0f} {height:.0f}'>"
        "<defs><marker id='arr' markerWidth='8' markerHeight='8' refX='7' "
        "refY='3' orient='auto'><path d='M0,0 L7,3 L0,6 z' fill='#94a3b8'/>"
        "</marker></defs>"
        f"<rect width='100%' height='100%' fill='white'/>{''.join(body)}</svg>"
    )
    with open(path, "w") as f:
        f.write(svg)
    return path


