"""Polynomial-time direct linearizability checker for plain mutex
histories.

General linearizability checking is NP-complete (the knossos search the
reference consumes at jepsen/src/jepsen/checker.clj:199-203 is
exponential), but a SINGLE plain lock is special: the model state is one
bit, every acquire is interchangeable with every other acquire (the
``models.Mutex`` step ignores the process), and likewise every release —
so a history is linearizable iff the completed ops admit an ALTERNATING
placement (acquire, release, acquire, …, seeded by the initial state)
with each op placed inside its invocation→completion window.  That is a
two-type interval scheduling problem, decidable greedily:

- Sweep ``linear.prepare``'s event list in order (the windows are
  defined by event positions, so the sweep IS the timeline).
- Lazy placement: an op is placed at the latest legal moment — its own
  completion event.  Placing later never hurts (windows constrain
  order, not absolute time), so any feasible schedule can be deformed
  into this one.
- When the lock state blocks the op being placed (acquire while locked
  / release while free), place ONE pending helper of the opposite kind
  first — the one with the EARLIEST deadline (completion index;
  crashed/info ops carry deadline ∞ and are thereby used only when no
  mandatory helper exists).  The standard EDF exchange argument
  applies because same-kind ops are interchangeable: if some feasible
  schedule uses a later-deadline helper here, swapping it with the
  EDF choice (placed elsewhere ≤ its earlier deadline) stays feasible.
- Info/crashed ops (knossos semantics: concurrent forever, may
  linearize once at any point after invocation, or never) sit in the
  pending pools indefinitely and are consumed only as helpers.

O(n log n) per history versus the exponential config search — this is
the engine ``wgl.check_batch`` routes mutex batches to (the on-chip
measurement that motivated oracle routing: frontier_results_tpu.json,
2026-07-31), now decided without any search at all.  Owner-aware and
reentrant locks are NOT handled here (their holds are not
interchangeable, which breaks the exchange argument); ``analysis``
returns None for them and the caller falls back to the generic oracle.
"""

from __future__ import annotations

import heapq
from typing import Optional

from ..history import History, INVOKE, OK
from .. import models as m
from . import linear


def _check_events(events: list, ops: list, locked0: bool) -> dict:
    """The greedy sweep over ``linear.prepare`` output.  Returns the
    analysis dict; ``{"valid?": None}`` is never produced — callers get
    a definite True/False (this checker has no budget to blow)."""
    # completion event index per op id = the op's placement deadline;
    # ops with no OK event (info/crashed) never expire
    inf = float("inf")
    deadline = [inf] * len(ops)
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            deadline[op_id] = idx

    pend_acq: list = []  # (deadline, op_id) heaps; lazy deletion
    pend_rel: list = []
    placed = [False] * len(ops)
    locked = locked0

    def pop_helper(heap) -> Optional[int]:
        while heap:
            _, cand = heapq.heappop(heap)
            if not placed[cand]:
                return cand
        return None

    for kind, op_id in events:
        f = ops[op_id].f
        if f == "acquire":
            is_acq = True
        elif f == "release":
            is_acq = False
        else:
            # not a plain-lock history after all — let the caller's
            # generic search handle it
            return {"valid?": None}
        if kind == INVOKE:
            heapq.heappush(
                pend_acq if is_acq else pend_rel,
                (deadline[op_id], op_id),
            )
        elif kind == OK:
            if placed[op_id]:
                continue  # consumed earlier as a helper
            if is_acq and locked:
                helper = pop_helper(pend_rel)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot acquire a held lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = False
            elif not is_acq and not locked:
                helper = pop_helper(pend_acq)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot release a free lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = True
            placed[op_id] = True
            locked = is_acq
        # INFO events carry no obligation: the op stays pending forever

    return {
        "valid?": True,
        "op-count": len(ops),
        "algorithm": "direct-mutex",
    }


def analysis(model, history: History) -> Optional[dict]:
    """Direct-decision analysis for plain-mutex histories, result-dict
    compatible with ``linear.analysis``.  Returns None when the model
    is not exactly ``models.Mutex`` (owner-aware and reentrant locks
    break the interchangeability the greedy rests on) or the history
    contains non-lock ops — callers then use the generic search."""
    if type(model) is not m.Mutex:
        return None
    events, ops = linear.prepare(history)
    out = _check_events(events, ops, bool(model.locked))
    if out["valid?"] is None:
        return None
    return out
