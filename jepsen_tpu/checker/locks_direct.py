"""Polynomial-time direct linearizability checker for plain mutex
histories.

General linearizability checking is NP-complete (the knossos search the
reference consumes at jepsen/src/jepsen/checker.clj:199-203 is
exponential), but a SINGLE plain lock is special: the model state is one
bit, every acquire is interchangeable with every other acquire (the
``models.Mutex`` step ignores the process), and likewise every release —
so a history is linearizable iff the completed ops admit an ALTERNATING
placement (acquire, release, acquire, …, seeded by the initial state)
with each op placed inside its invocation→completion window.  That is a
two-type interval scheduling problem, decidable greedily:

- Sweep ``linear.prepare``'s event list in order (the windows are
  defined by event positions, so the sweep IS the timeline).
- Lazy placement: an op is placed at the latest legal moment — its own
  completion event.  Placing later never hurts (windows constrain
  order, not absolute time), so any feasible schedule can be deformed
  into this one.
- When the lock state blocks the op being placed (acquire while locked
  / release while free), place ONE pending helper of the opposite kind
  first — the one with the EARLIEST deadline (completion index;
  crashed/info ops carry deadline ∞ and are thereby used only when no
  mandatory helper exists).  The standard EDF exchange argument
  applies because same-kind ops are interchangeable: if some feasible
  schedule uses a later-deadline helper here, swapping it with the
  EDF choice (placed elsewhere ≤ its earlier deadline) stays feasible.
- Info/crashed ops (knossos semantics: concurrent forever, may
  linearize once at any point after invocation, or never) sit in the
  pending pools indefinitely and are consumed only as helpers.

O(n log n) per history versus the exponential config search — this is
the engine ``wgl.check_batch`` routes mutex batches to (the on-chip
measurement that motivated oracle routing: frontier_results_tpu.json,
2026-07-31), now decided without any search at all.

Owner-aware locks lose that interchangeability but gain a stronger
structure instead: a client's ops are sequential in real time, so its
holds form statically-segmented spans each mandatorily occupying a
real-time core, and validity reduces to pairwise-disjoint cores plus
client-local count bounds (``_spans_check_events`` — the reentrant
argument; the non-reentrant owner-aware mutex is the same argument at
hold bound 1).  Histories whose crash structure leaves a span without
a fixed core return None and fall back to the generic search.
"""

from __future__ import annotations

import heapq
from typing import Optional

from ..history import History, INVOKE, OK
from .. import models as m
from . import linear


def _check_events(events: list, ops: list, locked0: bool) -> dict:
    """The greedy sweep over ``linear.prepare`` output.  Returns the
    analysis dict; ``{"valid?": None}`` is never produced — callers get
    a definite True/False (this checker has no budget to blow)."""
    # completion event index per op id = the op's placement deadline;
    # ops with no OK event (info/crashed) never expire
    inf = float("inf")
    deadline = [inf] * len(ops)
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            deadline[op_id] = idx

    pend_acq: list = []  # (deadline, op_id) heaps; lazy deletion
    pend_rel: list = []
    placed = [False] * len(ops)
    locked = locked0

    def pop_helper(heap) -> Optional[int]:
        while heap:
            _, cand = heapq.heappop(heap)
            if not placed[cand]:
                return cand
        return None

    for kind, op_id in events:
        f = ops[op_id].f
        if f == "acquire":
            is_acq = True
        elif f == "release":
            is_acq = False
        else:
            # not a plain-lock history after all — let the caller's
            # generic search handle it
            return {"valid?": None}
        if kind == INVOKE:
            heapq.heappush(
                pend_acq if is_acq else pend_rel,
                (deadline[op_id], op_id),
            )
        elif kind == OK:
            if placed[op_id]:
                continue  # consumed earlier as a helper
            if is_acq and locked:
                helper = pop_helper(pend_rel)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot acquire a held lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = False
            elif not is_acq and not locked:
                helper = pop_helper(pend_acq)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot release a free lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = True
            placed[op_id] = True
            locked = is_acq
        # INFO events carry no obligation: the op stays pending forever

    return {
        "valid?": True,
        "op-count": len(ops),
        "algorithm": "direct-mutex",
    }


def _index_and_group(events: list, ops: list):
    """Shared preamble for the owner-family and semaphore arguments:
    build completion/invocation indices, group op ids per client, and
    apply the sequentiality gate (a crashed op followed by more ops
    from the same client makes that client's structure point-flexible,
    so every fixed-core/extremal argument must hand off).  Returns
    (comp_idx, inv_idx, by_client) or None — None means 'fall back to
    the generic search'."""
    from ..models.locks import _client as _owner_client

    inf = float("inf")
    comp_idx = {}
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            comp_idx[op_id] = idx
    inv_idx = {}
    by_client: dict = {}
    for idx, (kind, op_id) in enumerate(events):
        if kind != INVOKE:
            continue
        inv_idx[op_id] = idx
        c = _owner_client(ops[op_id])
        if c is None:
            return None
        by_client.setdefault(c, []).append(op_id)
    for ids in by_client.values():
        for a, b in zip(ids, ids[1:]):
            if comp_idx.get(a, inf) > inv_idx[b]:
                return None
    return comp_idx, inv_idx, by_client


def _spans_check_events(
    events: list, ops: list, max_count: int, algo: str, model=None
) -> dict:
    """Direct decision for owner-aware lock histories (reentrant up to
    ``max_count`` holds; ``max_count=1`` IS the non-reentrant
    owner-aware mutex).

    Owner matching kills the plain-mutex interchangeability, but it
    buys something stronger: a client's lock ops are sequential in
    real time (one client = one logical thread), so its hold-count
    trajectory is FIXED and holds group into statically-segmented
    maximal nonzero-count SPANS — a span runs from the acquire that
    takes the count 0→1 (ok'd at event index ``ao``) to the release
    that returns it to 0 (invoked at ``ri``).  In-span validity is
    purely client-local: the count must never exceed ``max_count``,
    and a completed release at count 0 is unsatisfiable.  Across
    clients, a span mandatorily occupies the core [ao, ri] — its
    first acquire linearizes before ``ao``, its last release after
    ``ri``, and the count never reaches 0 in between — so two
    overlapping cores mean two owners at once: invalid.  Conversely,
    disjoint cores order the spans, and consecutive spans can always
    pick points (release just after its invocation, acquire just
    before its ok): VALID ⇔ pairwise-disjoint span cores.

    Crashed ops keep knossos semantics where a fixed core still
    exists: a span whose last release is info keeps its core (we may
    CHOOSE to linearize the release; with more holds outstanding the
    span stays open forever whether it peels or not, so nothing is
    ambiguous); a span never closed holds forever — core [ao, ∞); a
    trailing crashed acquire or unmatched crashed release is optional
    and never needs placing.  A crashed op followed by more ops from
    the same client makes that client's spans point-flexible (no
    fixed core), so the sequentiality gate returns
    ``{"valid?": None}`` and the caller falls back to the generic
    search: the direct path only ever decides shapes its argument
    covers."""
    inf = float("inf")
    grouped = _index_and_group(events, ops)
    if grouped is None:
        return {"valid?": None}
    comp_idx, inv_idx, by_client = grouped

    cores = []  # (start, end, witness_op_id, span_op_ids)
    for c, ids in by_client.items():
        count = 0
        span_start = None  # acquire-ok index opening the current span
        span_ops: list = []
        for op_id in ids:
            op = ops[op_id]
            done = op_id in comp_idx
            if op.f == "acquire":
                if not done:
                    # trailing crashed acquire: optional, never placed
                    # (placing an acquire only ever adds constraints)
                    continue
                count += 1
                if count > max_count:
                    return {
                        "valid?": False,
                        "op": op.to_dict(),
                        "error": (
                            f"client {c!r} acquires while already "
                            f"holding (bound {max_count})"
                        ),
                        "algorithm": algo,
                    }
                if count == 1:
                    span_start = comp_idx[op_id]
                if model is not None:  # span ops feed the replay only
                    span_ops.append(op_id)
            elif op.f == "release":
                if count == 0:
                    if done:
                        return {
                            "valid?": False,
                            "op": op.to_dict(),
                            "error": (
                                f"client {c!r} cannot release: never held"
                            ),
                            "algorithm": algo,
                        }
                    continue  # crashed unmatched release: optional
                # a crashed release here is necessarily the client's
                # LAST op (sequentiality gate); linearizing it is OUR
                # choice, so count==1 lets the span close at its
                # invocation, and with more holds outstanding the span
                # stays open forever whether it peels or not
                count -= 1
                if model is not None:
                    span_ops.append(op_id)
                if count == 0:
                    cores.append(
                        (span_start, inv_idx[op_id], op_id, span_ops)
                    )
                    span_start = None
                    span_ops = []
            else:
                return {"valid?": None}
        if span_start is not None:
            # span never closed: held forever from its first acquire
            cores.append((span_start, inf, ids[-1], span_ops))

    cores.sort(key=lambda t: (t[0], t[1]))
    for (s1, e1, w1, _o1), (s2, e2, w2, _o2) in zip(cores, cores[1:]):
        if s2 <= e1:  # cores share an instant: two owners at once
            return {
                "valid?": False,
                "op": ops[w2].to_dict(),
                "error": "two clients' hold spans overlap",
                "algorithm": algo,
            }

    if model is not None:
        # Disjoint cores FORCE the linearization order (spans by core,
        # ops client-sequential within a span), so full semantic
        # validity — including the fenced models' monotonic-token
        # rules, which depend on the global observation order — is
        # decided by replaying the model's own step function over that
        # one order.  The optional-op choices above (skip trailing
        # crashed acquires and stray releases, linearize a span-closing
        # crashed release) are each maximally permissive, so an
        # inconsistent replay means no linearization exists.
        state = model
        for _s, _e, _w, span in cores:
            for op_id in span:
                state = state.step(ops[op_id])
                if state.is_inconsistent:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": str(getattr(state, "msg", "inconsistent")),
                        "algorithm": algo,
                    }
    return {"valid?": True, "op-count": len(ops), "algorithm": algo}


def _owner_check_events(events: list, ops: list) -> dict:
    """Non-reentrant owner-aware mutex = the spans argument at hold
    bound 1.  No replay: the count walk already decides these models
    exactly (differentially validated), so the fast path stays fast."""
    return _spans_check_events(events, ops, 1, "direct-owner-mutex")


def _reentrant_check_events(events: list, ops: list, max_count: int) -> dict:
    return _spans_check_events(
        events, ops, max_count, "direct-reentrant-mutex"
    )


def _fenced_check_events(events: list, ops: list, model) -> dict:
    """Fenced flavors: segmentation + disjoint cores as above, then the
    forced-order replay carries the monotonic-fence rules via the
    model's own step function."""
    return _spans_check_events(
        events, ops, 1, "direct-fenced-mutex", model
    )


def _reentrant_fenced_check_events(events: list, ops: list, model) -> dict:
    return _spans_check_events(
        events, ops, model.max_count, "direct-reentrant-fenced-mutex",
        model,
    )


def _permits_check_events(events: list, ops: list, n_permits: int) -> dict:
    """Direct decision for SEMAPHORE (acquired-permits) histories.

    No cores needed here — the exact condition falls out of an
    extremal placement.  Every completed acquire must linearize by its
    ok (index ``ao``) and every release may linearize as early as just
    after its invocation (``ri``), so

        H(t) = #{acquires: ao ≤ t} − #{releases placed: ri ≤ t}

    is a LOWER bound on permits outstanding at time t under ANY
    placement: H(t) > n_permits anywhere means no linearization
    exists.  Conversely, placing each acquire just before its ok and
    each release just after its invocation — in anchor order, which
    respects every client's sequential op order — realizes exactly H,
    so H ≤ n_permits everywhere (plus per-client release sanity, which
    is deterministic because a client's op order is fixed) IS
    linearizability.  Optional crashed ops resolve maximally
    permissively: trailing crashed acquires are never placed (placing
    only raises H), trailing crashed releases are placed whenever the
    client holds a permit (placing only lowers H and nothing of that
    client follows).  Crashed ops with successors fall back to the
    generic search, as in the lock checkers."""
    algo = "direct-acquired-permits"
    grouped = _index_and_group(events, ops)
    if grouped is None:
        return {"valid?": None}
    comp_idx, inv_idx, by_client = grouped

    deltas = []  # (anchor_index, +1/-1, op_id)
    for c, ids in by_client.items():
        held = 0
        for op_id in ids:
            op = ops[op_id]
            done = op_id in comp_idx
            if op.f == "acquire":
                if not done:
                    continue  # trailing crashed acquire: never placed
                held += 1
                deltas.append((comp_idx[op_id], 1, op_id))
            elif op.f == "release":
                if held == 0:
                    if done:
                        return {
                            "valid?": False,
                            "op": op.to_dict(),
                            "error": (
                                f"client {c!r} releases a permit it "
                                "does not hold"
                            ),
                            "algorithm": algo,
                        }
                    continue  # trailing crashed release, nothing held
                held -= 1
                deltas.append((inv_idx[op_id], -1, op_id))
            else:
                return {"valid?": None}

    deltas.sort()
    outstanding = 0
    for _idx, d, op_id in deltas:
        outstanding += d
        if outstanding > n_permits:
            return {
                "valid?": False,
                "op": ops[op_id].to_dict(),
                "error": (
                    f"more than {n_permits} permits necessarily "
                    "outstanding"
                ),
                "algorithm": algo,
            }
    return {"valid?": True, "op-count": len(ops), "algorithm": algo}


def _queue_check_events(events: list, ops: list, init_counts) -> dict:
    """Direct decision for UNORDERED-QUEUE histories.

    The model factors per value: enqueues never block and dequeue(v)
    only touches v's count, so constraints exist only WITHIN a value —
    each completed dequeue of v needs its own enqueue of v linearized
    before it (or an initial copy of v).  For a dequeue with deadline
    ``do`` (its ok index) and an enqueue invoked at ``ei``, points
    satisfying enq < deq exist iff ``ei < do``; distinct pairs share
    no resource beyond the one-enqueue-per-dequeue injection, so
    per-value validity is a bipartite matching under that threshold
    condition — and because later dequeues have later deadlines,
    greedy assignment in deadline order (consume ANY available
    enqueue) is exact.  Crashed enqueues are placeable helpers
    (window (ei, ∞)); crashed dequeues are optional and never consumed
    (placing one only spends an enqueue).  Unlike the lock checkers
    this needs no client-sequentiality gate: values, not clients, are
    the unit of interaction, so every history shape is decidable."""
    algo = "direct-unordered-queue"
    comp_idx = {}
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            comp_idx[op_id] = idx
    enq_by_value: dict = {}
    deqs = []  # (deadline, value, op_id) — completed dequeues only
    for idx, (kind, op_id) in enumerate(events):
        if kind != INVOKE:
            continue
        op = ops[op_id]
        if op.f == "enqueue":
            # completed or crashed: both may linearize (crashed ones at
            # any point after invocation — knossos semantics)
            enq_by_value.setdefault(op.value, []).append(idx)
        elif op.f == "dequeue":
            if op_id in comp_idx:
                deqs.append((comp_idx[op_id], op.value, op_id))
        else:
            return {"valid?": None}

    counts = dict(init_counts or {})
    deqs.sort()
    cursor: dict = {}  # per-value index of the next unconsumed enqueue
    for deadline, v, op_id in deqs:
        if v is None:
            return {
                "valid?": False,
                "op": ops[op_id].to_dict(),
                "error": "dequeue with unknown value",
                "algorithm": algo,
            }
        if counts.get(v, 0) > 0:
            counts[v] -= 1  # initial copies serve any dequeue
            continue
        pool = enq_by_value.get(v)
        # any enqueue invoked before this dequeue's deadline works,
        # and staying available for later (later-deadline) dequeues is
        # automatic — consume the earliest-invoked, via a cursor so
        # the matching stays O(n)
        i = cursor.get(v, 0)
        if pool and i < len(pool) and pool[i] < deadline:
            cursor[v] = i + 1
            continue
        return {
            "valid?": False,
            "op": ops[op_id].to_dict(),
            "error": f"dequeued {v!r} without a matching enqueue",
            "algorithm": algo,
        }
    return {"valid?": True, "op-count": len(ops), "algorithm": algo}


def dispatch_events(model, events: list, ops: list) -> Optional[dict]:
    """Events-level entry point — the ONE place that owns which models
    the direct arguments cover: plain ``models.Mutex`` via greedy
    alternation scheduling; the initially-free owner-aware family
    (``OwnerMutex``, ``ReentrantMutex``, ``FencedMutex``,
    ``ReentrantFencedMutex``) via disjoint span cores — with a
    forced-order model replay carrying the fenced flavors' token
    rules; initially-empty ``AcquiredPermits`` via the extremal
    mandatory-count argument.  Shared by :func:`analysis` and
    ``linear.analysis``'s hook so the two entries cannot diverge.
    Returns None for uncovered models or histories outside the
    structure a direct argument covers — callers then use the generic
    search."""
    from ..models.locks import FencedMutex, ReentrantFencedMutex

    if type(model) is m.Mutex:
        out = _check_events(events, ops, bool(model.locked))
    elif type(model) is m.OwnerMutex and model.owner is None:
        out = _owner_check_events(events, ops)
    elif (
        type(model) is m.ReentrantMutex
        and model.owner is None
        and model.count == 0
    ):
        out = _reentrant_check_events(events, ops, model.max_count)
    elif type(model) is FencedMutex and model.owner is None:
        out = _fenced_check_events(events, ops, model)
    elif (
        type(model) is ReentrantFencedMutex
        and model.owner is None
        and model.count == 0
    ):
        out = _reentrant_fenced_check_events(events, ops, model)
    elif type(model) is m.AcquiredPermits and not model.acquired:
        out = _permits_check_events(events, ops, model.n_permits)
    elif type(model) is m.UnorderedQueue:
        out = _queue_check_events(events, ops, dict(model.items))
    else:
        return None
    return None if out["valid?"] is None else out


def analysis(model, history: History) -> Optional[dict]:
    """History-level wrapper over :func:`dispatch_events`, result-dict
    compatible with ``linear.analysis``."""
    from ..models.locks import FencedMutex, ReentrantFencedMutex

    if type(model) not in (
        m.Mutex,
        m.OwnerMutex,
        m.ReentrantMutex,
        FencedMutex,
        ReentrantFencedMutex,
        m.AcquiredPermits,
        m.UnorderedQueue,
    ):
        return None  # skip prepare() for models no argument covers
    events, ops = linear.prepare(history)
    return dispatch_events(model, events, ops)
