"""Polynomial-time direct linearizability checker for plain mutex
histories.

General linearizability checking is NP-complete (the knossos search the
reference consumes at jepsen/src/jepsen/checker.clj:199-203 is
exponential), but a SINGLE plain lock is special: the model state is one
bit, every acquire is interchangeable with every other acquire (the
``models.Mutex`` step ignores the process), and likewise every release —
so a history is linearizable iff the completed ops admit an ALTERNATING
placement (acquire, release, acquire, …, seeded by the initial state)
with each op placed inside its invocation→completion window.  That is a
two-type interval scheduling problem, decidable greedily:

- Sweep ``linear.prepare``'s event list in order (the windows are
  defined by event positions, so the sweep IS the timeline).
- Lazy placement: an op is placed at the latest legal moment — its own
  completion event.  Placing later never hurts (windows constrain
  order, not absolute time), so any feasible schedule can be deformed
  into this one.
- When the lock state blocks the op being placed (acquire while locked
  / release while free), place ONE pending helper of the opposite kind
  first — the one with the EARLIEST deadline (completion index;
  crashed/info ops carry deadline ∞ and are thereby used only when no
  mandatory helper exists).  The standard EDF exchange argument
  applies because same-kind ops are interchangeable: if some feasible
  schedule uses a later-deadline helper here, swapping it with the
  EDF choice (placed elsewhere ≤ its earlier deadline) stays feasible.
- Info/crashed ops (knossos semantics: concurrent forever, may
  linearize once at any point after invocation, or never) sit in the
  pending pools indefinitely and are consumed only as helpers.

O(n log n) per history versus the exponential config search — this is
the engine ``wgl.check_batch`` routes mutex batches to (the on-chip
measurement that motivated oracle routing: frontier_results_tpu.json,
2026-07-31), now decided without any search at all.  Owner-aware and
reentrant locks are NOT handled here (their holds are not
interchangeable, which breaks the exchange argument); ``analysis``
returns None for them and the caller falls back to the generic oracle.
"""

from __future__ import annotations

import heapq
from typing import Optional

from ..history import History, INVOKE, OK
from .. import models as m
from . import linear


def _check_events(events: list, ops: list, locked0: bool) -> dict:
    """The greedy sweep over ``linear.prepare`` output.  Returns the
    analysis dict; ``{"valid?": None}`` is never produced — callers get
    a definite True/False (this checker has no budget to blow)."""
    # completion event index per op id = the op's placement deadline;
    # ops with no OK event (info/crashed) never expire
    inf = float("inf")
    deadline = [inf] * len(ops)
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            deadline[op_id] = idx

    pend_acq: list = []  # (deadline, op_id) heaps; lazy deletion
    pend_rel: list = []
    placed = [False] * len(ops)
    locked = locked0

    def pop_helper(heap) -> Optional[int]:
        while heap:
            _, cand = heapq.heappop(heap)
            if not placed[cand]:
                return cand
        return None

    for kind, op_id in events:
        f = ops[op_id].f
        if f == "acquire":
            is_acq = True
        elif f == "release":
            is_acq = False
        else:
            # not a plain-lock history after all — let the caller's
            # generic search handle it
            return {"valid?": None}
        if kind == INVOKE:
            heapq.heappush(
                pend_acq if is_acq else pend_rel,
                (deadline[op_id], op_id),
            )
        elif kind == OK:
            if placed[op_id]:
                continue  # consumed earlier as a helper
            if is_acq and locked:
                helper = pop_helper(pend_rel)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot acquire a held lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = False
            elif not is_acq and not locked:
                helper = pop_helper(pend_acq)
                if helper is None:
                    return {
                        "valid?": False,
                        "op": ops[op_id].to_dict(),
                        "error": "cannot release a free lock",
                        "algorithm": "direct-mutex",
                    }
                placed[helper] = True
                locked = True
            placed[op_id] = True
            locked = is_acq
        # INFO events carry no obligation: the op stays pending forever

    return {
        "valid?": True,
        "op-count": len(ops),
        "algorithm": "direct-mutex",
    }


def _owner_check_events(events: list, ops: list) -> dict:
    """Direct decision for OWNER-AWARE mutex histories.

    Owner matching kills the plain-mutex interchangeability, but it
    buys something stronger: each client's lock ops are sequential in
    real time (one client = one logical thread), so a completed hold —
    acquire ok'd at event index ``ao``, matching release invoked at
    ``ri`` — necessarily occupies the whole span [ao, ri]: the acquire
    linearizes before its ok, the release after its invocation, and
    both belong to the SAME hold because only the owner can release.
    Two holds whose cores overlap would both be held at once →
    invalid.  Conversely, if all cores are pairwise disjoint, ordering
    holds by core start gives ri_i < ao_j for consecutive holds, so
    points can always be chosen (release just after its invocation,
    acquire just before its ok): VALID ⇔ cores pairwise disjoint.

    Crashed ops keep knossos semantics where a FIXED core still
    exists: a hold whose release is info (may or may not linearize,
    any time ≥ ri) keeps core [ao, ri]; an acquire with no release at
    all holds forever — core [ao, ∞); a TRAILING crashed acquire is
    optional and never needs placing.  A crashed op followed by more
    ops from the same client makes that client's holds point-flexible
    (no fixed core — the crashed op may linearize arbitrarily late),
    so the sequentiality gate returns ``{"valid?": None}`` and the
    caller falls back to the generic search: the direct path only
    ever decides shapes its argument covers."""
    from ..models.locks import _client as _owner_client
    inf = float("inf")
    comp_idx = {}
    for idx, (kind, op_id) in enumerate(events):
        if kind == OK:
            comp_idx[op_id] = idx
    inv_idx = {}
    by_client: dict = {}
    for idx, (kind, op_id) in enumerate(events):
        if kind != INVOKE:
            continue
        inv_idx[op_id] = idx
        c = _owner_client(ops[op_id])
        if c is None:
            return {"valid?": None}
        by_client.setdefault(c, []).append(op_id)

    cores = []  # (start, end, witness_op_id)
    for c, ids in by_client.items():
        # clients must be internally sequential: op k+1 invoked after
        # op k completed (guaranteed when client==process; bail to the
        # generic search otherwise)
        for a, b in zip(ids, ids[1:]):
            if comp_idx.get(a, inf) > inv_idx[b]:
                return {"valid?": None}
        i = 0
        while i < len(ids):
            op = ops[ids[i]]
            acq_done = ids[i] in comp_idx
            if op.f != "acquire":
                if op.f != "release":
                    return {"valid?": None}
                # a release with no prior acquire by this client: no
                # linearization can ever satisfy the owner check
                if ids[i] in comp_idx:
                    return {
                        "valid?": False,
                        "op": op.to_dict(),
                        "error": (
                            f"client {c!r} cannot release: never held"
                        ),
                        "algorithm": "direct-owner-mutex",
                    }
                i += 1  # crashed unmatched release: optional, skip
                continue
            rel = ids[i + 1] if i + 1 < len(ids) else None
            if rel is not None and ops[rel].f != "release":
                rel = None  # acquire-acquire: second starts a new hold
            if rel is None:
                if acq_done:
                    # completed acquire, never released: holds forever
                    cores.append((comp_idx[ids[i]], inf, ids[i]))
                # crashed acquire with nothing after: optional, skip
                i += 1
                continue
            rel_done = rel in comp_idx
            if not acq_done:
                # a crashed acquire's hold is point-flexible (it may
                # linearize arbitrarily late), so it has no FIXED core
                # and the disjointness argument would over-reject; the
                # sequentiality gate above already sends these to the
                # generic search — bail defensively if one slips here
                return {"valid?": None}
            cores.append(
                (comp_idx[ids[i]], inv_idx[rel], rel if rel_done else ids[i])
            )
            i += 2

    cores.sort()
    for (s1, e1, w1), (s2, e2, w2) in zip(cores, cores[1:]):
        if s2 <= e1:  # cores share an instant: two holds at once
            return {
                "valid?": False,
                "op": ops[w2].to_dict(),
                "error": "two overlapping holds of a non-reentrant lock",
                "algorithm": "direct-owner-mutex",
            }
    return {
        "valid?": True,
        "op-count": len(ops),
        "algorithm": "direct-owner-mutex",
    }


def dispatch_events(model, events: list, ops: list) -> Optional[dict]:
    """Events-level entry point — the ONE place that owns which models
    the direct arguments cover: plain ``models.Mutex`` via greedy
    alternation scheduling, initially-free ``models.OwnerMutex`` via
    the disjoint-cores argument (the reentrant lock's nesting counts
    are not covered).  Shared by :func:`analysis` and
    ``linear.analysis``'s hook so the two entries cannot diverge.
    Returns None for uncovered models or histories outside the
    structure a direct argument covers — callers then use the generic
    search."""
    if type(model) is m.Mutex:
        out = _check_events(events, ops, bool(model.locked))
    elif type(model) is m.OwnerMutex and model.owner is None:
        out = _owner_check_events(events, ops)
    else:
        return None
    return None if out["valid?"] is None else out


def analysis(model, history: History) -> Optional[dict]:
    """History-level wrapper over :func:`dispatch_events`, result-dict
    compatible with ``linear.analysis``."""
    if type(model) not in (m.Mutex, m.OwnerMutex):
        return None  # skip prepare() for models no argument covers
    events, ops = linear.prepare(history)
    return dispatch_events(model, events, ops)
