"""Database lifecycle protocols (reference: jepsen/src/jepsen/db.clj).

- ``DB``: install/start (:11-19) and teardown a database on a node
- ``Process``: start!/kill! (:21-25)
- ``Pause``: pause!/resume! (:26-30)
- ``Primary``: primaries/setup-primary! (:31-39)
- ``LogFiles``: log-files (:40-48)
- ``cycle``: teardown → setup with 3 retries (:117-158)
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, List, Optional

from .util import real_pmap

log = logging.getLogger("jepsen_tpu.db")

SETUP_RETRIES = 3  # (reference: db.clj:117-119)


class DB:
    def setup(self, test: dict, node: Any) -> None:
        pass

    def teardown(self, test: dict, node: Any) -> None:
        pass


class Process:
    """Databases whose processes can be started and killed.
    (reference: db.clj:21-25)"""

    def start(self, test: dict, node: Any) -> None:
        raise NotImplementedError

    def kill(self, test: dict, node: Any) -> None:
        raise NotImplementedError


class Pause:
    """(reference: db.clj:26-30)"""

    def pause(self, test: dict, node: Any) -> None:
        raise NotImplementedError

    def resume(self, test: dict, node: Any) -> None:
        raise NotImplementedError


class Primary:
    """(reference: db.clj:31-39)"""

    def primaries(self, test: dict) -> List[Any]:
        raise NotImplementedError

    def setup_primary(self, test: dict, node: Any) -> None:
        pass


class LogFiles:
    """(reference: db.clj:40-48)"""

    def log_files(self, test: dict, node: Any) -> Iterable[str]:
        return ()


class NoopDB(DB):
    pass


def noop() -> DB:
    return NoopDB()


def cycle(test: dict, retries: int = SETUP_RETRIES) -> None:
    """Teardown then set up the DB on every node, retrying setup failures
    up to `retries` times.  Runs setup-primary on the first node for
    Primary DBs.  (reference: db.clj:121-158)"""
    from . import control

    db = test["db"]
    attempt = 0
    while True:
        attempt += 1
        try:
            real_pmap(
                lambda node: control.with_node(
                    node, lambda n=node: (db.teardown(test, n), db.setup(test, n))
                ),
                test["nodes"],
            )
            if isinstance(db, Primary):
                node = test["nodes"][0]
                control.with_node(node, lambda: db.setup_primary(test, node))
            return
        except Exception:
            if attempt >= retries:
                raise
            log.exception("DB setup failed; retrying (%d/%d)", attempt, retries)
            continue
