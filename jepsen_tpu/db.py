"""Database lifecycle protocols (reference: jepsen/src/jepsen/db.clj).

- ``DB``: install/start (:11-19) and teardown a database on a node
- ``Process``: start!/kill! (:21-25)
- ``Pause``: pause!/resume! (:26-30)
- ``Primary``: primaries/setup-primary! (:31-39)
- ``LogFiles``: log-files (:40-48)
- ``cycle``: teardown → setup with 3 retries (:117-158)
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Any, Dict, Iterable, List, Optional

from . import control
from .util import real_pmap

log = logging.getLogger("jepsen_tpu.db")

SETUP_RETRIES = 3  # (reference: db.clj:117-119)


class DB:
    def setup(self, test: dict, node: Any) -> None:
        pass

    def teardown(self, test: dict, node: Any) -> None:
        pass


class Process:
    """Databases whose processes can be started and killed.
    (reference: db.clj:21-25)"""

    def start(self, test: dict, node: Any) -> None:
        raise NotImplementedError

    def kill(self, test: dict, node: Any) -> None:
        raise NotImplementedError


class Pause:
    """(reference: db.clj:26-30)"""

    def pause(self, test: dict, node: Any) -> None:
        raise NotImplementedError

    def resume(self, test: dict, node: Any) -> None:
        raise NotImplementedError


class Primary:
    """(reference: db.clj:31-39)"""

    def primaries(self, test: dict) -> List[Any]:
        raise NotImplementedError

    def setup_primary(self, test: dict, node: Any) -> None:
        pass


class LogFiles:
    """(reference: db.clj:40-48)"""

    def log_files(self, test: dict, node: Any) -> Iterable[str]:
        return ()


class NoopDB(DB):
    pass


def noop() -> DB:
    return NoopDB()


def cycle(test: dict, retries: int = SETUP_RETRIES) -> None:
    """Teardown then set up the DB on every node, retrying setup failures
    up to `retries` times.  Runs setup-primary on the first node for
    Primary DBs.  (reference: db.clj:121-158)"""
    from . import control

    db = test["db"]
    attempt = 0
    while True:
        attempt += 1
        try:
            real_pmap(
                lambda node: control.with_node(
                    node, lambda n=node: (db.teardown(test, n), db.setup(test, n))
                ),
                test["nodes"],
            )
            if isinstance(db, Primary):
                node = test["nodes"][0]
                control.with_node(node, lambda: db.setup_primary(test, node))
            return
        except Exception:
            if attempt >= retries:
                raise
            log.exception("DB setup failed; retrying (%d/%d)", attempt, retries)
            continue


def control_ip(via: Any = None) -> str:
    """This (control) host's outbound IPv4 address — as seen on the
    route toward ``via`` (a DB node) when given, else the default route.
    Routing toward the node matters on multi-homed control hosts: the
    internet-facing address would match none of the client traffic.
    (reference: jepsen/src/jepsen/control/net.clj control-ip)"""
    target = "8.8.8.8"
    if via is not None:
        try:
            target = socket.gethostbyname(str(via))
        except OSError:
            pass
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((target, 80))  # no packets sent; just picks a route
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class TcpdumpDB(DB, LogFiles):
    """A DB that runs a tcpdump capture on every node from setup to
    teardown and yields the capture + daemon log as log files.  Compose
    it alongside the real DB to record a test's network traffic.

    Options (reference: db.clj:49-115 tcpdump):

    - ``ports``: capture only traffic on these ports
    - ``clients-only?``: capture only traffic to/from the control node
    - ``filter``: an extra pcap filter string, ANDed in
    """

    DIR = "/tmp/jepsen/tcpdump"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = dict(opts or {})
        self.logfile = f"{self.DIR}/log"
        self.capfile = f"{self.DIR}/tcpdump"
        self.pidfile = f"{self.DIR}/pid"

    def _filter_str(self, node: Any = None) -> str:
        parts = []
        ports = self.opts.get("ports") or ()
        if ports:
            # parenthesized: pcap 'and' binds tighter than 'or', so the
            # bare join would attach later filters to the last port only
            disj = " or ".join(f"port {p}" for p in ports)
            parts.append(f"({disj})" if len(ports) > 1 else disj)
        if self.opts.get("clients-only?"):
            # the control node's IP as the DB node sees it (reference:
            # control/net.clj control-ip — the address of the machine
            # running the harness)
            parts.append(f"host {control_ip(via=node)}")
        if self.opts.get("filter"):
            parts.append(self.opts["filter"])
        return " and ".join(parts)

    def setup(self, test: dict, node: Any) -> None:
        from .control import util as cu

        with control.su():
            control.execute("mkdir", "-p", self.DIR)
            cu.start_daemon(
                {"logfile": self.logfile, "pidfile": self.pidfile,
                 "chdir": self.DIR},
                "/usr/sbin/tcpdump",
                "-w", self.capfile,
                "-s", "65535",
                "-B", "16384",
                # unbuffered: killing tcpdump mid-buffer loses the most
                # interesting packets (the ones right before the failure)
                "-U",
                self._filter_str(node),
            )

    def teardown(self, test: dict, node: Any) -> None:
        from .control import util as cu

        with control.su():
            pid = control.execute("cat", self.pidfile, check=False)
            if pid:
                # SIGINT first so tcpdump flushes its capture cleanly
                control.execute("kill", "-s", "INT", pid, check=False)
                for _ in range(100):
                    # `ps -o pid= -p` prints nothing (no header) for a
                    # dead pid, unlike bare `ps -p`
                    if not control.execute(
                        "ps", "-o", "pid=", "-p", pid, check=False
                    ):
                        break
                    time.sleep(0.05)
            cu.stop_daemon(pidfile=self.pidfile, cmd="tcpdump")
            control.execute("rm", "-rf", self.DIR)

    def log_files(self, test: dict, node: Any) -> Iterable[str]:
        return [self.logfile, self.capfile]


def tcpdump(opts: Optional[dict] = None) -> TcpdumpDB:
    """(reference: db.clj:49-115)"""
    return TcpdumpDB(opts)
