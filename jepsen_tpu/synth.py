"""Synthetic history generation — for differential tests and benchmarks.

Simulates honest linearizable executions of a CAS register with real
concurrency (ops linearize at completion; crashes secretly apply or not),
plus an optional corruption pass that produces likely-invalid histories.
This is the batch feeder for BASELINE configs 1 and 3 (synthetic
CAS-register suites).
"""

from __future__ import annotations

import random
from typing import Optional

from .history import History, invoke_op, ok_op, fail_op, info_op


def generate_history(
    rng: random.Random,
    n_procs: int = 4,
    n_ops: int = 30,
    crash_p: float = 0.1,
    corrupt: bool = False,
    n_values: int = 5,
    replace_crashed: bool = False,
    op_weights=None,
) -> History:
    """One simulated concurrent CAS-register execution.

    Valid by construction when corrupt=False (every completed op
    linearizes at its completion point; crashed ops apply secretly with
    probability 1/2).  corrupt=True flips one completion value, usually
    (not always) making the history non-linearizable.

    replace_crashed=True mirrors the interpreter's process retirement
    (interpreter.clj:233-236): a crash frees the logical worker under a
    fresh process id, so open (crashed) ops accumulate beyond n_procs.
    op_weights biases the (read, write, cas) mix.
    """
    state = 0
    hist = []
    pending = {}
    idle = list(range(n_procs))
    next_pid = n_procs
    values = list(range(1, n_values + 1))
    ops_done = 0
    while ops_done < n_ops or pending:
        do_invoke = idle and (ops_done < n_ops) and (not pending or rng.random() < 0.6)
        if do_invoke:
            p = rng.choice(idle)
            idle.remove(p)
            # plain choice when unweighted: rng.choices consumes a
            # different PRNG stream, which would silently regenerate
            # every fixed-seed corpus
            if op_weights is None:
                f = rng.choice(["read", "write", "cas"])
            else:
                f = rng.choices(["read", "write", "cas"], weights=op_weights)[0]
            if f == "read":
                hist.append(invoke_op(p, "read"))
                pending[p] = ("read", None)
            elif f == "write":
                v = rng.choice(values)
                hist.append(invoke_op(p, "write", v))
                pending[p] = ("write", v)
            else:
                old = rng.choice(values + [state])
                new = rng.choice(values)
                hist.append(invoke_op(p, "cas", (old, new)))
                pending[p] = ("cas", (old, new))
            ops_done += 1
        else:
            p = rng.choice(list(pending.keys()))
            f, v = pending.pop(p)
            if rng.random() < crash_p:
                # crashed: decide secretly whether it took effect; the
                # crashed process id is never reused
                if f == "write" and rng.random() < 0.5:
                    state = v
                elif f == "cas" and rng.random() < 0.5 and state == v[0]:
                    state = v[1]
                hist.append(info_op(p, f, v))
                if replace_crashed:
                    idle.append(next_pid)
                    next_pid += 1
            else:
                if f == "read":
                    v = state
                elif f == "write":
                    state = v
                elif f == "cas":
                    if state == v[0]:
                        state = v[1]
                    else:
                        hist.append(fail_op(p, f, v))
                        idle.append(p)
                        continue
                hist.append(ok_op(p, f, v))
                idle.append(p)
        if not idle and not pending:
            break
    out = History(hist)
    if corrupt and len(out) > 2:
        oks = [i for i, op in enumerate(out) if op.type == "ok"]
        if oks:
            i = rng.choice(oks)
            op = out[i]
            if op.f in ("read", "write"):
                out[i] = op.copy(value=rng.choice([7, 8, 9]))
    for i, op in enumerate(out):
        op.index = i
        op.time = i
    return out


def generate_batch(
    seed: int,
    n_histories: int,
    n_procs: int = 4,
    n_ops: int = 30,
    crash_p: float = 0.05,
    corrupt_fraction: float = 0.0,
):
    """A list of histories, a deterministic function of seed."""
    rng = random.Random(seed)
    out = []
    for i in range(n_histories):
        corrupt = rng.random() < corrupt_fraction
        out.append(
            generate_history(
                rng, n_procs=n_procs, n_ops=n_ops, crash_p=crash_p, corrupt=corrupt
            )
        )
    return out


def generate_mr_history(
    rng: random.Random,
    n_procs: int = 4,
    n_ops: int = 40,
    n_keys: int = 3,
    n_values: int = 4,
    crash_p: float = 0.1,
    corrupt: bool = False,
) -> History:
    """One simulated concurrent execution over a multi-register: ops are
    single-mop transactions ``[("r"|"w", key, value)]`` against keys
    0..n_keys-1, each initially 0 (pair with models.multi_register({k: 0
    for k in range(n_keys)})).  Valid by construction unless corrupt."""
    state = {k: 0 for k in range(n_keys)}
    hist = []
    pending = {}
    idle = list(range(n_procs))
    values = list(range(1, n_values + 1))
    ops_done = 0
    while ops_done < n_ops or pending:
        do_invoke = idle and (ops_done < n_ops) and (not pending or rng.random() < 0.6)
        if do_invoke:
            p = rng.choice(idle)
            idle.remove(p)
            k = rng.randrange(n_keys)
            if rng.random() < 0.5:
                hist.append(invoke_op(p, "txn", [("r", k, None)]))
                pending[p] = ("r", k, None)
            else:
                v = rng.choice(values)
                hist.append(invoke_op(p, "txn", [("w", k, v)]))
                pending[p] = ("w", k, v)
            ops_done += 1
        else:
            p = rng.choice(list(pending.keys()))
            mf, k, v = pending.pop(p)
            if rng.random() < crash_p:
                if mf == "w" and rng.random() < 0.5:
                    state[k] = v
                hist.append(info_op(p, "txn", [(mf, k, v)]))
            else:
                if mf == "r":
                    v = state[k]
                else:
                    state[k] = v
                hist.append(ok_op(p, "txn", [(mf, k, v)]))
                idle.append(p)
        if not idle and not pending:
            break  # every process crashed
    out = History(hist)
    if corrupt and len(out) > 2:
        reads = [
            i
            for i, op in enumerate(out)
            if op.type == "ok" and op.value and op.value[0][0] == "r"
        ]
        if reads:
            i = rng.choice(reads)
            op = out[i]
            _mf, k, _v = op.value[0]
            out[i] = op.copy(value=[("r", k, rng.choice([7, 8, 9]))])
    for i, op in enumerate(out):
        op.index = i
        op.time = i
    return out


def generate_lock_history(
    rng,
    n_procs: int = 4,
    n_ops: int = 40,
    reentrant: bool = False,
    corrupt: bool = False,
):
    """Simulated owner-aware (optionally reentrant, hold bound 2)
    distributed lock with real contention: waiters stay pending until
    the lock frees (like the hazelcast suite's try_lock clients), so
    histories are dense with successful acquire/release cycles rather
    than failed probes.  A release's linearization point sits anywhere
    in its invoke window, so a grant may interleave there — real
    concurrency, still linearizable.  Completions carry {"client":
    name} the way suites/hazelcast.py stamps identity.  corrupt=True
    fabricates one definite violation: a grant while held with no open
    release that could linearize first."""
    cap = 2 if reentrant else 1
    hist = []
    idle = list(range(n_procs))
    waiting: list = []      # acquire invoked, not granted
    holds = {p: 0 for p in range(n_procs)}
    releasing: list = []    # release invoked, not ok'd
    eff = 0                 # holds outstanding after in-flight releases
    corrupted = False
    done = 0
    while done < n_ops or waiting or releasing:
        can_acq = [p for p in idle if holds[p] == 0]
        can_reacq = [p for p in idle if 0 < holds[p] < cap]
        can_rel = [p for p in idle if holds[p] > 0]
        legit_grant = [
            p for p in waiting
            if eff == 0 or (0 < holds[p] < cap)
        ]
        moves = []
        if done < n_ops and (can_acq or (reentrant and can_reacq)):
            moves.append("inv_acq")
        # releases stay available past the op budget so waiters drain
        # (holders must free the lock for pending grants to complete)
        if can_rel and (done < n_ops or waiting):
            moves.append("inv_rel")
        if legit_grant:
            moves.append("grant")
        elif waiting and corrupt and not corrupted and not releasing:
            # no legitimate grant exists and no release is open: a
            # grant here is a definite violation in every ordering
            moves.append("bad_grant")
        if releasing:
            moves.append("ok_rel")
        if not moves:
            break  # defensive: the current move set always drains
        mv = rng.choice(moves)
        if mv == "inv_acq":
            pool = can_acq + (can_reacq if reentrant else [])
            p = pool[rng.randrange(len(pool))]
            idle.remove(p)
            hist.append(invoke_op(p, "acquire", None))
            waiting.append(p)
            done += 1
        elif mv == "inv_rel":
            p = can_rel[rng.randrange(len(can_rel))]
            idle.remove(p)
            hist.append(invoke_op(p, "release", None))
            releasing.append(p)
            eff -= 1  # the release may linearize from here on
            done += 1
        elif mv in ("grant", "bad_grant"):
            pool = legit_grant if mv == "grant" else waiting
            p = pool[rng.randrange(len(pool))]
            waiting.remove(p)
            holds[p] += 1
            eff += 1
            hist.append(ok_op(p, "acquire", {"client": f"c{p}"}))
            idle.append(p)
            if mv == "bad_grant":
                corrupted = True
        else:  # ok_rel
            p = releasing.pop(rng.randrange(len(releasing)))
            holds[p] -= 1
            hist.append(ok_op(p, "release", {"client": f"c{p}"}))
            idle.append(p)
    # Defensive tail (currently unreachable: a move always exists while
    # waiters remain, so the loop drains them): if a future move-set
    # change ever strands a waiter, it must leave as an IDENTITY-BEARING
    # info op — an identity-less open invoke would push the whole
    # history onto the oracle, which is exponential at contended shapes.
    for p in waiting:
        hist.append(info_op(p, "acquire", {"client": f"c{p}"}))
    h = History(hist)
    for i, op in enumerate(h):
        op.index = i
        op.time = i
    return h.index_ops()


def generate_permits_history(
    rng,
    n_procs: int = 5,
    n_ops: int = 40,
    n_permits: int = 2,
    corrupt: bool = False,
):
    """Simulated semaphore: each process is one client holding at most
    one permit at a time; waiters block until a permit frees (a
    release's linearization point sits anywhere in its invoke window).
    Completions carry {"client": name}.  corrupt=True fabricates one
    definite over-issue: a grant past n_permits with no open release
    that could linearize first."""
    from .history import History, info_op, invoke_op, ok_op

    hist = []
    idle = list(range(n_procs))
    waiting: list = []
    holds = {p: 0 for p in range(n_procs)}
    releasing: list = []
    eff = 0  # permits outstanding after in-flight releases linearize
    corrupted = False
    done = 0
    while done < n_ops or waiting or releasing:
        can_acq = [p for p in idle if holds[p] == 0]
        can_rel = [p for p in idle if holds[p] > 0]
        grantable = eff < n_permits
        moves = []
        if done < n_ops and can_acq:
            moves.append("inv_acq")
        if can_rel and (done < n_ops or waiting):
            moves.append("inv_rel")
        if waiting and grantable:
            moves.append("grant")
        elif waiting and corrupt and not corrupted and not releasing:
            moves.append("bad_grant")
        if releasing:
            moves.append("ok_rel")
        if not moves:
            break  # stranded waiters become open info ops below
        mv = rng.choice(moves)
        if mv == "inv_acq":
            p = can_acq[rng.randrange(len(can_acq))]
            idle.remove(p)
            hist.append(invoke_op(p, "acquire", None))
            waiting.append(p)
            done += 1
        elif mv == "inv_rel":
            p = can_rel[rng.randrange(len(can_rel))]
            idle.remove(p)
            hist.append(invoke_op(p, "release", None))
            releasing.append(p)
            eff -= 1
            done += 1
        elif mv in ("grant", "bad_grant"):
            p = waiting.pop(rng.randrange(len(waiting)))
            holds[p] += 1
            eff += 1
            hist.append(ok_op(p, "acquire", {"client": f"c{p}"}))
            idle.append(p)
            if mv == "bad_grant":
                corrupted = True
        else:  # ok_rel
            p = releasing.pop(rng.randrange(len(releasing)))
            holds[p] -= 1
            hist.append(ok_op(p, "release", {"client": f"c{p}"}))
            idle.append(p)
    for p in waiting:
        hist.append(info_op(p, "acquire", {"client": f"c{p}"}))
    h = History(hist)
    for i, op in enumerate(h):
        op.index = i
        op.time = i
    return h.index_ops()
