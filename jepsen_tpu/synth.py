"""Synthetic history generation — for differential tests and benchmarks.

Simulates honest linearizable executions of a CAS register with real
concurrency (ops linearize at completion; crashes secretly apply or not),
plus an optional corruption pass that produces likely-invalid histories.
This is the batch feeder for BASELINE configs 1 and 3 (synthetic
CAS-register suites).
"""

from __future__ import annotations

import random
from typing import Optional

from .history import History, invoke_op, ok_op, fail_op, info_op


def generate_history(
    rng: random.Random,
    n_procs: int = 4,
    n_ops: int = 30,
    crash_p: float = 0.1,
    corrupt: bool = False,
    n_values: int = 5,
) -> History:
    """One simulated concurrent CAS-register execution.

    Valid by construction when corrupt=False (every completed op
    linearizes at its completion point; crashed ops apply secretly with
    probability 1/2).  corrupt=True flips one completion value, usually
    (not always) making the history non-linearizable.
    """
    state = 0
    hist = []
    pending = {}
    idle = list(range(n_procs))
    values = list(range(1, n_values + 1))
    ops_done = 0
    while ops_done < n_ops or pending:
        do_invoke = idle and (ops_done < n_ops) and (not pending or rng.random() < 0.6)
        if do_invoke:
            p = rng.choice(idle)
            idle.remove(p)
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                hist.append(invoke_op(p, "read"))
                pending[p] = ("read", None)
            elif f == "write":
                v = rng.choice(values)
                hist.append(invoke_op(p, "write", v))
                pending[p] = ("write", v)
            else:
                old = rng.choice(values + [state])
                new = rng.choice(values)
                hist.append(invoke_op(p, "cas", (old, new)))
                pending[p] = ("cas", (old, new))
            ops_done += 1
        else:
            p = rng.choice(list(pending.keys()))
            f, v = pending.pop(p)
            if rng.random() < crash_p:
                # crashed: decide secretly whether it took effect; the
                # crashed process id is never reused
                if f == "write" and rng.random() < 0.5:
                    state = v
                elif f == "cas" and rng.random() < 0.5 and state == v[0]:
                    state = v[1]
                hist.append(info_op(p, f, v))
            else:
                if f == "read":
                    v = state
                elif f == "write":
                    state = v
                elif f == "cas":
                    if state == v[0]:
                        state = v[1]
                    else:
                        hist.append(fail_op(p, f, v))
                        idle.append(p)
                        continue
                hist.append(ok_op(p, f, v))
                idle.append(p)
        if not idle and not pending:
            break
    out = History(hist)
    if corrupt and len(out) > 2:
        oks = [i for i, op in enumerate(out) if op.type == "ok"]
        if oks:
            i = rng.choice(oks)
            op = out[i]
            if op.f in ("read", "write"):
                out[i] = op.copy(value=rng.choice([7, 8, 9]))
    for i, op in enumerate(out):
        op.index = i
        op.time = i
    return out


def generate_batch(
    seed: int,
    n_histories: int,
    n_procs: int = 4,
    n_ops: int = 30,
    crash_p: float = 0.05,
    corrupt_fraction: float = 0.0,
):
    """A list of histories, a deterministic function of seed."""
    rng = random.Random(seed)
    out = []
    for i in range(n_histories):
        corrupt = rng.random() < corrupt_fraction
        out.append(
            generate_history(
                rng, n_procs=n_procs, n_ops=n_ops, crash_p=crash_p, corrupt=corrupt
            )
        )
    return out
