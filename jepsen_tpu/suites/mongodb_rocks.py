"""MongoDB (RocksDB storage engine) suite.

Reference: mongodb-rocks/src/jepsen/mongodb_rocks.clj — install the
parse-built mongodb-org-server deb (:29-40), run mongod with
``--storageEngine rocksdb`` and a replica set spanning the test nodes,
``replSetInitiate`` from node 1, and run a CAS-register workload over
the wire protocol with majority write concern / linearizable-ish reads
(the reference layers atop the jepsen.mongodb suite's document CAS via
findAndModify).
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from . import common
from .proto import IndeterminateError
from .proto.mongo import MongoClient, MongoError

PORT = 27017
RS = "jepsen"
DB_DIR = "/var/lib/mongodb"
STORAGE_ENGINE = "rocksdb"


class MongoDB(common.DaemonDB):
    logfile = "/var/log/mongodb/mongod.log"
    pidfile = "/var/run/mongod.pid"
    proc_name = "mongod"
    storage_engine = STORAGE_ENGINE

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "3.0.6")
        self.storage_engine = (opts or {}).get(
            "storage-engine", type(self).storage_engine)

    def install(self, test, node):
        # (reference: mongodb_rocks.clj:29-40 install!)
        url = (
            "https://s3.amazonaws.com/parse-mongodb-builds/debs/"
            f"mongodb-org-server_{self.version}_amd64.deb"
        )
        with control.su():
            deb = cu.cached_wget(url)
            control.execute("dpkg", "-i", deb, check=False)
            control.execute("mkdir", "-p", DB_DIR, "/var/log/mongodb")

    def start(self, test, node):
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile,
             "chdir": DB_DIR},
            "/usr/bin/mongod",
            "--dbpath", DB_DIR,
            "--port", str(PORT),
            "--bind_ip", "0.0.0.0",
            "--replSet", RS,
            "--storageEngine", self.storage_engine,
        )

    def setup(self, test, node):
        super().setup(test, node)
        if node == test["nodes"][0]:
            members = ", ".join(
                f'{{_id: {i}, host: "{n}:{PORT}"}}'
                for i, n in enumerate(test["nodes"])
            )
            control.execute(
                "mongo", "--port", str(PORT), "--eval",
                f'rs.initiate({{_id: "{RS}", members: [{members}]}})',
                check=False,
            )

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", DB_DIR)


class MongoRegisterClient(client_mod.Client):
    """Document CAS via findAndModify with majority write concern
    (reference: the jepsen.mongodb document-cas client the rocks suite
    reuses)."""

    COLL = "registers"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[MongoClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = MongoClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            database=self.opts.get("database", "jepsen"),
            timeout=self.opts.get("timeout", 10.0),
        )
        return c

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                docs = self.conn.find(self.COLL, {"_id": int(k)})
                val = docs[0].get("value") if docs else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.conn.update(
                    self.COLL, {"_id": int(k)},
                    {"$set": {"value": int(v)}}, upsert=True,
                    write_concern={"w": "majority"},
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                doc = self.conn.find_and_modify(
                    self.COLL,
                    {"_id": int(k), "value": int(old)},
                    {"$set": {"value": int(new)}},
                    write_concern={"w": "majority"},
                )
                if doc is None:
                    return {**op, "type": "fail", "error": "cas-miss"}
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except MongoError as e:
            return {**op, "type": "fail", "error": str(e)}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return MongoDB(opts)


def client(opts: Optional[dict] = None):
    return MongoRegisterClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": common.register_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["register"]
    return common.build_test(
        "mongodb-rocks-register", opts, db=MongoDB(opts),
        client=MongoRegisterClient(opts), workload=w,
    )
