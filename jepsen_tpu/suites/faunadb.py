"""FaunaDB suite.

Reference: faunadb/src/jepsen/faunadb/{auto,client,register,bank,set,
monotonic,multimonotonic,pages,g2,topology}.clj — install the faunadb
deb from the repo (auto.clj:379-420), write ``/etc/faunadb.yml`` with
the cluster's replica topology, ``faunadb-admin init/join`` the ring,
and drive FQL transactions through the Java driver.

Here the client speaks Fauna's JSON wire protocol directly: an FQL
expression serialises to JSON (``{"get": {"@ref": …}}`` etc.) POSTed to
``/`` with HTTP basic auth (the cluster admin secret), which is exactly
what the Java driver emits on the wire.  Register CAS compiles to a
single ``if(equals(select(..), old), update(..), abort(..))``
transaction, so each op is one atomic Fauna query.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional

from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

PORT = 8443
SECRET = "secret"  # cluster admin key (reference: auto.clj root-key)
DIR = "/opt/faunadb"
LOGFILE = "/var/log/faunadb/core.log"

CLASS = "registers"


class FaunaDB(common.DaemonDB):
    logfile = LOGFILE

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "2.5.5")

    def install(self, test, node):
        # (reference: auto.clj:379-420 install! — deb repo + JDK)
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.write_file(
                "deb [arch=all] https://repo.fauna.com/debian stable non-free\n",
                "/etc/apt/sources.list.d/faunadb.list",
            )
            execute("apt-get", "update", check=False)
        debian.install([f"faunadb={self.version}"])

    def configure(self, test, node):
        # (reference: auto.clj configure! — faunadb.yml topology)
        config = "\n".join(
            [
                f"auth_root_key: {SECRET}",
                f"network_broadcast_address: {node}",
                "network_listen_address: 0.0.0.0",
                "storage_data_path: /var/lib/faunadb",
                "cluster_name: jepsen",
            ]
        )
        with sudo():
            cu.write_file(config, "/etc/faunadb.yml")

    def start(self, test, node):
        with sudo():
            execute("service", "faunadb", "start", check=False)
        cu.await_tcp_port(PORT, timeout_s=300)
        if node == test["nodes"][0]:
            execute("faunadb-admin", "init", check=False)
        else:
            execute("faunadb-admin", "join", str(test["nodes"][0]),
                    check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "faunadb", "stop", check=False)
            cu.grepkill("faunadb")

    def pause(self, test, node):
        cu.signal("java", "STOP")

    def resume(self, test, node):
        cu.signal("java", "CONT")

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", "/var/lib/faunadb")


# -- FQL JSON wire helpers --------------------------------------------


def ref(cls: str, id_: Any) -> dict:
    return {"ref": {"@ref": f"classes/{cls}/{id_}"}}


def class_ref(cls: str) -> dict:
    return {"@ref": f"classes/{cls}"}


class FaunaClient(client_mod.Client):
    """CAS register over Fauna's JSON wire protocol
    (reference: faunadb/client.clj query/0 + register.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def _headers(self):
        tok = base64.b64encode(f"{SECRET}:".encode()).decode()
        return {"Authorization": f"Basic {tok}"}

    def query(self, expr: Any):
        _, body = self.conn.post(
            "/", json.dumps(expr), headers=self._headers(), ok=(200,)
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        return (body or {}).get("resource")

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": CLASS}}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        r = {"@ref": f"classes/{CLASS}/{k}"}
        sel = {"select": ["data", "value"], "from": {"get": r},
               "default": None}
        try:
            if op["f"] == "read":
                val = self.query(sel)
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.query(
                    {
                        "if": {"exists": r},
                        "then": {"update": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                        "else": {"create": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                    }
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                out = self.query(
                    {
                        "if": {"equals": [sel, old]},
                        "then": [
                            {"update": r,
                             "params": {"object": {"data": {
                                 "object": {"value": new}}}}},
                            True,
                        ],
                        "else": False,
                    }
                )
                if out in (True, [True]) or (
                        isinstance(out, list) and out and out[-1] is True):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return FaunaDB(opts)


def client(opts: Optional[dict] = None):
    return FaunaClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    # bank/set/pages/monotonic need FQL pagination the wire client
    # doesn't model yet; register and g2 are complete
    from ..workloads import adya

    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "g2": adya.workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = FaunaG2Client(opts) if wname == "g2" else FaunaClient(opts)
    return common.build_test(
        f"faunadb-{wname}", opts, db=FaunaDB(opts), client=c, workload=w,
    )


# ---------------------------------------------------------------------
# g2 (anti-dependency cycle) workload
# ---------------------------------------------------------------------

G2_CLASSES = ("g2a", "g2b")


class FaunaG2Client(FaunaClient):
    """Paired predicate inserts: create into class a (or b) only if the
    *other* class's index has no entry for the key — under
    serializability at most one of each pair commits.

    Reference: faunadb/src/jepsen/faunadb/g2.clj:33-76 — setup upserts
    classes a/b plus key-term indexes; :insert runs
    ``when (not (exists (match other-index k))) (create (ref class id))``
    and reuses jepsen.tests.adya's generator/checker.
    """

    def setup(self, test):
        for cls in G2_CLASSES:
            try:
                self.query({"create_class": {"object": {"name": cls}}})
            except (HttpError, IndeterminateError):
                pass
            try:
                self.query(
                    {
                        "create_index": {
                            "object": {
                                "name": f"{cls}-index",
                                "source": class_ref(cls),
                                "terms": [{"field": ["data", "key"]}],
                                "active": True,
                            }
                        }
                    }
                )
            except (HttpError, IndeterminateError):
                pass

    def invoke(self, test, op):
        assert op["f"] == "insert", op
        k, ids = op["value"]
        a_id, b_id = ids
        id_ = a_id if a_id is not None else b_id
        cls = G2_CLASSES[0] if a_id is not None else G2_CLASSES[1]
        other = G2_CLASSES[1] if a_id is not None else G2_CLASSES[0]
        try:
            res = self.query(
                {
                    "if": {
                        "not": {
                            "exists": {
                                "match": {"index": f"{other}-index"},
                                "terms": [k],
                            }
                        }
                    },
                    "then": {
                        "create": ref(cls, id_),
                        "params": {"object": {"data": {
                            "object": {"key": k}}}},
                    },
                    "else": None,
                }
            )
            if res:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "conflict"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}
