"""FaunaDB suite.

Reference: faunadb/src/jepsen/faunadb/{auto,client,register,bank,set,
monotonic,multimonotonic,pages,g2,topology}.clj — install the faunadb
deb from the repo (auto.clj:379-420), write ``/etc/faunadb.yml`` with
the cluster's replica topology, ``faunadb-admin init/join`` the ring,
and drive FQL transactions through the Java driver.

Here the client speaks Fauna's JSON wire protocol directly: an FQL
expression serialises to JSON (``{"get": {"@ref": …}}`` etc.) POSTed to
``/`` with HTTP basic auth (the cluster admin secret), which is exactly
what the Java driver emits on the wire.  Register CAS compiles to a
single ``if(equals(select(..), old), update(..), abort(..))``
transaction, so each op is one atomic Fauna query.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

PORT = 8443
SECRET = "secret"  # cluster admin key (reference: auto.clj root-key)
DIR = "/opt/faunadb"
LOGFILE = "/var/log/faunadb/core.log"

CLASS = "registers"


class FaunaDB(common.DaemonDB):
    logfile = LOGFILE

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "2.5.5")

    def install(self, test, node):
        # (reference: auto.clj:379-420 install! — deb repo + JDK)
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.write_file(
                "deb [arch=all] https://repo.fauna.com/debian stable non-free\n",
                "/etc/apt/sources.list.d/faunadb.list",
            )
            execute("apt-get", "update", check=False)
        debian.install([f"faunadb={self.version}"])

    def configure(self, test, node):
        # (reference: auto.clj configure! — faunadb.yml topology)
        config = "\n".join(
            [
                f"auth_root_key: {SECRET}",
                f"network_broadcast_address: {node}",
                "network_listen_address: 0.0.0.0",
                "storage_data_path: /var/lib/faunadb",
                "cluster_name: jepsen",
            ]
        )
        with sudo():
            cu.write_file(config, "/etc/faunadb.yml")

    def start(self, test, node):
        with sudo():
            execute("service", "faunadb", "start", check=False)
        cu.await_tcp_port(PORT, timeout_s=300)
        if node == test["nodes"][0]:
            execute("faunadb-admin", "init", check=False)
        else:
            execute("faunadb-admin", "join", str(test["nodes"][0]),
                    check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "faunadb", "stop", check=False)
            cu.grepkill("faunadb")

    def pause(self, test, node):
        cu.signal("java", "STOP")

    def resume(self, test, node):
        cu.signal("java", "CONT")

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", "/var/lib/faunadb")


# -- FQL JSON wire helpers --------------------------------------------


def ref(cls: str, id_: Any) -> dict:
    return {"ref": {"@ref": f"classes/{cls}/{id_}"}}


def class_ref(cls: str) -> dict:
    return {"@ref": f"classes/{cls}"}


class FaunaClient(client_mod.Client):
    """CAS register over Fauna's JSON wire protocol
    (reference: faunadb/client.clj query/0 + register.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def _headers(self):
        tok = base64.b64encode(f"{SECRET}:".encode()).decode()
        return {"Authorization": f"Basic {tok}"}

    def query(self, expr: Any):
        _, body = self.conn.post(
            "/", json.dumps(expr), headers=self._headers(), ok=(200,)
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        return (body or {}).get("resource")

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": CLASS}}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        r = {"@ref": f"classes/{CLASS}/{k}"}
        sel = {"select": ["data", "value"], "from": {"get": r},
               "default": None}
        try:
            if op["f"] == "read":
                val = self.query(sel)
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.query(
                    {
                        "if": {"exists": r},
                        "then": {"update": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                        "else": {"create": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                    }
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                out = self.query(
                    {
                        "if": {"equals": [sel, old]},
                        "then": [
                            {"update": r,
                             "params": {"object": {"data": {
                                 "object": {"value": new}}}}},
                            True,
                        ],
                        "else": False,
                    }
                )
                if out in (True, [True]) or (
                        isinstance(out, list) and out and out[-1] is True):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return FaunaDB(opts)


def client(opts: Optional[dict] = None):
    return FaunaClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    from ..workloads import adya

    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "g2": adya.workload(opts),
        # flagship probes (reference: faunadb/pages.clj, monotonic.clj)
        "pages": pages_workload(opts),
        "monotonic": monotonic_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {
        "g2": FaunaG2Client,
        "pages": FaunaPagesClient,
        "monotonic": FaunaMonotonicClient,
    }.get(wname, FaunaClient)(opts)
    # topology churn rides the membership state machine
    # (reference: faunadb/topology.clj via nemesis.clj)
    pkg = None
    if "topology" in set(opts.get("faults", ())):
        from . import fauna_topology

        pkg = common.suite_nemesis_package(
            opts, FaunaDB(opts), fauna_topology.package(opts), {"topology"}
        )
    return common.build_test(
        f"faunadb-{wname}", opts, db=FaunaDB(opts), client=c, workload=w,
        nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# g2 (anti-dependency cycle) workload
# ---------------------------------------------------------------------

G2_CLASSES = ("g2a", "g2b")


class FaunaG2Client(FaunaClient):
    """Paired predicate inserts: create into class a (or b) only if the
    *other* class's index has no entry for the key — under
    serializability at most one of each pair commits.

    Reference: faunadb/src/jepsen/faunadb/g2.clj:33-76 — setup upserts
    classes a/b plus key-term indexes; :insert runs
    ``when (not (exists (match other-index k))) (create (ref class id))``
    and reuses jepsen.tests.adya's generator/checker.
    """

    def setup(self, test):
        for cls in G2_CLASSES:
            try:
                self.query({"create_class": {"object": {"name": cls}}})
            except (HttpError, IndeterminateError):
                pass
            try:
                self.query(
                    {
                        "create_index": {
                            "object": {
                                "name": f"{cls}-index",
                                "source": class_ref(cls),
                                "terms": [{"field": ["data", "key"]}],
                                "active": True,
                            }
                        }
                    }
                )
            except (HttpError, IndeterminateError):
                pass

    def invoke(self, test, op):
        assert op["f"] == "insert", op
        k, ids = op["value"]
        a_id, b_id = ids
        id_ = a_id if a_id is not None else b_id
        cls = G2_CLASSES[0] if a_id is not None else G2_CLASSES[1]
        other = G2_CLASSES[1] if a_id is not None else G2_CLASSES[0]
        try:
            res = self.query(
                {
                    "if": {
                        "not": {
                            "exists": {
                                "match": {"index": f"{other}-index"},
                                "terms": [k],
                            }
                        }
                    },
                    "then": {
                        "create": ref(cls, id_),
                        "params": {"object": {"data": {
                            "object": {"key": k}}}},
                    },
                    "else": None,
                }
            )
            if res:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "conflict"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


# ---------------------------------------------------------------------
# pages workload (reference: faunadb/src/jepsen/faunadb/pages.clj)
# ---------------------------------------------------------------------

ELEMENTS_CLASS = "elements"
ELEMENTS_INDEX = "all-elements"


class FaunaPagesClient(FaunaClient):
    """Grouped inserts vs paginated index reads: every element of a
    group must appear with all its companions or not at all.
    (reference: pages.clj — setup:32-42 class+index, add/read:45-60)"""

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": ELEMENTS_CLASS}}})
            self.query({"create_index": {"object": {
                "name": ELEMENTS_INDEX,
                "source": {"@ref": f"classes/{ELEMENTS_CLASS}"},
                "active": True,
                "serialized": bool(test.get("serialized-indices", True)),
                "terms": [{"field": ["data", "key"]}],
                "values": [{"field": ["data", "value"]}],
            }}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "add":
                # one request = one transaction: all group members land
                # together (pages.clj:50-56 q/do* of creates)
                self.query([
                    {"create": {"@ref": f"classes/{ELEMENTS_CLASS}"},
                     "params": {"object": {"data": {"object": {
                         "key": int(k), "value": int(x)}}}}}
                    for x in v
                ])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                out = self.query({
                    "paginate": {"match": {
                        "index": ELEMENTS_INDEX, "terms": [int(k)]}}
                })
                vals = list((out or {}).get("data", []))
                return {**op, "type": "ok", "value": independent.kv(k, vals)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class PagesChecker(checker_mod.Checker):
    """Each read must be a union of whole add-groups.
    (reference: pages.clj:68-94 read-errs, :96-141 checker)"""

    def check(self, test, history, opts=None):
        from ..history import INVOKE, OK, FAIL

        invokes, fails = set(), set()
        ok_reads = []
        for op in history:
            if op.f == "add":
                group = tuple(op.value)
                if op.type == INVOKE:
                    invokes.add(group)
                elif op.type == FAIL:
                    fails.add(group)
            elif op.f == "read" and op.type == OK:
                ok_reads.append(op)
        adds = invokes - fails
        idx = {}
        for group in adds:
            xs = frozenset(group)
            for x in xs:
                if x in idx:
                    return {
                        "valid?": "unknown",
                        "error": f"element {x} added by two groups",
                    }
                idx[x] = xs
        errs = []
        for op in ok_reads:
            vals = list(op.value or [])
            read = set(vals)
            if len(vals) != len(read):
                errs.append({"op-index": op.index,
                             "errors": ["duplicate-items"]})
                continue
            op_errs = []
            while read:
                e = next(iter(read))
                group = idx.get(e)
                if group is None:
                    # not in any possibly-successful add: either a
                    # phantom value or a definitely-failed add showing
                    # up anyway (the reference's invokes-minus-fails
                    # index makes these unaccountable; reporting them
                    # beats passing them)
                    op_errs.append({"unexpected": e})
                    read = read - {e}
                    continue
                if not group <= read:
                    op_errs.append({
                        "expected": sorted(group),
                        "found": sorted(read & group),
                    })
                read = read - group
            if op_errs:
                errs.append({"op-index": op.index, "errors": op_errs})
        return {
            "valid?": not errs,
            "ok-read-count": len(ok_reads),
            "error-count": len(errs),
            "first-error": errs[0] if errs else None,
        }


def pages_workload(opts: Optional[dict] = None) -> dict:
    """Group adds mixed 4:1 with reads, lifted over independent keys.
    (reference: pages.clj:143-169 workload — group-size 4, limit 256,
    stagger 1/5; limits scaled by opts for short runs)"""
    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))
    group_size = int(opts.get("group-size", 4))
    per_key = int(opts.get("per-key-limit", 64))
    value_range = int(opts.get("value-range", 10_000))

    def fgen(k):
        vals = list(range(-value_range, value_range))
        gen_mod.rng.shuffle(vals)
        groups = [
            vals[i : i + group_size]
            for i in range(0, len(vals), group_size)
        ]
        it = iter(groups)

        def g(test, ctx):
            if gen_mod.rng.random() < 0.8:
                try:
                    return {"type": "invoke", "f": "add",
                            "value": next(it)}
                except StopIteration:
                    pass
            return {"type": "invoke", "f": "read", "value": None}

        return gen_mod.limit(
            per_key, gen_mod.stagger(1 / 50, g)
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(PagesChecker()),
        "concurrency": 2 * n,
    }


# ---------------------------------------------------------------------
# monotonic workload (reference: faunadb/src/jepsen/faunadb/monotonic.clj)
# ---------------------------------------------------------------------

REGISTERS_CLASS = "registers"
MONO_KEY = 0


class FaunaMonotonicClient(FaunaClient):
    """A single incrementing register queried with Time() stamps and
    At() temporal reads.

    Reference: monotonic.clj:84-146 — inc returns [ts, old-value] via an
    if/exists/create-or-update transaction; read returns [ts, value];
    read-at evaluates the read At() a (jittered) past timestamp."""

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": REGISTERS_CLASS}}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        r = {"@ref": f"classes/{REGISTERS_CLASS}/{MONO_KEY}"}
        sel = {"select": ["data", "value"], "from": {"get": r},
               "default": 0}
        try:
            if op["f"] == "inc":
                res = self.query([
                    {"time": "now"},
                    {"if": {"exists": r},
                     # old value first, then the increment — list exprs
                     # evaluate in order inside one transaction
                     "then": [sel,
                              {"update": r,
                               "params": {"object": {"data": {"object": {
                                   "value": {"add": [sel, 1]}}}}}}],
                     "else": [{"create": r,
                               "params": {"object": {"data": {"object": {
                                   "value": 1}}}}},
                              0]},
                ])
                ts, branch = res
                v = next(x for x in branch if isinstance(x, int))
                return {**op, "type": "ok", "value": [ts, v]}
            if op["f"] == "read":
                res = self.query([
                    {"time": "now"},
                    {"if": {"exists": r}, "then": sel, "else": 0},
                ])
                return {**op, "type": "ok", "value": [res[0], res[1]]}
            if op["f"] == "read-at":
                ts = (op.get("value") or [None, None])[0]
                if ts is None:
                    now = self.query({"time": "now"})
                    # jitter a few ticks into the past
                    # (reference: f/jitter-time, monotonic.clj:115-119)
                    import random as _random

                    ts = f"{max(1, int(now) - _random.randint(0, 4)):012d}"
                v = self.query({
                    "at": ts,
                    "expr": {"if": {"exists": r}, "then": sel, "else": 0},
                })
                return {**op, "type": "ok", "value": [ts, v]}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            if "not found" in str(e.body):
                return {**op, "type": "fail", "error": "not-found"}
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def _non_monotonic_pairs_by_process(extract, history):
    """(reference: monotonic.clj:152-173)"""
    from ..history import OK

    last: dict = {}
    errs = []
    for op in history:
        if op.type != OK:
            continue
        p = op.process
        v = extract(op)
        lv = extract(last[p]) if p in last else None
        if lv is not None and lv > v:
            errs.append([last[p].index, op.index])
        last[p] = op
    return errs


class MonotonicChecker(checker_mod.Checker):
    """Per-process monotonic values and timestamps over inc/read ops.
    (reference: monotonic.clj:175-193 checker)"""

    def check(self, test, history, opts=None):
        hist = [op for op in history if op.f in ("inc", "read")]
        value_errs = _non_monotonic_pairs_by_process(
            lambda op: op.value[1], hist
        )
        ts_errs = _non_monotonic_pairs_by_process(
            lambda op: op.value[0], hist
        )
        return {
            "valid?": not (value_errs or ts_errs),
            "value-errors": value_errs[:10],
            "ts-errors": ts_errs[:10],
        }


class TimestampValueChecker(checker_mod.Checker):
    """Globally: sorted by timestamp, values never decrease.
    (reference: monotonic.clj:195-218 timestamp-value-checker)"""

    def check(self, test, history, opts=None):
        from ..history import OK

        ops = sorted(
            (op for op in history
             if op.type == OK and op.f in ("read-at", "inc")),
            key=lambda op: op.value[0],
        )
        errs = [
            [a.index, b.index]
            for a, b in zip(ops, ops[1:])
            if a.value[1] > b.value[1]
        ]
        return {"valid?": not errs, "errors": errs[:10]}


class NotFoundChecker(checker_mod.Checker):
    """Existence is checked inside every transaction, so a not-found
    failure is itself a bug.  (reference: monotonic.clj:335-347)"""

    def check(self, test, history, opts=None):
        from ..history import FAIL

        errs = [
            op.index
            for op in history
            if op.type == FAIL and op.error == "not-found"
        ]
        return {"valid?": not errs, "error-count": len(errs),
                "first": errs[0] if errs else None}


class _MonotonicPlotter(checker_mod.Checker):
    """Register value over DB timestamps, one series per process — the
    SVG stand-in for the reference's gnuplot timestamp-value plot
    (monotonic.clj:246-292)."""

    def check(self, test, history, opts=None):
        from ..checker import perf
        from ..history import OK

        series: dict = {}
        for op in history:
            if op.type == OK and op.f in ("inc", "read", "read-at"):
                series.setdefault(op.process, []).append(
                    (int(op.value[0]), op.value[1])
                )
        if not any(series.values()):
            return {"valid?": True}
        perf.scatter_plot(
            test,
            series,
            path_components=list((opts or {}).get("subdirectory", []))
            + ["monotonic.svg"],
            title=f"{test.get('name', 'test')} value by timestamp",
            ylabel="register value",
            history=history,
        )
        return {"valid?": True}


def monotonic_workload(opts: Optional[dict] = None) -> dict:
    """(reference: monotonic.clj:349-372 workload; the :events final
    generator is omitted — the reference marks Fauna's event-history
    traversal as broken, monotonic.clj:130-131)"""
    from .. import generator as gen_mod

    def inc_gen(test, ctx):
        return {"type": "invoke", "f": "inc", "value": None}

    def read_gen(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def read_at_gen(test, ctx):
        return {"type": "invoke", "f": "read-at", "value": [None, None]}

    return {
        "generator": gen_mod.mix([inc_gen, read_gen, read_at_gen]),
        "checker": checker_mod.compose({
            "monotonic": MonotonicChecker(),
            "not-found": NotFoundChecker(),
            "timestamp-value": TimestampValueChecker(),
            "timestamp-value-plot": _MonotonicPlotter(),
        }),
    }
