"""FaunaDB suite.

Reference: faunadb/src/jepsen/faunadb/{auto,client,register,bank,set,
monotonic,multimonotonic,pages,g2,topology}.clj — install the faunadb
deb from the repo (auto.clj:379-420), write ``/etc/faunadb.yml`` with
the cluster's replica topology, ``faunadb-admin init/join`` the ring,
and drive FQL transactions through the Java driver.

Here the client speaks Fauna's JSON wire protocol directly: an FQL
expression serialises to JSON (``{"get": {"@ref": …}}`` etc.) POSTed to
``/`` with HTTP basic auth (the cluster admin secret), which is exactly
what the Java driver emits on the wire.  Register CAS compiles to a
single ``if(equals(select(..), old), update(..), abort(..))``
transaction, so each op is one atomic Fauna query.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import generator as gen_base
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

PORT = 8443
SECRET = "secret"  # cluster admin key (reference: auto.clj root-key)
DIR = "/opt/faunadb"
LOGFILE = "/var/log/faunadb/core.log"

CLASS = "registers"


class FaunaDB(common.DaemonDB):
    logfile = LOGFILE

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", "2.5.5")

    def install(self, test, node):
        # (reference: auto.clj:379-420 install! — deb repo + JDK)
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.write_file(
                "deb [arch=all] https://repo.fauna.com/debian stable non-free\n",
                "/etc/apt/sources.list.d/faunadb.list",
            )
            execute("apt-get", "update", check=False)
        debian.install([f"faunadb={self.version}"])

    def configure(self, test, node):
        # (reference: auto.clj configure! — faunadb.yml topology)
        config = "\n".join(
            [
                f"auth_root_key: {SECRET}",
                f"network_broadcast_address: {node}",
                "network_listen_address: 0.0.0.0",
                "storage_data_path: /var/lib/faunadb",
                "cluster_name: jepsen",
            ]
        )
        with sudo():
            cu.write_file(config, "/etc/faunadb.yml")

    def start(self, test, node):
        with sudo():
            execute("service", "faunadb", "start", check=False)
        cu.await_tcp_port(PORT, timeout_s=300)
        if node == test["nodes"][0]:
            execute("faunadb-admin", "init", check=False)
        else:
            execute("faunadb-admin", "join", str(test["nodes"][0]),
                    check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "faunadb", "stop", check=False)
            cu.grepkill("faunadb")

    def pause(self, test, node):
        cu.signal("java", "STOP")

    def resume(self, test, node):
        cu.signal("java", "CONT")

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", "/var/lib/faunadb")


# -- FQL JSON wire helpers --------------------------------------------


def ref(cls: str, id_: Any) -> dict:
    return {"ref": {"@ref": f"classes/{cls}/{id_}"}}


def class_ref(cls: str) -> dict:
    return {"@ref": f"classes/{cls}"}


class FaunaClient(client_mod.Client):
    """CAS register over Fauna's JSON wire protocol
    (reference: faunadb/client.clj query/0 + register.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", PORT),
            timeout=10.0,
        )
        return c

    def _headers(self):
        tok = base64.b64encode(f"{SECRET}:".encode()).decode()
        return {"Authorization": f"Basic {tok}"}

    def query(self, expr: Any):
        _, body = self.conn.post(
            "/", json.dumps(expr), headers=self._headers(), ok=(200,)
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        return (body or {}).get("resource")

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": CLASS}}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        r = {"@ref": f"classes/{CLASS}/{k}"}
        sel = {"select": ["data", "value"], "from": {"get": r},
               "default": None}
        try:
            if op["f"] == "read":
                val = self.query(sel)
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self.query(
                    {
                        "if": {"exists": r},
                        "then": {"update": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                        "else": {"create": r,
                                 "params": {"object": {"data": {
                                     "object": {"value": v}}}}},
                    }
                )
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                out = self.query(
                    {
                        "if": {"equals": [sel, old]},
                        "then": [
                            {"update": r,
                             "params": {"object": {"data": {
                                 "object": {"value": new}}}}},
                            True,
                        ],
                        "else": False,
                    }
                )
                if out in (True, [True]) or (
                        isinstance(out, list) and out and out[-1] is True):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return FaunaDB(opts)


def client(opts: Optional[dict] = None):
    return FaunaClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    from ..workloads import adya

    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "g2": adya.workload(opts),
        # flagship probes (reference: faunadb/pages.clj, monotonic.clj,
        # bank.clj, set.clj, multimonotonic.clj)
        "pages": pages_workload(opts),
        "monotonic": monotonic_workload(opts),
        "bank": bank_workload(opts),
        "bank-index": bank_workload(opts),
        "set": set_workload(opts),
        "multimonotonic": multimonotonic_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {
        "g2": FaunaG2Client,
        "pages": FaunaPagesClient,
        "monotonic": FaunaMonotonicClient,
        "bank": FaunaBankClient,
        "bank-index": FaunaBankIndexClient,
        "set": FaunaSetClient,
        "multimonotonic": FaunaMultiMonotonicClient,
    }.get(wname, FaunaClient)(opts)
    # topology churn rides the membership state machine
    # (reference: faunadb/topology.clj via nemesis.clj)
    pkg = None
    if "topology" in set(opts.get("faults", ())):
        from . import fauna_topology

        pkg = common.suite_nemesis_package(
            opts, FaunaDB(opts), fauna_topology.package(opts), {"topology"}
        )
    return common.build_test(
        f"faunadb-{wname}", opts, db=FaunaDB(opts), client=c, workload=w,
        nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# g2 (anti-dependency cycle) workload
# ---------------------------------------------------------------------

G2_CLASSES = ("g2a", "g2b")


class FaunaG2Client(FaunaClient):
    """Paired predicate inserts: create into class a (or b) only if the
    *other* class's index has no entry for the key — under
    serializability at most one of each pair commits.

    Reference: faunadb/src/jepsen/faunadb/g2.clj:33-76 — setup upserts
    classes a/b plus key-term indexes; :insert runs
    ``when (not (exists (match other-index k))) (create (ref class id))``
    and reuses jepsen.tests.adya's generator/checker.
    """

    def setup(self, test):
        for cls in G2_CLASSES:
            try:
                self.query({"create_class": {"object": {"name": cls}}})
            except (HttpError, IndeterminateError):
                pass
            try:
                self.query(
                    {
                        "create_index": {
                            "object": {
                                "name": f"{cls}-index",
                                "source": class_ref(cls),
                                "terms": [{"field": ["data", "key"]}],
                                "active": True,
                            }
                        }
                    }
                )
            except (HttpError, IndeterminateError):
                pass

    def invoke(self, test, op):
        assert op["f"] == "insert", op
        k, ids = op["value"]
        a_id, b_id = ids
        id_ = a_id if a_id is not None else b_id
        cls = G2_CLASSES[0] if a_id is not None else G2_CLASSES[1]
        other = G2_CLASSES[1] if a_id is not None else G2_CLASSES[0]
        try:
            res = self.query(
                {
                    "if": {
                        "not": {
                            "exists": {
                                "match": {"index": f"{other}-index"},
                                "terms": [k],
                            }
                        }
                    },
                    "then": {
                        "create": ref(cls, id_),
                        "params": {"object": {"data": {
                            "object": {"key": k}}}},
                    },
                    "else": None,
                }
            )
            if res:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": "conflict"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


# ---------------------------------------------------------------------
# pages workload (reference: faunadb/src/jepsen/faunadb/pages.clj)
# ---------------------------------------------------------------------

ELEMENTS_CLASS = "elements"
ELEMENTS_INDEX = "all-elements"


class FaunaPagesClient(FaunaClient):
    """Grouped inserts vs paginated index reads: every element of a
    group must appear with all its companions or not at all.
    (reference: pages.clj — setup:32-42 class+index, add/read:45-60)"""

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": ELEMENTS_CLASS}}})
            self.query({"create_index": {"object": {
                "name": ELEMENTS_INDEX,
                "source": {"@ref": f"classes/{ELEMENTS_CLASS}"},
                "active": True,
                "serialized": bool(test.get("serialized-indices", True)),
                "terms": [{"field": ["data", "key"]}],
                "values": [{"field": ["data", "value"]}],
            }}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "add":
                # one request = one transaction: all group members land
                # together (pages.clj:50-56 q/do* of creates)
                self.query([
                    {"create": {"@ref": f"classes/{ELEMENTS_CLASS}"},
                     "params": {"object": {"data": {"object": {
                         "key": int(k), "value": int(x)}}}}}
                    for x in v
                ])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                out = self.query({
                    "paginate": {"match": {
                        "index": ELEMENTS_INDEX, "terms": [int(k)]}}
                })
                vals = list((out or {}).get("data", []))
                return {**op, "type": "ok", "value": independent.kv(k, vals)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class PagesChecker(checker_mod.Checker):
    """Each read must be a union of whole add-groups.
    (reference: pages.clj:68-94 read-errs, :96-141 checker)"""

    def check(self, test, history, opts=None):
        from ..history import INVOKE, OK, FAIL

        invokes, fails = set(), set()
        ok_reads = []
        for op in history:
            if op.f == "add":
                group = tuple(op.value)
                if op.type == INVOKE:
                    invokes.add(group)
                elif op.type == FAIL:
                    fails.add(group)
            elif op.f == "read" and op.type == OK:
                ok_reads.append(op)
        adds = invokes - fails
        idx = {}
        for group in adds:
            xs = frozenset(group)
            for x in xs:
                if x in idx:
                    return {
                        "valid?": "unknown",
                        "error": f"element {x} added by two groups",
                    }
                idx[x] = xs
        errs = []
        for op in ok_reads:
            vals = list(op.value or [])
            read = set(vals)
            if len(vals) != len(read):
                errs.append({"op-index": op.index,
                             "errors": ["duplicate-items"]})
                continue
            op_errs = []
            while read:
                e = next(iter(read))
                group = idx.get(e)
                if group is None:
                    # not in any possibly-successful add: either a
                    # phantom value or a definitely-failed add showing
                    # up anyway (the reference's invokes-minus-fails
                    # index makes these unaccountable; reporting them
                    # beats passing them)
                    op_errs.append({"unexpected": e})
                    read = read - {e}
                    continue
                if not group <= read:
                    op_errs.append({
                        "expected": sorted(group),
                        "found": sorted(read & group),
                    })
                read = read - group
            if op_errs:
                errs.append({"op-index": op.index, "errors": op_errs})
        return {
            "valid?": not errs,
            "ok-read-count": len(ok_reads),
            "error-count": len(errs),
            "first-error": errs[0] if errs else None,
        }


def pages_workload(opts: Optional[dict] = None) -> dict:
    """Group adds mixed 4:1 with reads, lifted over independent keys.
    (reference: pages.clj:143-169 workload — group-size 4, limit 256,
    stagger 1/5; limits scaled by opts for short runs)"""
    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))
    group_size = int(opts.get("group-size", 4))
    per_key = int(opts.get("per-key-limit", 64))
    value_range = int(opts.get("value-range", 10_000))

    def fgen(k):
        vals = list(range(-value_range, value_range))
        gen_mod.rng.shuffle(vals)
        groups = [
            vals[i : i + group_size]
            for i in range(0, len(vals), group_size)
        ]
        it = iter(groups)

        def g(test, ctx):
            if gen_mod.rng.random() < 0.8:
                try:
                    return {"type": "invoke", "f": "add",
                            "value": next(it)}
                except StopIteration:
                    pass
            return {"type": "invoke", "f": "read", "value": None}

        return gen_mod.limit(
            per_key, gen_mod.stagger(1 / 50, g)
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(PagesChecker()),
        "concurrency": 2 * n,
    }


# ---------------------------------------------------------------------
# monotonic workload (reference: faunadb/src/jepsen/faunadb/monotonic.clj)
# ---------------------------------------------------------------------

REGISTERS_CLASS = "registers"
MONO_KEY = 0


class FaunaMonotonicClient(FaunaClient):
    """A single incrementing register queried with Time() stamps and
    At() temporal reads.

    Reference: monotonic.clj:84-146 — inc returns [ts, old-value] via an
    if/exists/create-or-update transaction; read returns [ts, value];
    read-at evaluates the read At() a (jittered) past timestamp."""

    def setup(self, test):
        try:
            self.query({"create_class": {"object": {"name": REGISTERS_CLASS}}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        r = {"@ref": f"classes/{REGISTERS_CLASS}/{MONO_KEY}"}
        sel = {"select": ["data", "value"], "from": {"get": r},
               "default": 0}
        try:
            if op["f"] == "inc":
                res = self.query([
                    {"time": "now"},
                    {"if": {"exists": r},
                     # old value first, then the increment — list exprs
                     # evaluate in order inside one transaction
                     "then": [sel,
                              {"update": r,
                               "params": {"object": {"data": {"object": {
                                   "value": {"add": [sel, 1]}}}}}}],
                     "else": [{"create": r,
                               "params": {"object": {"data": {"object": {
                                   "value": 1}}}}},
                              0]},
                ])
                ts, branch = res
                v = next(x for x in branch if isinstance(x, int))
                return {**op, "type": "ok", "value": [ts, v]}
            if op["f"] == "read":
                res = self.query([
                    {"time": "now"},
                    {"if": {"exists": r}, "then": sel, "else": 0},
                ])
                return {**op, "type": "ok", "value": [res[0], res[1]]}
            if op["f"] == "read-at":
                ts = (op.get("value") or [None, None])[0]
                if ts is None:
                    now = self.query({"time": "now"})
                    # jitter a few ticks into the past
                    # (reference: f/jitter-time, monotonic.clj:115-119)
                    import random as _random

                    ts = f"{max(1, int(now) - _random.randint(0, 4)):012d}"
                v = self.query({
                    "at": ts,
                    "expr": {"if": {"exists": r}, "then": sel, "else": 0},
                })
                return {**op, "type": "ok", "value": [ts, v]}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            if "not found" in str(e.body):
                return {**op, "type": "fail", "error": "not-found"}
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def _non_monotonic_pairs_by_process(extract, history):
    """(reference: monotonic.clj:152-173)"""
    from ..history import OK

    last: dict = {}
    errs = []
    for op in history:
        if op.type != OK:
            continue
        p = op.process
        v = extract(op)
        lv = extract(last[p]) if p in last else None
        if lv is not None and lv > v:
            errs.append([last[p].index, op.index])
        last[p] = op
    return errs


class MonotonicChecker(checker_mod.Checker):
    """Per-process monotonic values and timestamps over inc/read ops.
    (reference: monotonic.clj:175-193 checker)"""

    def check(self, test, history, opts=None):
        hist = [op for op in history if op.f in ("inc", "read")]
        value_errs = _non_monotonic_pairs_by_process(
            lambda op: op.value[1], hist
        )
        ts_errs = _non_monotonic_pairs_by_process(
            lambda op: op.value[0], hist
        )
        return {
            "valid?": not (value_errs or ts_errs),
            "value-errors": value_errs[:10],
            "ts-errors": ts_errs[:10],
        }


class TimestampValueChecker(checker_mod.Checker):
    """Globally: sorted by timestamp, values never decrease.
    (reference: monotonic.clj:195-218 timestamp-value-checker)"""

    def check(self, test, history, opts=None):
        from ..history import OK

        ops = sorted(
            (op for op in history
             if op.type == OK and op.f in ("read-at", "inc")),
            key=lambda op: op.value[0],
        )
        errs = [
            [a.index, b.index]
            for a, b in zip(ops, ops[1:])
            if a.value[1] > b.value[1]
        ]
        return {"valid?": not errs, "errors": errs[:10]}


class NotFoundChecker(checker_mod.Checker):
    """Existence is checked inside every transaction, so a not-found
    failure is itself a bug.  (reference: monotonic.clj:335-347)"""

    def check(self, test, history, opts=None):
        from ..history import FAIL

        errs = [
            op.index
            for op in history
            if op.type == FAIL and op.error == "not-found"
        ]
        return {"valid?": not errs, "error-count": len(errs),
                "first": errs[0] if errs else None}


class _MonotonicPlotter(checker_mod.Checker):
    """Register value over DB timestamps, one series per process — the
    SVG stand-in for the reference's gnuplot timestamp-value plot
    (monotonic.clj:246-292)."""

    def check(self, test, history, opts=None):
        from ..checker import perf
        from ..history import OK

        series: dict = {}
        for op in history:
            if op.type == OK and op.f in ("inc", "read", "read-at"):
                series.setdefault(op.process, []).append(
                    (int(op.value[0]), op.value[1])
                )
        if not any(series.values()):
            return {"valid?": True}
        perf.scatter_plot(
            test,
            series,
            path_components=list((opts or {}).get("subdirectory", []))
            + ["monotonic.svg"],
            title=f"{test.get('name', 'test')} value by timestamp",
            ylabel="register value",
            history=history,
        )
        return {"valid?": True}


def monotonic_workload(opts: Optional[dict] = None) -> dict:
    """(reference: monotonic.clj:349-372 workload; the :events final
    generator is omitted — the reference marks Fauna's event-history
    traversal as broken, monotonic.clj:130-131)"""
    from .. import generator as gen_mod

    def inc_gen(test, ctx):
        return {"type": "invoke", "f": "inc", "value": None}

    def read_gen(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def read_at_gen(test, ctx):
        return {"type": "invoke", "f": "read-at", "value": [None, None]}

    return {
        "generator": gen_mod.mix([inc_gen, read_gen, read_at_gen]),
        "checker": checker_mod.compose({
            "monotonic": MonotonicChecker(),
            "not-found": NotFoundChecker(),
            "timestamp-value": TimestampValueChecker(),
            "timestamp-value-plot": _MonotonicPlotter(),
        }),
    }


# ---------------------------------------------------------------------
# bank workload (reference: faunadb/bank.clj)
# ---------------------------------------------------------------------

ACCOUNTS_CLASS = "accounts"
IDX_ALL_ACCOUNTS = "all_accounts"


class FaunaBankClient(FaunaClient):
    """Bank transfers as single FQL transactions (reference:
    faunadb/bank.clj:69-137): a transfer debits the source inside one
    query that aborts when the balance would go negative, deletes
    drained accounts (writes 0 with ``fixed-instances``), and creates
    the destination on demand; reads fetch every account's balance in
    one transaction."""

    def _acct(self, i):
        return {"@ref": f"classes/{ACCOUNTS_CLASS}/{i}"}

    def _opt(self, test, key):
        """Client opts win; fall back to the test map (build_test merges
        workload keys but not arbitrary suite opts)."""
        if key in self.opts:
            return self.opts[key]
        return (test or {}).get(key)

    def _balance(self, i, default=None):
        return {"select": ["data", "balance"],
                "from": {"get": self._acct(i)}, "default": default}

    def setup(self, test):
        try:
            self.query(
                {"create_class": {"object": {"name": ACCOUNTS_CLASS}}}
            )
        except (HttpError, IndeterminateError):
            pass
        # the whole total starts in the first account (bank.clj:47-66)
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        first = self._acct(accounts[0])
        try:
            self.query({
                "if": {"exists": first},
                "then": None,
                "else": {"create": first,
                         "params": {"object": {"data": {"object": {
                             "balance": total}}}}},
            })
            if self._opt(test, "fixed-instances"):
                for i in accounts[1:]:
                    r = self._acct(i)
                    self.query({
                        "if": {"exists": r},
                        "then": {"update": r,
                                 "params": {"object": {"data": {"object": {
                                     "balance": 0}}}}},
                        "else": {"create": r,
                                 "params": {"object": {"data": {"object": {
                                     "balance": 0}}}}},
                    })
        except (HttpError, IndeterminateError):
            pass

    def _read_expr(self, test):
        return [
            {"if": {"exists": self._acct(i)},
             "then": [i, self._balance(i)],
             "else": None}
            for i in test.get("accounts", list(range(8)))
        ]

    def _transfer_expr(self, test, value):
        frm, to, amount = value["from"], value["to"], value["amount"]
        debited = {"subtract": [
            {"if": {"exists": self._acct(frm)},
             "then": self._balance(frm, 0), "else": 0},
            amount,
        ]}
        if self._opt(test, "fixed-instances"):
            drained = {"update": self._acct(frm),
                       "params": {"object": {"data": {"object": {
                           "balance": 0}}}}}
        else:
            drained = {"delete": self._acct(frm)}
        debit = {
            "if": {"lt": [debited, 0]},
            "then": {"abort": "balance would go negative"},
            "else": {
                "if": {"equals": [debited, 0]},
                "then": drained,
                "else": {"update": self._acct(frm),
                         "params": {"object": {"data": {"object": {
                             "balance": debited}}}}},
            },
        }
        credit = {
            "if": {"exists": self._acct(to)},
            "then": {"update": self._acct(to),
                     "params": {"object": {"data": {"object": {
                         "balance": {"add": [self._balance(to, 0),
                                             amount]}}}}}},
            "else": {"create": self._acct(to),
                     "params": {"object": {"data": {"object": {
                         "balance": amount}}}}},
        }
        return {"do": [debit, credit]}

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                rows = self.query(self._read_expr(test))
                balances = {r[0]: r[1] for r in rows if r is not None}
                return {**op, "type": "ok", "value": balances}
            if op["f"] == "transfer":
                self.query(self._transfer_expr(test, op["value"]))
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            if "would go negative" in str(e.body):
                return {**op, "type": "fail", "error": "negative"}
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class FaunaBankIndexClient(FaunaBankClient):
    """Bank reads through a covering index over every account instead
    of per-ref gets (reference: bank.clj:139-171 IndexClient)."""

    def setup(self, test):
        super().setup(test)
        try:
            self.query({"create_index": {"object": {
                "name": IDX_ALL_ACCOUNTS,
                "source": class_ref(ACCOUNTS_CLASS),
                "active": True,
                "serialized": bool(self._opt(test, "serialized-indices")),
                "values": [{"field": ["ref"]},
                           {"field": ["data", "balance"]}],
            }}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        if op["f"] != "read":
            return super().invoke(test, op)
        try:
            out = self.query(
                {"paginate": {"match": {"index": IDX_ALL_ACCOUNTS}}}
            )
            balances = {}
            for ref_map, balance in (out or {}).get("data", []):
                id_ = ref_map["@ref"].rsplit("/", 1)[-1]
                balances[int(id_)] = balance
            return {**op, "type": "ok", "value": balances}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def bank_workload(opts: Optional[dict] = None) -> dict:
    """(reference: bank.clj:173-187 workload/index-workload; the
    generic balance-invariant generator/checker come from
    workloads.bank, staggered like the reference's 1/10s delay)"""
    from .. import generator as gen_mod
    from ..workloads import bank as bank_mod

    opts = dict(opts or {})
    w = bank_mod.test(opts)
    if "rate" not in opts:
        # the reference paces fauna bank ops at ~10/s (bank.clj:177-180);
        # suite runs with an explicit rate are throttled by build_test
        w["generator"] = gen_mod.stagger(0.1, w["generator"])
    return w


# ---------------------------------------------------------------------
# set workload (reference: faunadb/set.clj)
# ---------------------------------------------------------------------

ELEMENTS_CLASS = "elements"
SIDE_EFFECTS_CLASS = "side-effects"
IDX_ALL_ELEMENTS = "all-elements"


class FaunaSetClient(FaunaClient):
    """Unique-element inserts + full index reads (reference:
    set.clj:19-64).  With ``strong-read`` the read transaction also
    performs a throwaway write, upgrading it from a snapshot index read
    to a strict-serializable read-write transaction (set.clj:47-56)."""

    def setup(self, test):
        try:
            for cls in (ELEMENTS_CLASS, SIDE_EFFECTS_CLASS):
                self.query({"create_class": {"object": {"name": cls}}})
            self.query({"create_index": {"object": {
                "name": IDX_ALL_ELEMENTS,
                "source": class_ref(ELEMENTS_CLASS),
                "active": True,
                "serialized": bool(self.opts.get("serialized-indices")),
                "values": [{"field": ["data", "value"]}],
            }}})
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                v = op["value"]
                self.query({
                    "create": {"@ref": f"classes/{ELEMENTS_CLASS}/{v}"},
                    "params": {"object": {"data": {"object": {"value": v}}}},
                })
                return {**op, "type": "ok"}
            if op["f"] == "read":
                read = {"paginate": {"match": {"index": IDX_ALL_ELEMENTS}}}
                if self.opts.get("strong-read"):
                    # the write rides the same transaction; `do` returns
                    # its last expression, the index read
                    read = {"do": [
                        {"create": {"@ref": f"classes/{SIDE_EFFECTS_CLASS}"},
                         "params": {"object": {"data": {"object": {}}}}},
                        read,
                    ]}
                out = self.query(read)
                vals = sorted(
                    v for v in (out or {}).get("data", []) if v is not None
                )
                return {**op, "type": "ok", "value": vals}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def set_workload(opts: Optional[dict] = None) -> dict:
    """(reference: set.clj:66-96 workload: mixed unique adds + index
    reads, a final read, and set-full — linearizable only when both
    strong reads and serialized indices are on)"""
    from .. import generator as gen_mod

    opts = dict(opts or {})
    counter = {"n": 0}

    def add(test, ctx):
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": counter["n"]}

    def read(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    return {
        "generator": gen_mod.stagger(1 / 5, gen_mod.mix([add, read])),
        "final-generator": gen_mod.once(
            {"type": "invoke", "f": "read", "value": None}
        ),
        "checker": checker_mod.set_full(
            linearizable=bool(
                opts.get("strong-read") and opts.get("serialized-indices")
            )
        ),
    }


# ---------------------------------------------------------------------
# multimonotonic workload (reference: faunadb/multimonotonic.clj)
# ---------------------------------------------------------------------

REGISTERS_CLASS = "registers"


class FaunaMultiMonotonicClient(FaunaClient):
    """Blind single-writer increments + timestamped multi-key reads
    (reference: multimonotonic.clj:76-110).  Writes upsert {k: v} maps
    without reading (no OCC read locks); reads fetch a set of registers
    plus the transaction time, returning
    ``{"ts": ..., "registers": {k: {"ts": ..., "value": ...}}}``."""

    def _reg(self, k):
        return {"@ref": f"classes/{REGISTERS_CLASS}/{k}"}

    def setup(self, test):
        try:
            self.query(
                {"create_class": {"object": {"name": REGISTERS_CLASS}}}
            )
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        try:
            if op["f"] == "write":
                upserts = []
                for k, v in dict(op["value"]).items():
                    r = self._reg(k)
                    params = {"object": {"data": {"object": {"value": v}}}}
                    upserts.append({
                        "if": {"exists": r},
                        "then": {"update": r, "params": params},
                        "else": {"create": r, "params": params},
                    })
                self.query({"do": upserts})
                return {**op, "type": "ok"}
            if op["f"] == "read":
                ks = list(op["value"] or [])
                ts, instances = self.query([
                    {"time": "now"},
                    [
                        {"if": {"exists": self._reg(k)},
                         "then": {"get": self._reg(k)}, "else": None}
                        for k in ks
                    ],
                ])
                registers = {}
                for k, inst in zip(ks, instances):
                    if inst is not None:
                        registers[k] = {
                            "ts": inst.get("ts"),
                            "value": inst.get("data", {}).get("value"),
                        }
                return {**op, "type": "ok",
                        "value": {"ts": ts, "registers": registers}}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def _mm_read_state(op) -> dict:
    regs = (op.value or {}).get("registers", {})
    return {k: r.get("value") for k, r in regs.items()
            if r.get("value") is not None}


def _mm_observation(op, k) -> dict:
    """What a read op observed for key k (multimonotonic.clj:164-177)."""
    reg = (op.value or {}).get("registers", {}).get(k, {})
    return {
        "read-ts": (op.value or {}).get("ts"),
        "ts": reg.get("ts"),
        "value": reg.get("value"),
        "op-index": op.index,
    }


class TsOrderChecker(checker_mod.Checker):
    """Replays ok reads in read-timestamp order, tracking the highest
    observed value per register; a later-timestamped read showing a
    LOWER value for an increment-only register proves the timestamp
    order is inconsistent with the data (reference:
    multimonotonic.clj:253-273 + nonmonotonic-states:181-244)."""

    def check(self, test, history, opts=None):
        from ..history import OK

        reads = [
            op for op in history
            if op.type == OK and op.f == "read"
            and isinstance(op.value, dict) and op.value.get("ts")
        ]
        reads.sort(key=lambda o: str(o.value["ts"]))
        inferred: dict = {}  # k -> observation with the highest value
        errors = []
        for op in reads:
            state = _mm_read_state(op)
            nm = {
                k: v for k, v in state.items()
                if k in inferred and v < inferred[k]["value"]
            }
            if nm:
                errors.append({
                    "inferred": {
                        k: inferred[k]["value"]
                        for k in state if k in inferred
                    },
                    "observed": state,
                    "op-index": op.index,
                    "errors": {
                        k: [inferred[k], _mm_observation(op, k)]
                        for k in sorted(nm, key=str)
                    },
                })
            for k, v in state.items():
                if k not in inferred or v >= inferred[k]["value"]:
                    inferred[k] = _mm_observation(op, k)
        return {"valid?": not errors, "errors": errors}


class ReadSkewChecker(checker_mod.Checker):
    """Read skew over increment-only registers as cycle detection: for
    each register k, order reads by their observed value of k (edges to
    the next-greater value); union the per-key orders and hunt for
    cycles — a cycle means two reads disagree about time's arrow across
    two registers.  The reference describes exactly this construction
    but ships it unimplemented (multimonotonic.clj:274-313 returns
    valid? true unconditionally); here it runs for real on the shared
    SCC machinery (elle.graph)."""

    def check(self, test, history, opts=None):
        from ..elle.graph import (
            Graph,
            find_cycle,
            strongly_connected_components,
        )
        from ..history import OK

        reads = [
            op for op in history
            if op.type == OK and op.f == "read"
            and isinstance(op.value, dict)
        ]
        by_key: dict = {}  # k -> value -> [op indices]
        states = {}
        for op in reads:
            state = _mm_read_state(op)
            states[op.index] = state
            for k, v in state.items():
                by_key.setdefault(k, {}).setdefault(v, []).append(op.index)
        g = Graph()
        for k, val_map in by_key.items():
            vals = sorted(val_map)
            for lo, hi in zip(vals, vals[1:]):
                for a in val_map[lo]:
                    for b in val_map[hi]:
                        g.add_edge(a, b, f"k{k}")
        errors = []
        for scc in strongly_connected_components(g):
            if len(scc) < 2:
                continue
            cyc = find_cycle(g, list(scc))
            if cyc is None:
                continue
            errors.append({
                "cycle": [
                    {"op-index": a,
                     "state": states.get(a, {}),
                     "rels": sorted(g.edge_rels(a, b))}
                    for a, b in zip(cyc, cyc[1:])
                ],
            })
        return {"valid?": not errors, "read-skew": errors}


class _MultiMonoWrites(gen_base.Generator):
    """Per-thread blind-increment write generator: the key IS the
    executing process id, so no key ever sees concurrent updates and a
    crash (fresh process) naturally rotates to a fresh key (reference:
    multimonotonic.clj:315-333, which likewise derives keys from
    process ids).  Each thread's instance also registers its current
    key so readers know the active key set — the reference keeps the
    same registry in an atom."""

    def __init__(self, active: dict, k=None, v=0):
        self.active = active
        self.k = k
        self.v = v

    def op(self, test, ctx):
        from .. import generator as gen_mod

        free = gen_mod.free_threads(ctx)
        if not free:
            return (gen_mod.PENDING, self)
        t = free[0]
        p = gen_mod.thread_to_process(ctx, t)
        v2 = self.v + 1 if p == self.k else 0
        self.active[t] = p
        op = gen_mod.fill_in_op(
            {"f": "write", "value": {p: v2}, "process": p}, ctx
        )
        return (op, _MultiMonoWrites(self.active, p, v2))

    def update(self, test, ctx, event):
        return self


def multimonotonic_workload(opts: Optional[dict] = None) -> dict:
    """(reference: multimonotonic.clj:335-352 workload: half the
    threads write their own registers blind, half read random subsets;
    ts-order + read-skew checkers)"""
    from .. import generator as gen_mod
    from ..util import random_nonempty_subset

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))
    active: dict = {}

    def reads(test, ctx):
        ks = sorted(set(active.values()))
        value = random_nonempty_subset(ks, gen_mod.rng) if ks else []
        return {"type": "invoke", "f": "read", "value": value}

    writers = max(1, n)
    return {
        "generator": gen_mod.reserve(
            writers, gen_mod.each_thread(_MultiMonoWrites(active)), reads
        ),
        "checker": checker_mod.compose({
            "ts-order": TsOrderChecker(),
            "read-skew": ReadSkewChecker(),
        }),
        "concurrency": 2 * n,
    }
