"""LogCabin (Raft reference implementation) suite.

Reference: logcabin/src/jepsen/logcabin.clj — build LogCabin from
source with scons (:30-45), bootstrap the Raft log on node 1
(:76-83), start daemons with per-node server ids (:85-91), grow the
cluster via the ``Reconfigure`` example binary (:100-115), and drive a
CAS register **through the ``TreeOps`` example binary executed on the
nodes over SSH** (:163-207) — LogCabin's client protocol is not a
stable wire format, so the reference shells out, and this suite does
the same through the control DSL.
"""

from __future__ import annotations

import json
from typing import Optional

from .. import client as client_mod
from .. import independent
from .. import control
from ..control import util as cu
from ..control.core import RemoteError
from ..os_setup import debian
from . import common

CONFIG_FILE = "/root/logcabin.conf"  # (reference: logcabin.clj:55-62)
LOG_FILE = "/root/logcabin.log"
PID_FILE = "/root/logcabin.pid"
STORE_DIR = "/root/storage"
PORT = 5254
LOGCABIN_BIN = "/root/LogCabin"
RECONFIGURE_BIN = "/root/Reconfigure"
TREEOPS_BIN = "/root/TreeOps"
KEY = "/jepsen"


def server_addrs(test: dict) -> str:
    return ",".join(f"{n}:{PORT}" for n in test["nodes"])


class LogCabinDB(common.DaemonDB):
    logfile = LOG_FILE
    pidfile = PID_FILE
    proc_name = "LogCabin"

    def install(self, test, node):
        # (reference: logcabin.clj:30-45 — scons build from git)
        debian.install(["git-core", "build-essential", "scons",
                        "protobuf-compiler", "libprotobuf-dev",
                        "libcrypto++-dev"])
        with control.su():
            control.execute(
                "bash", "-c",
                "test -d /logcabin || git clone --depth 1 "
                "https://github.com/logcabin/logcabin.git /logcabin",
            )
            with control.cd("/logcabin"):
                control.execute("git", "submodule", "update", "--init",
                                check=False)
                control.execute("scons", check=False)
            for b in ("LogCabin", "Examples/Reconfigure", "Examples/TreeOps"):
                control.execute("cp", "-f", f"/logcabin/build/{b}", "/root",
                                check=False)

    def configure(self, test, node):
        # (reference: logcabin.clj:64-74)
        sid = test["nodes"].index(node) + 1
        with control.su():
            cu.write_file(
                f"serverId = {sid}\nlistenAddresses = {node}:{PORT}\n",
                CONFIG_FILE,
            )

    def start(self, test, node):
        with control.su(), control.cd("/root"):
            if node == test["nodes"][0] and not cu.exists(STORE_DIR):
                # (reference: logcabin.clj:76-83 bootstrap!)
                control.execute(LOGCABIN_BIN, "-c", CONFIG_FILE,
                                "-l", LOG_FILE, "--bootstrap")
            control.execute(LOGCABIN_BIN, "-c", CONFIG_FILE, "-d",
                            "-l", LOG_FILE, "-p", PID_FILE)

    def setup(self, test, node):
        super().setup(test, node)
        if node == test["nodes"][0]:
            # grow the cluster to all nodes (reference: :100-115)
            with control.su(), control.cd("/root"):
                control.execute(
                    RECONFIGURE_BIN, "-c", server_addrs(test), "set",
                    *[f"{n}:{PORT}" for n in test["nodes"]], check=False,
                )

    def kill(self, test, node):
        cu.grepkill("LogCabin")
        control.execute("rm", "-f", PID_FILE, check=False)

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=120)

    def wipe(self, test, node):
        with control.su():
            control.execute("rm", "-rf", STORE_DIR, check=False)


class LogCabinClient(client_mod.Client):
    """CAS register through TreeOps on the node
    (reference: logcabin.clj:163-237 CASClient)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.node = None
        self.test = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.node = node
        c.test = test
        return c

    def _treeops(self, *args: str, stdin: Optional[str] = None) -> str:
        def run():
            with control.su(), control.cd("/root"):
                res = control.execute(
                    TREEOPS_BIN, "-c", server_addrs(self.test),
                    "-q", *args, stdin=stdin,
                )
                return res.out if hasattr(res, "out") else str(res)

        return control.with_node(self.node, run)

    def invoke(self, test, op):
        k, v = op["value"]
        path = f"{KEY}-{k}"
        try:
            if op["f"] == "read":
                out = self._treeops("read", path)
                val = json.loads(out) if out.strip() else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                self._treeops("write", path, stdin=json.dumps(v))
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                self._treeops(
                    "write", path, "-p", f"{path}:{json.dumps(old)}",
                    stdin=json.dumps(new),
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except RemoteError as e:
            msg = str(e)
            if "timed out" in msg.lower() or "timeout" in msg.lower():
                return {**op, "type": "info", "error": msg}
            return {**op, "type": "fail", "error": msg}

    def close(self, test):
        pass


def db(opts: Optional[dict] = None):
    return LogCabinDB(opts)


def client(opts: Optional[dict] = None):
    return LogCabinClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"register": common.register_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)["register"]
    return common.build_test(
        "logcabin-register", opts, db=LogCabinDB(opts),
        client=LogCabinClient(opts), workload=w,
    )
