"""PostgreSQL-RDS suite.

Reference: postgres-rds/src/jepsen/dirty_read.clj — unlike every other
suite, the database is an *externally managed* RDS endpoint: there is
no DB automation at all (the DB is a noop), and every client connects
to the one endpoint given by ``--endpoint`` rather than to its own
node.  The workload probes READ COMMITTED dirty reads: writers insert
rows in transactions, readers select, and a final read determines which
writes are visible.

Clients speak pgwire via :mod:`.sql` (dialect ``pg``); the endpoint is
passed as ``opts["host"]`` (every node maps to the same endpoint,
matching the reference's single-endpoint topology).
"""

from __future__ import annotations

from typing import Optional

from .. import db as db_mod
from . import common, sql

PORT = 5432


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "pg")
    o.setdefault("port", PORT)
    o.setdefault("user", "postgres")
    if o.get("endpoint"):
        o.setdefault("host", o["endpoint"])
    return o


def db(opts: Optional[dict] = None):
    """RDS is managed; nothing to install.  (reference:
    postgres-rds has no db.clj — the endpoint is a CLI param)"""
    return db_mod.noop()


def client(opts: Optional[dict] = None):
    return sql.SetClient(_opts(opts))


WORKLOADS = ("set", "register", "bank", "list-append")


def workloads(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    return {w: common.generic_workload(w, opts) for w in WORKLOADS}


def test(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    wname = opts.get("workload", "set")
    w = workloads(opts)[wname]
    return common.build_test(
        f"postgres-rds-{wname}", opts, db=db(opts),
        client=sql.client_for(wname, opts), workload=w,
    )
