"""Dirty-read probe for the MySQL-replication suites (Galera,
Percona XtraDB).

Writers race to set EVERY row of a table to one unique value inside a
serializable transaction (read-all in random order, then update-all);
readers read all rows at once.  Two anomalies fall out: a read whose
rows disagree (the writer's txn was seen half-applied) and a read
containing a *failed* writer's value (the dirty read proper).

Reference: galera/src/jepsen/galera/dirty_reads.clj:28-120 and its
namespace-for-namespace twin percona/src/jepsen/percona/dirty_reads.clj
— client (:28-67: n rows seeded to -1, read-all / write-everything
transactions), checker (:73-96: inconsistent-reads = rows disagree,
dirty-reads = failed write visible), generator (:98-105: reads mixed
with sequentially-numbered writes).
"""

from __future__ import annotations

from typing import Optional

from .. import generator as gen
from ..checker import Checker
from ..history import FAIL, OK
from . import sql

N_ROWS = 10  # rows per table (reference passes n per-test; 10 typical)


class DirtyReadsClient(sql._Base):
    """(reference: dirty_reads.clj:28-67 Client)"""

    dialect = "mysql"

    def __init__(self, opts: Optional[dict] = None):
        import random as _random

        super().__init__(opts)
        self.n = int(self.opts.get("rows", N_ROWS))
        # private rng: worker threads must not race the seeded module
        # rng the scheduler draws deterministic schedules from
        self.rng = _random.Random()

    def setup(self, test):
        self._exec_ddl(
            "CREATE TABLE IF NOT EXISTS dirty "
            "(id INT NOT NULL PRIMARY KEY, x BIGINT NOT NULL)"
        )
        for i in range(self.n):
            try:
                self.conn.query(
                    f"INSERT INTO dirty (id, x) VALUES ({i}, -1)"
                )
            except (sql.PgError, sql.MysqlError):
                pass  # another client seeded this row

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                res = self.conn.query("SELECT x FROM dirty ORDER BY id")
                return {**op, "type": "ok",
                        "value": [int(r[0]) for r in res.rows]}
            if op["f"] == "write":
                x = int(op["value"])
                order = list(range(self.n))
                self.rng.shuffle(order)
                self.conn.query("BEGIN")
                try:
                    for i in order:
                        self.conn.query(
                            f"SELECT x FROM dirty WHERE id = {i}"
                        )
                    for i in order:
                        self.conn.query(
                            f"UPDATE dirty SET x = {x} WHERE id = {i}"
                        )
                    self.conn.query("COMMIT")
                    return {**op, "type": "ok"}
                except (sql.PgError, sql.MysqlError) as e:
                    try:
                        self.conn.query("ROLLBACK")
                    except Exception:  # noqa: BLE001
                        pass
                    return self._fail(op, e)
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


class DirtyReadsChecker(Checker):
    """A failed write's value must never be read; every read must be
    internally uniform (reference: dirty_reads.clj:73-96)."""

    def check(self, test, history, opts=None):
        failed_writes = {
            op.value for op in history
            if op.type == FAIL and op.f == "write"
        }
        reads = [op.value for op in history
                 if op.type == OK and op.f == "read" and op.value]
        inconsistent = [r for r in reads if len(set(r)) > 1]
        filthy = [r for r in reads
                  if any(x in failed_writes for x in r)]
        return {
            "valid?": not filthy,
            "inconsistent-reads": inconsistent[:10],
            "dirty-reads": filthy[:10],
        }


class _Writes(gen.Generator):
    """Sequentially numbered writes (reference: dirty_reads.clj:100-105
    — an infinite seq over (range))."""

    def __init__(self, i: int = 0):
        self.i = i

    def op(self, test, ctx):
        return (
            gen.fill_in_op({"f": "write", "value": self.i}, ctx),
            _Writes(self.i + 1),
        )

    def update(self, test, ctx, event):
        return self


def workload(opts: Optional[dict] = None) -> dict:
    """(reference: dirty_reads.clj:107-120 test-)"""
    return {
        "generator": gen.mix([
            gen.repeat({"f": "read", "value": None}),
            _Writes(),
        ]),
        "checker": DirtyReadsChecker(),
    }
