"""Monotonic-inserts workload for the SQL suites.

Clients insert successive integer values tagged with a database-assigned
transaction timestamp, spread over several tables; a final read returns
every row ordered by that timestamp.  The checker verifies that the
timestamp order agrees with insertion order (globally and per process /
per table), and accounts for lost, duplicated, revived (failed-but-seen)
and recovered (indeterminate-but-seen) rows.

Reference: cockroachdb/src/jepsen/cockroach/monotonic.clj:32-248 — the
client creates per-key tables and inserts (val, sts, node, process, tb)
rows with ``cluster_logical_timestamp()``; check-monotonic computes
off-order pairs, lost/dup/revived/recovered sets.  This implementation
is dialect-generic (cockroach / pg / mysql timestamp expressions) so the
same workload runs on cockroachdb, tidb, stolon, and yugabyte-ysql.
"""

from __future__ import annotations

from decimal import Decimal
from typing import Any, Dict, List, Optional

from .. import generator as gen
from ..checker import Checker
from ..history import OK, FAIL, INFO
from . import sql

#: timestamp expression per dialect.  Only cockroach's
#: cluster_logical_timestamp() is a *commit* timestamp monotone with
#: commit order (the property the reference relies on,
#: monotonic.clj:81-96); pg's clock_timestamp() and mysql's now(6) are
#: wall-clock *statement* time — two concurrent txns can commit in
#: reverse wall-clock order on a perfectly correct DB, so those
#: dialects only support the per-process / per-table ordering checks.
TS_EXPR = {
    "cockroach": "cluster_logical_timestamp()",
    "pg": "extract(epoch from clock_timestamp())",
    "mysql": "unix_timestamp(now(6))",
}

#: dialects whose TS_EXPR is a real commit timestamp: the global
#: timestamp-vs-value ordering check is only sound on these
COMMIT_ORDERED_DIALECTS = {"cockroach"}

TABLE_COUNT = 2


def table_name(i: int) -> str:
    return f"mono{i}"


class MonotonicClient(sql._Base):
    """Insert sequential values with DB timestamps; final read returns
    all rows ordered by timestamp.  (reference: monotonic.clj:81-145)"""

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.table_count = int(self.opts.get("table-count", TABLE_COUNT))

    def setup(self, test):
        self._exec_ddl(
            *(
                f"CREATE TABLE IF NOT EXISTS {table_name(i)} "
                "(val INT, sts TEXT, proc INT, tb INT)"
                for i in range(self.table_count)
            )
        )

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                v = int(op["value"])
                tb = v % self.table_count
                ts = TS_EXPR[self.dialect if self.dialect in TS_EXPR else "pg"]
                # mysql spells string casts CHAR, everyone else TEXT
                txt = "CHAR" if self.dialect == "mysql" else "TEXT"
                proc = op.get("process", -1)
                proc = proc if isinstance(proc, int) else -1
                self.conn.query(
                    f"INSERT INTO {table_name(tb)} (val, sts, proc, tb) "
                    f"VALUES ({v}, CAST({ts} AS {txt}), {proc}, {tb})"
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                union = " UNION ALL ".join(
                    f"SELECT val, sts, proc, tb FROM {table_name(i)}"
                    for i in range(self.table_count)
                )
                # bare DECIMAL is DECIMAL(10,0) on mysql — keep the
                # fractional digits or sub-second reorders vanish
                dec = (
                    "DECIMAL(30,10)" if self.dialect == "mysql" else "DECIMAL"
                )
                res = self.conn.query(
                    f"SELECT val, sts, proc, tb FROM ({union}) AS u "
                    f"ORDER BY CAST(sts AS {dec}), val"
                )
                out = [
                    [int(r[0]), str(r[1]), int(r[2]), int(r[3])]
                    for r in res.rows
                ]
                return {**op, "type": "ok", "value": out}
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


def _non_monotonic(rows: List[list], field: int, strict: bool) -> List[list]:
    """Successive row pairs where ``field`` fails to increase
    ((reference: monotonic.clj:147-154 non-monotonic)."""
    bad = []
    for x, y in zip(rows, rows[1:]):
        a, b = x[field], y[field]
        if field == 1:  # timestamps compare numerically
            a, b = Decimal(a), Decimal(b)
        ok = a < b if strict else a <= b
        if not ok:
            bad.append([x, y])
    return bad


def _non_monotonic_by(rows: List[list], group: int, field: int) -> Dict[Any, list]:
    """(reference: monotonic.clj:156-164 non-monotonic-by)"""
    groups: Dict[Any, List[list]] = {}
    for r in rows:
        groups.setdefault(r[group], []).append(r)
    return {
        k: _non_monotonic(sub, field, strict=True)
        for k, sub in sorted(groups.items())
        if _non_monotonic(sub, field, strict=True)
    }


class MonotonicChecker(Checker):
    """(reference: monotonic.clj:166-248 check-monotonic)"""

    def __init__(self, use_global: bool = False):
        self.use_global = use_global

    def check(self, test, history, opts=None):
        adds, fails, infos = set(), set(), set()
        final = None
        for op in history:
            if op.f == "add":
                if op.type == OK:
                    adds.add(op.value)
                elif op.type == FAIL:
                    fails.add(op.value)
                elif op.type == INFO:
                    infos.add(op.value)
            elif op.f == "read" and op.type == OK:
                final = op.value
        if final is None:
            return {"valid?": "unknown", "error": "set was never read"}

        from collections import Counter

        vals = [r[0] for r in final]
        counts = Counter(vals)
        seen = set(counts)
        dups = sorted(v for v, c in counts.items() if c > 1)
        lost = sorted(adds - seen)
        revived = sorted(seen & fails)
        recovered = sorted(seen & infos)
        off_order_sts = _non_monotonic(final, 1, strict=False)
        off_order_vals = _non_monotonic(final, 0, strict=True)
        per_proc = _non_monotonic_by(final, 2, 0)
        per_table = _non_monotonic_by(final, 3, 0)
        valid = (
            not lost
            and not dups
            and not revived
            and not off_order_sts
            and not per_proc
            and (not off_order_vals if self.use_global else True)
        )
        return {
            "valid?": valid,
            "lost": lost,
            "duplicates": dups,
            "revived": revived,
            "recovered": recovered,
            "order-by-errors": off_order_sts,
            "value-reorders": off_order_vals,
            "value-reorders-per-process": per_proc,
            "value-reorders-per-table": per_table,
        }


def workload(opts: Optional[dict] = None) -> dict:
    """add ops with sequential values during the run; one final read.
    The strict global value-order check (``linearizable?``) only engages
    on commit-timestamp dialects (:data:`COMMIT_ORDERED_DIALECTS`) —
    wall-clock timestamps would produce false reorder findings.
    (reference: monotonic.clj:251-283 test)"""
    opts = dict(opts or {})
    counter = {"n": 0}

    def add(test, ctx):
        v = counter["n"]
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": v}

    final = gen.clients(
        gen.each_thread(
            gen.once({"type": "invoke", "f": "read", "value": None})
        )
    )
    return {
        "generator": add,
        "final-generator": final,
        "checker": MonotonicChecker(
            use_global=(
                bool(opts.get("linearizable?", False))
                and opts.get("dialect", sql._Base.dialect)
                in COMMIT_ORDERED_DIALECTS
            )
        ),
    }
