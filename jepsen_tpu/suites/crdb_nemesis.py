"""CockroachDB fault menu: a *named-bundle* nemesis algebra.

Reference: cockroachdb/src/jepsen/cockroach/nemesis.clj — each nemesis
is a named bundle {name, during-gen, final-gen, client, clocks}
(:26-59 single/double schedules over 5 s delay + 5 s duration), and
``compose`` merges bundles by tagging every op's :f with [name, inner-f]
and routing on the tag (:61-106).  The menu (:108-316): parts (random
halves), startstop/startkill over n nodes, majring, strobe-skews,
a clock-skew ladder (small 100 ms → huge 5 s, the big ones paired with
netem slowdowns via the ``slowing`` wrapper :151-172), the
``restarting`` wrapper that restarts dead cockroach daemons after every
:stop (:174-194), and the range-``split`` nemesis driving
``ALTER TABLE … SPLIT AT`` below the most recently written key
(:270-316).

The double schedule overlaps two *instances* of a fault family
(start1/start2 interleaved), exactly the shape the reference uses for
compound runs.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .. import control
from .. import generator as gen
from .. import net as net_mod
from ..nemesis import (
    Nemesis,
    hammer_time,
    node_start_stopper,
    noop,
    partition_random_halves,
    partition_majorities_ring,
)
from ..nemesis import time as nt

#: seconds between interruptions / duration of one (reference: :19-23)
NEMESIS_DELAY = 5
NEMESIS_DURATION = 5


def no_gen() -> dict:
    return {"during": None, "final": None}


def single_gen() -> dict:
    """delay → start → duration → stop, forever (reference: :31-37)."""
    return {
        "during": gen.cycle([
            gen.sleep(NEMESIS_DELAY),
            {"type": "info", "f": "start"},
            gen.sleep(NEMESIS_DURATION),
            {"type": "info", "f": "stop"},
        ]),
        "final": [{"type": "info", "f": "stop"}],
    }


def double_gen() -> dict:
    """Two overlapping fault instances, alternating which leads
    (reference: :39-59)."""
    half = NEMESIS_DURATION / 2
    return {
        "during": gen.cycle([
            gen.sleep(NEMESIS_DELAY),
            {"type": "info", "f": "start1"},
            gen.sleep(half),
            {"type": "info", "f": "start2"},
            gen.sleep(half),
            {"type": "info", "f": "stop1"},
            gen.sleep(half),
            {"type": "info", "f": "stop2"},
            gen.sleep(NEMESIS_DELAY),
            {"type": "info", "f": "start2"},
            gen.sleep(half),
            {"type": "info", "f": "start1"},
            gen.sleep(half),
            {"type": "info", "f": "stop2"},
            gen.sleep(half),
            {"type": "info", "f": "stop1"},
        ]),
        "final": [{"type": "info", "f": "stop1"},
                  {"type": "info", "f": "stop2"}],
    }


# ---------------------------------------------------------------------
# Wrappers (reference: slowing :151-172, restarting :174-194)
# ---------------------------------------------------------------------


class Slowing(Nemesis):
    """Slow the network by ``dt`` seconds around the wrapped nemesis's
    start/stop window."""

    def __init__(self, nem: Nemesis, dt_s: float):
        self.nem = nem
        self.dt_s = dt_s

    def setup(self, test):
        net_mod_fast(test)
        self.nem = self.nem.setup(test) or self.nem
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if _inner_f(f) == "start":
            net_mod_slow(test, {"mean": self.dt_s * 1000, "variance": 1})
            return self.nem.invoke(test, op)
        if _inner_f(f) == "stop":
            try:
                return self.nem.invoke(test, op)
            finally:
                net_mod_fast(test)
        return self.nem.invoke(test, op)

    def teardown(self, test):
        net_mod_fast(test)
        self.nem.teardown(test)

    def fs(self):
        return self.nem.fs()


class Restarting(Nemesis):
    """After the wrapped nemesis completes a :stop, restart the DB on
    every node (clock faults can wedge cockroach; reference restarts
    via auto/start! :174-194)."""

    def __init__(self, nem: Nemesis, db):
        self.nem = nem
        self.db = db

    def setup(self, test):
        self.nem = self.nem.setup(test) or self.nem
        return self

    def invoke(self, test, op):
        out = self.nem.invoke(test, op)
        if _inner_f(op.get("f")) != "stop":
            return out

        def restart(test, node):
            try:
                self.db.start(test, node)
                return "started"
            except Exception as e:  # noqa: BLE001
                return repr(e)[:120]

        res = control.on_nodes(test, list(test["nodes"]), restart)
        return {**out,
                "value": [out.get("value"),
                          {str(k): str(v) for k, v in res.items()}]}

    def teardown(self, test):
        self.nem.teardown(test)

    def fs(self):
        return self.nem.fs()


def _inner_f(f):
    """A tagged f is (name, inner); untagged is inner."""
    if isinstance(f, (tuple, list)) and len(f) == 2:
        return f[1]
    return f


def net_mod_slow(test, opts):
    net = test.get("net", net_mod.iptables)
    net.slow(test, opts)


def net_mod_fast(test):
    net = test.get("net", net_mod.iptables)
    net.fast(test)


# ---------------------------------------------------------------------
# Clock-fault clients (reference: strobe-time :196-227, bump-time
# :229-255)
# ---------------------------------------------------------------------


class StrobeTime(Nemesis):
    """On :start, strobe the clock between now and delta ms ahead,
    flipping every period ms, for duration s, on every node."""

    def __init__(self, delta_ms, period_ms, duration_s):
        self.delta_ms = delta_ms
        self.period_ms = period_ms
        self.duration_s = duration_s

    def setup(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())
        return self

    def invoke(self, test, op):
        if _inner_f(op.get("f")) != "start":
            return {**op, "type": "info", "value": None}
        res = control.on_nodes(
            test, list(test["nodes"]),
            lambda t, n: nt.strobe_time(
                self.delta_ms, self.period_ms, self.duration_s
            ),
        )
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())

    def fs(self):
        return frozenset({"start", "stop"})


class BumpTime(Nemesis):
    """On :start, bump the clock by dt seconds on a random half of the
    nodes; on :stop, reset every clock."""

    def __init__(self, dt_s: float):
        self.dt_s = dt_s

    def setup(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())
        return self

    def invoke(self, test, op):
        f = _inner_f(op.get("f"))
        if f == "start":
            dt_ms = self.dt_s * 1000

            def act(t, n):
                if gen.rng.random() < 0.5:
                    nt.bump_time(dt_ms)
                    return self.dt_s
                return 0

            res = control.on_nodes(test, list(test["nodes"]), act)
        else:
            res = control.on_nodes(test, list(test["nodes"]),
                                   lambda t, n: nt.reset_time())
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())

    def fs(self):
        return frozenset({"start", "stop"})


# ---------------------------------------------------------------------
# Range-split nemesis (reference: split-nemesis :270-316)
# ---------------------------------------------------------------------


#: (table, key column) the split probe targets per workload — the
#: shared SQL clients' schemas (suites/sql.py)
SPLIT_TABLES = {
    "register": ("registers", "id"),
    "bank": ("accounts", "id"),
    "set": ("sets", "val"),
    "sets": ("sets", "val"),
    "list-append": ("lists", "id"),
}


class SplitNemesis(Nemesis):
    """Perform ``ALTER TABLE … SPLIT AT`` at the most recently written
    key.  Key sources, in order: an optional test-supplied ``keyrange``
    map ({table: set-of-keys} — the shape of the reference's atom,
    cockroach clients there maintain it); else a live ``SELECT max``
    probe on the running workload's table (SPLIT_TABLES maps
    opts["workload"] to its schema).  Splitting a key twice is
    recorded, not raised."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = dict(opts or {})
        self.table, self.column = SPLIT_TABLES.get(
            self.opts.get("workload", "register"), ("registers", "id")
        )
        self.already: dict = {}
        self.client = None

    def setup(self, test):
        from . import sql

        opts = {**self.opts, "host": self.opts.get(
            "host", str(test["nodes"][0]))}
        opts.setdefault("dialect", "cockroach")
        try:
            c = sql.RegisterClient(opts)
            self.client = c.open(test, test["nodes"][0])
        except Exception:  # noqa: BLE001 - probe-only client
            self.client = None
        return self

    def _pick_key(self, test):
        keyrange = test.get("keyrange")
        if keyrange is None:
            return self._probe_key(test)
        if not keyrange:
            return None, "nothing-to-split"
        table = gen.rng.choice(sorted(keyrange))
        ks = set(keyrange[table]) - self.already.get(table, set())
        if not ks:
            return None, "nothing-to-split"
        # the newest unsplit key: splits chase the active write
        # frontier, not cold historical ranges
        return (table, max(ks)), None

    def _probe_key(self, test):
        if self.client is None:
            return None, "no-keyrange"
        try:
            res = self.client.conn.query(
                f"SELECT max({self.column}) FROM {self.table}"
            )
            k = res.rows[0][0] if res.rows else None
        except Exception:  # noqa: BLE001
            return None, "no-keyrange"
        if k is None:
            return None, "nothing-to-split"
        k = int(k)
        if k in self.already.get(self.table, set()):
            return None, "nothing-to-split"
        return (self.table, k), None

    def invoke(self, test, op):
        picked, why = self._pick_key(test)
        if picked is None:
            return {**op, "type": "info", "value": why}
        table, k = picked
        try:
            self.client.conn.query(
                f"ALTER TABLE {table} SPLIT AT VALUES ({int(k)})"
            )
            self.already.setdefault(table, set()).add(k)
            value = ["split", table, k]
        except Exception as e:  # noqa: BLE001
            if "already split" in str(e):
                self.already.setdefault(table, set()).add(k)
                value = ["already-split", table, k]
            else:
                value = ["split-failed", table, k, repr(e)[:120]]
        return {**op, "type": "info", "value": value}

    def teardown(self, test):
        if self.client is not None:
            try:
                self.client.close(test)
            except Exception:  # noqa: BLE001
                pass

    def fs(self):
        return frozenset({"split"})


# ---------------------------------------------------------------------
# The named menu (reference: :108-316)
# ---------------------------------------------------------------------


def none() -> dict:
    return {**no_gen(), "name": "blank", "client": noop(), "clocks": False}


def parts() -> dict:
    return {**single_gen(), "name": "parts",
            "client": partition_random_halves(), "clocks": False}


def _take_n_shuffled(n: int) -> Callable:
    def targeter(nodes):
        nodes = list(nodes)
        gen.rng.shuffle(nodes)
        return nodes[:n]
    return targeter


def startstop(n: int = 1, db=None) -> dict:
    """SIGSTOP/CONT the cockroach process on n random nodes."""
    return {**single_gen(),
            "name": f"startstop{n if n > 1 else ''}",
            "client": hammer_time("cockroach", _take_n_shuffled(n)),
            "clocks": False}


def startkill(n: int = 1, db=None) -> dict:
    """Kill + restart the DB on n random nodes."""
    assert db is not None, "startkill needs the suite DB"
    return {**single_gen(),
            "name": f"startkill{n if n > 1 else ''}",
            "client": node_start_stopper(
                _take_n_shuffled(n),
                lambda test, node: db.kill(test, node),
                lambda test, node: db.start(test, node),
            ),
            "clocks": False}


def majring() -> dict:
    return {**single_gen(), "name": "majring",
            "client": partition_majorities_ring(), "clocks": False}


def strobe_skews(db=None) -> dict:
    # no sleeps: the start op itself takes `duration` to run (:229-236)
    return {
        "during": gen.cycle([{"type": "info", "f": "start"},
                             {"type": "info", "f": "stop"}]),
        "final": [{"type": "info", "f": "stop"}],
        "name": "strobe-skews",
        "client": Restarting(StrobeTime(200, 10, 10), db),
        "clocks": True,
    }


def _skew(name: str, offset_s: float, db=None) -> dict:
    return {**single_gen(), "name": name,
            "client": Restarting(BumpTime(offset_s), db), "clocks": True}


def small_skews(db=None) -> dict:
    return _skew("small-skews", 0.100, db)


def subcritical_skews(db=None) -> dict:
    return _skew("subcritical-skews", 0.200, db)


def critical_skews(db=None) -> dict:
    return _skew("critical-skews", 0.250, db)


def big_skews(db=None) -> dict:
    b = _skew("big-skews", 0.5, db)
    b["client"] = Slowing(b["client"], 0.5)
    return b


def huge_skews(db=None) -> dict:
    b = _skew("huge-skews", 5, db)
    b["client"] = Slowing(b["client"], 5)
    return b


def split(opts: Optional[dict] = None) -> dict:
    return {
        "during": gen.delay(2, gen.repeat({"type": "info", "f": "split"})),
        "final": None,
        "name": "splits",
        "client": SplitNemesis(opts),
        "clocks": False,
    }


#: name → constructor(db, opts); the runner's --nemesis vocabulary
MENU: dict = {
    "none": lambda db, opts: none(),
    "parts": lambda db, opts: parts(),
    "majority-ring": lambda db, opts: majring(),
    "start-stop": lambda db, opts: startstop(1, db),
    "start-stop-2": lambda db, opts: startstop(2, db),
    "start-kill": lambda db, opts: startkill(1, db),
    "start-kill-2": lambda db, opts: startkill(2, db),
    "strobe-skews": lambda db, opts: strobe_skews(db),
    "small-skews": lambda db, opts: small_skews(db),
    "subcritical-skews": lambda db, opts: subcritical_skews(db),
    "critical-skews": lambda db, opts: critical_skews(db),
    "big-skews": lambda db, opts: big_skews(db),
    "huge-skews": lambda db, opts: huge_skews(db),
    "split": lambda db, opts: split(opts),
}


# ---------------------------------------------------------------------
# Tagged composition (reference: compose :61-106)
# ---------------------------------------------------------------------


class TaggedCompose(Nemesis):
    """Routes ops whose f is (name, inner-f) to the named client,
    invoking it with the inner f and re-tagging the result."""

    def __init__(self, clients: dict):
        self.clients = dict(clients)

    def setup(self, test):
        self.clients = {
            name: (c.setup(test) or c) for name, c in self.clients.items()
        }
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if not (isinstance(f, (tuple, list)) and len(f) == 2):
            raise ValueError(f"untagged nemesis op f {f!r}")
        name, inner = f
        if name not in self.clients:
            raise ValueError(f"no nemesis bundle named {name!r}")
        out = self.clients[name].invoke(test, {**op, "f": inner})
        return {**out, "f": (name, out.get("f", inner))}

    def teardown(self, test):
        for c in self.clients.values():
            c.teardown(test)

    def fs(self):
        return frozenset(
            (name, f)
            for name, c in self.clients.items()
            for f in (c.fs() or ())
        )


def _tag(name: str, g):
    """Rewrite every op's f to (name, f).  Special ops (sleep/log)
    carry no f and pass through untouched."""
    if g is None:
        return None

    def retag(op):
        if op.get("type") in ("sleep", "log") or "f" not in op:
            return op
        return {**op, "f": (name, op["f"])}

    return gen.map(retag, g)


#: shading colors for named bundles, cycled by position
_PERF_COLORS = ("#E9A4A0", "#A0B1E9", "#A0E9DB", "#E9D3A0", "#C9A0E9")


def _bundle_perf(bundles):
    """One plot-shading spec per bundle: its (name, start/stop) tagged
    fs and a stable color."""
    return {
        (b["name"], frozenset({(b["name"], "start")}),
         frozenset({(b["name"], "stop")}),
         _PERF_COLORS[i % len(_PERF_COLORS)])
        for i, b in enumerate(bundles)
    }


def _f_map_ops(fmap: dict, g):
    """f_map that leaves special (sleep/log) ops untouched."""
    if g is None:
        return None

    def rf(op):
        if op.get("type") in ("sleep", "log") or "f" not in op:
            return op
        return {**op, "f": fmap.get(op["f"], op["f"])}

    return gen.map(rf, g)


def compose_double(bundles: List[dict]) -> dict:
    """Run exactly two bundles on the overlapping double schedule:
    instance 1 and 2 start/stop interleaved, alternating which leads
    (reference: nemesis-double-gen :39-59 — its start1/stop1 fs are
    this composition's routing keys)."""
    assert len(bundles) == 2, "the double schedule takes exactly 2"
    n1, n2 = bundles[0]["name"], bundles[1]["name"]
    assert n1 != n2, f"duplicate name {n1!r}"
    fmap = {"start1": (n1, "start"), "stop1": (n1, "stop"),
            "start2": (n2, "start"), "stop2": (n2, "stop")}
    sched = double_gen()
    return {
        "name": f"{n1}~{n2}",
        "nemesis": TaggedCompose({b["name"]: b["client"]
                                  for b in bundles}),
        "generator": _f_map_ops(fmap, sched["during"]),
        "final_generator": _f_map_ops(fmap, sched["final"]),
        "clocks": any(b.get("clocks") for b in bundles),
        "perf": _bundle_perf(bundles),
    }


def compose_named(bundles: List[dict]) -> dict:
    """Merge named bundles into one {name, nemesis, generator,
    final_generator, clocks} package."""
    bundles = [b for b in bundles if b is not None]
    names = [b["name"] for b in bundles]
    assert len(set(names)) == len(names), f"duplicate names in {names}"
    durings = [_tag(b["name"], b.get("during")) for b in bundles]
    durings = [d for d in durings if d is not None]
    finals = [_tag(b["name"], b.get("final")) for b in bundles]
    finals = [f for f in finals if f is not None]
    return {
        "name": "+".join(names),
        "nemesis": TaggedCompose({b["name"]: b["client"] for b in bundles}),
        "generator": gen.mix(durings) if durings else None,
        "final_generator": finals or None,
        "clocks": any(b.get("clocks") for b in bundles),
        "perf": _bundle_perf(bundles),
    }


def package(opts: dict, db) -> dict:
    """Build the composed package from opts["nemesis"] — one name or a
    list from MENU (reference: runner.clj parses --nemesis /
    --nemesis2 into exactly this composition)."""
    spec = opts.get("nemesis", "none")
    if isinstance(spec, str):
        spec = [spec]
    unknown = [s for s in spec if s not in MENU]
    if unknown:
        raise ValueError(
            f"unknown cockroach nemesis {unknown}; menu: {sorted(MENU)}"
        )
    bundles = [MENU[s](db, opts) for s in spec]
    if opts.get("nemesis-schedule") == "double":
        if len(bundles) != 2:
            raise ValueError(
                "nemesis-schedule=double needs exactly two nemeses, "
                f"got {spec}"
            )
        return compose_double(bundles)
    return compose_named(bundles)
