"""Sequential-consistency workload for the SQL suites.

A writer inserts a key's subkeys one per transaction, in order; a reader
later queries them in *reverse* order.  Under sequential consistency a
reader that observes subkey i must observe every subkey written before
it — so the reversed read list may contain nils only as a prefix.  Keys
shard over several tables so they land in different ranges.

Reference: cockroachdb/src/jepsen/cockroach/sequential.clj:1-185 — the
Client writes subkeys ``k_0..k_{n-1}`` in separate txns and reads them
reversed; ``trailing-nil?`` detects a nil after a non-nil, the checker
counts all/some/none/bad reads; the generator reserves n writer threads
emitting sequential keys and readers sampling recently-written keys.
"""

from __future__ import annotations

import random
from collections import deque
from typing import List, Optional

from .. import generator as gen
from ..checker import Checker
from ..history import OK
from . import sql

TABLE_PREFIX = "seq_"
TABLE_COUNT = 3
KEY_COUNT = 5


def table_for(subkey: str, table_count: int = TABLE_COUNT) -> str:
    # stable shard assignment (python's str hash is salted per process)
    return f"{TABLE_PREFIX}{sum(subkey.encode()) % table_count}"


def subkeys(key_count: int, k) -> List[str]:
    return [f"{k}_{i}" for i in range(key_count)]


class SequentialClient(sql._Base):
    """(reference: sequential.clj:52-105)"""

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.table_count = int(self.opts.get("table-count", TABLE_COUNT))
        self.key_count = int(self.opts.get("key-count", KEY_COUNT))

    def setup(self, test):
        self._exec_ddl(
            *(
                f"CREATE TABLE IF NOT EXISTS {TABLE_PREFIX}{i} "
                # "sk", not "key": KEY is reserved in MySQL/TiDB, and a
                # dialect-neutral name beats per-dialect quoting
                "(sk VARCHAR(255) PRIMARY KEY)"
                for i in range(self.table_count)
            )
        )

    def invoke(self, test, op):
        k = op["value"]
        ks = subkeys(self.key_count, k)
        try:
            if op["f"] == "write":
                # one transaction per subkey, in client order
                for sk in ks:
                    self.conn.query(
                        f"INSERT INTO {table_for(sk, self.table_count)} "
                        f"(sk) VALUES ('{sk}')"
                    )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                out = []
                for sk in reversed(ks):
                    res = self.conn.query(
                        f"SELECT sk FROM {table_for(sk, self.table_count)} "
                        f"WHERE sk = '{sk}'"
                    )
                    out.append(str(res.rows[0][0]) if res.rows else None)
                return {**op, "type": "ok", "value": [k, out]}
            raise ValueError(f"unknown f {op['f']!r}")
        except sql.IndeterminateError as e:
            return self._info(op, e)
        except (sql.PgError, sql.MysqlError) as e:
            return self._fail(op, e)


def trailing_nil(coll) -> bool:
    """A nil after a non-nil element.  (reference: sequential.clj:137-140)"""
    it = iter(coll)
    for x in it:
        if x is not None:
            break
    return any(x is None for x in it)


class SequentialChecker(Checker):
    """(reference: sequential.clj:142-162)"""

    def __init__(self, key_count: int = KEY_COUNT):
        self.key_count = key_count

    def check(self, test, history, opts=None):
        reads = [
            op.value
            for op in history
            if op.type == OK and op.f == "read" and isinstance(op.value, (list, tuple))
        ]
        none = [r for r in reads if all(x is None for x in r[1])]
        some = [r for r in reads if any(x is None for x in r[1])]
        bad = [r for r in reads if trailing_nil(r[1])]
        all_ = [
            r
            for r in reads
            if list(r[1]) == list(reversed(subkeys(self.key_count, r[0])))
        ]
        return {
            "valid?": not bad,
            "all-count": len(all_),
            "some-count": len(some),
            "none-count": len(none),
            "bad-count": len(bad),
            "bad": bad,
        }


def workload(opts: Optional[dict] = None) -> dict:
    """n reserved writer threads emit sequential keys; the rest read a
    recently-written key.  (reference: sequential.clj:107-133,164-185)"""
    opts = dict(opts or {})
    n = int(opts.get("writer-threads", 5))
    key_count = int(opts.get("key-count", KEY_COUNT))
    last_written: deque = deque([None] * (2 * n), maxlen=2 * n)
    counter = {"k": 0}

    def write(test, ctx):
        k = counter["k"]
        counter["k"] += 1
        last_written.append(k)
        return {"type": "invoke", "f": "write", "value": k}

    def read(test, ctx):
        k = random.choice([x for x in last_written if x is not None] or [0])
        return {"type": "invoke", "f": "read", "value": k}

    return {
        "generator": gen.reserve(n, write, read),
        "checker": SequentialChecker(key_count),
        "key-count": key_count,
    }
