"""MySQL Cluster (NDB) suite.

Reference: mysql-cluster/src/jepsen/mysql_cluster.clj — install the
mysql-cluster debs (install!:41-51), then run all three roles on every
node with distinct node-id ranges (mgmd 1+, ndbd 11+, mysqld 21+;
:53-73): ``ndb_mgmd`` management daemons with a config.ini listing the
whole cluster, ``ndbd`` data nodes, and ``mysqld`` SQL frontends with
``ndbcluster`` enabled.  Clients via :mod:`.sql` (dialect ``mysql``).
"""

from __future__ import annotations

from typing import Optional

from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common, sql

PORT = 3306
MGMD_PORT = 1186
MGMD_DIR = "/var/lib/mysql/cluster"    # (reference: :53-55)
NDBD_DIR = "/var/lib/mysql/data"
MYSQLD_DIR = "/var/lib/mysql/mysql"

MGMD_ID_OFFSET, NDBD_ID_OFFSET, MYSQLD_ID_OFFSET = 1, 11, 21  # (:56-58)


def config_ini(test: dict) -> str:
    """config.ini listing every role on every node.
    (reference: :75-110 nbd-mgmd-conf)"""
    nodes = list(test["nodes"])
    out = [
        "[ndbd default]",
        f"NoOfReplicas={min(2, len(nodes))}",
        "DataMemory=98M",
        "IndexMemory=32M",
    ]
    for i, n in enumerate(nodes):
        out += ["[ndb_mgmd]",
                f"NodeId={MGMD_ID_OFFSET + i}",
                f"HostName={n}",
                f"DataDir={MGMD_DIR}"]
    for i, n in enumerate(nodes):
        out += ["[ndbd]",
                f"NodeId={NDBD_ID_OFFSET + i}",
                f"HostName={n}",
                f"DataDir={NDBD_DIR}"]
    for i, n in enumerate(nodes):
        out += ["[mysqld]",
                f"NodeId={MYSQLD_ID_OFFSET + i}",
                f"HostName={n}"]
    return "\n".join(out) + "\n"


def connect_string(test: dict) -> str:
    return ",".join(f"{n}:{MGMD_PORT}" for n in test["nodes"])


class MysqlClusterDB(common.DaemonDB):
    logfile = "/var/log/mysql/error.log"
    proc_name = "mysqld"

    def install(self, test, node):
        # (reference: :41-51 — mysql-cluster community debs + libaio)
        debian.install(["libaio1", "mysql-cluster-community-server"])
        with sudo():
            execute("service", "mysql", "stop", check=False)
            execute("mkdir", "-p", MGMD_DIR, NDBD_DIR, MYSQLD_DIR)

    def configure(self, test, node):
        with sudo():
            cu.write_file(config_ini(test), f"{MGMD_DIR}/config.ini")
            cu.write_file(
                "\n".join([
                    "[mysqld]",
                    "ndbcluster",
                    "bind-address=0.0.0.0",
                    f"ndb-connectstring={connect_string(test)}",
                    "[mysql_cluster]",
                    f"ndb-connectstring={connect_string(test)}",
                ]) + "\n",
                "/etc/mysql/conf.d/cluster.cnf",
            )

    def start(self, test, node):
        i = test["nodes"].index(node)
        with sudo():
            execute(
                "ndb_mgmd", f"--ndb-nodeid={MGMD_ID_OFFSET + i}",
                "-f", f"{MGMD_DIR}/config.ini",
                f"--configdir={MGMD_DIR}", check=False,
            )
            execute(
                "ndbd", f"--ndb-nodeid={NDBD_ID_OFFSET + i}",
                f"--connect-string={connect_string(test)}", check=False,
            )
            execute("service", "mysql", "start", check=False)

    def kill(self, test, node):
        with sudo():
            execute("service", "mysql", "stop", check=False)
            cu.grepkill("mysqld")
            cu.grepkill("ndbd")
            cu.grepkill("ndb_mgmd")

    def await_ready(self, test, node):
        cu.await_tcp_port(PORT, timeout_s=300)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", NDBD_DIR, MGMD_DIR)


def _opts(opts: Optional[dict]) -> dict:
    o = dict(opts or {})
    o.setdefault("dialect", "mysql")
    o.setdefault("port", PORT)
    o.setdefault("user", "root")
    return o


def db(opts: Optional[dict] = None):
    return MysqlClusterDB(opts)


def client(opts: Optional[dict] = None):
    return sql.RegisterClient(_opts(opts))


WORKLOADS = ("register", "bank", "set")


def workloads(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    return {w: common.generic_workload(w, opts) for w in WORKLOADS}


def test(opts: Optional[dict] = None) -> dict:
    opts = _opts(opts)
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    return common.build_test(
        f"mysql-cluster-{wname}", opts, db=MysqlClusterDB(opts),
        client=sql.client_for(wname, opts), workload=w,
    )
