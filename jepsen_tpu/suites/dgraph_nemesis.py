"""Dgraph fault menu: alpha/zero-targeted process faults, speculative
alpha repair, tablet (predicate) moves, partitions, and clock skew.

Reference: dgraph/src/jepsen/dgraph/nemesis.clj — alpha-killer (:17-23,
targeting every node), alpha-fixer (:25-41, speculative restarts of
alphas that fell over while zero was away), zero-killer (:43-49),
tablet-mover (:51-101, shuffling predicates between groups through the
zero leader's HTTP API), bump-time clock skew with NTP-reset setup and
tiny…huge presets (:100-139), full-nemesis composition (:141-156), the
per-flag cycle generator (:158-186), and the delayed recovery final
generator (:187-202).  Tablet moves run under tracing spans exactly as
the reference wraps them (trace.clj via nemesis.clj:55-60).
"""

from __future__ import annotations

import re
from typing import Optional

from .. import control
from .. import generator as gen
from .. import trace
from ..nemesis import (
    Nemesis,
    bisect,
    complete_grudge,
    compose,
    majorities_ring,
    partitioner,
)
from ..nemesis import time as nt
from ..util import random_nonempty_subset

#: skew presets, milliseconds (reference: nemesis.clj:131-139)
SKEWS = {"tiny": 100, "small": 250, "big": 2000, "huge": 7500}


class AlphaKiller(Nemesis):
    """kill-alpha stops alphas on every node; restart-alpha brings them
    all back (reference: nemesis.clj:17-23 — its targeter is
    `identity`, i.e. the whole node list)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        nodes = list(test["nodes"])
        if op["f"] == "kill-alpha":
            res = control.on_nodes(test, nodes, self.db.stop_alpha)
        else:
            res = control.on_nodes(test, nodes, self.db.start_alpha)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"kill-alpha", "restart-alpha"})


class AlphaFixer(Nemesis):
    """Speculative alpha restarts: alphas fall over when zero
    disappears, so fix-alpha restarts any that aren't running on a
    random node subset (reference: nemesis.clj:25-41)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        db = self.db

        def fix(test, node):
            if db.alpha_running(test, node):
                return "already-running"
            db.start_alpha(test, node)
            return "restarted"

        targets = random_nonempty_subset(list(test["nodes"]), gen.rng)
        res = control.on_nodes(test, targets, fix)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"fix-alpha"})


class ZeroKiller(Nemesis):
    """kill/restart zero on (a random subset of) the zero nodes
    (reference: nemesis.clj:43-49)."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        return self

    def invoke(self, test, op):
        zeros = self.db.zero_nodes(test)
        if op["f"] == "kill-zero":
            targets = random_nonempty_subset(zeros, gen.rng)
            res = control.on_nodes(test, targets, self.db.stop_zero)
        else:
            res = control.on_nodes(test, zeros, self.db.start_zero)
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"kill-zero", "restart-zero"})


class TabletMover(Nemesis):
    """Shuffles predicates (tablets) between Raft groups through the
    zero leader (reference: nemesis.clj:51-101).  Reserved predicates
    and not-the-leader refusals are recorded, not raised — the point is
    to exercise dgraph's rebalancing under load, not to crash the
    harness on its answers."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        with trace.with_trace("nemesis.tablet-mover.setup"):
            return self

    def invoke(self, test, op):
        with trace.with_trace("nemesis.tablet-mover.invoke"):
            # the zero HTTP API lives on the zero nodes only
            node = gen.rng.choice(self.db.zero_nodes(test))
            state = self.db.zero_state(test, node)
            if not isinstance(state, dict):
                return {**op, "type": "info", "value": "timeout"}
            groups = list((state.get("groups") or {}).keys())
            moves = {}
            refused = {}
            tablets = [
                t
                for g in (state.get("groups") or {}).values()
                for t in (g.get("tablets") or {}).values()
            ]
            gen.rng.shuffle(tablets)
            for tablet in tablets:
                pred = tablet.get("predicate")
                group = str(tablet.get("groupId"))
                group2 = gen.rng.choice(groups) if groups else group
                if group2 == group:
                    continue
                trace.annotate(f"moving {pred} {group}->{group2}")
                status, body = self.db.move_tablet(test, node, pred, group2)
                if status == 200:
                    moves[pred] = [group, group2]
                elif status == 500 and re.search(
                    "Unable to move reserved|not leader", str(body)
                ):
                    refused[pred] = str(body)[:120]
                else:
                    # zero died / unexpected answer: record and stop —
                    # the remaining moves would hit the same wall
                    refused[pred] = str(body)[:120]
                    break
            value: dict = {"moved": moves}
            if refused:
                value["refused"] = refused
            return {**op, "type": "info", "value": value}

    def teardown(self, test):
        pass

    def fs(self):
        return frozenset({"move-tablet"})


class BumpTime(Nemesis):
    """start-skew bumps the clock by dt ms on a random half of the
    nodes; stop-skew resets everyone.  Setup resets clocks up front
    (reference: nemesis.clj:100-129)."""

    def __init__(self, dt_ms: int):
        self.dt_ms = dt_ms

    def setup(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())
        return self

    def invoke(self, test, op):
        nodes = list(test["nodes"])
        if op["f"] == "start-skew":
            dt = self.dt_ms

            def act(t, n):
                if gen.rng.random() < 0.5:
                    nt.bump_time(dt)
                    return dt
                return 0

            res = control.on_nodes(test, nodes, act)
        else:
            res = control.on_nodes(
                test, nodes, lambda t, n: nt.reset_time()
            )
        return {**op, "type": "info",
                "value": {str(k): str(v) for k, v in res.items()}}

    def teardown(self, test):
        control.on_nodes(test, list(test["nodes"]),
                         lambda t, n: nt.reset_time())

    def fs(self):
        return frozenset({"start-skew", "stop-skew"})


def skew_nemesis(opts: dict) -> BumpTime:
    """(reference: nemesis.clj:131-139)"""
    return BumpTime(SKEWS.get(opts.get("skew"), 0))


def full_nemesis(db, opts: Optional[dict] = None) -> Nemesis:
    """(reference: nemesis.clj:141-156 full-nemesis)"""
    opts = opts or {}
    return compose([
        (frozenset({"fix-alpha"}), AlphaFixer(db)),
        (frozenset({"kill-alpha", "restart-alpha"}), AlphaKiller(db)),
        (frozenset({"kill-zero", "restart-zero"}), ZeroKiller(db)),
        (frozenset({"move-tablet"}), TabletMover(db)),
        ({"start-partition-halves": "start",
          "stop-partition-halves": "stop",
          "start-partition-ring": "start",
          "stop-partition-ring": "stop"}, partitioner()),
        (frozenset({"start-skew", "stop-skew"}), skew_nemesis(opts)),
    ])


def _op(f, value=None, **extra):
    return {"type": "info", "f": f, "value": value, **extra}


def _partition_halves_gen(test, ctx):
    nodes = list(test["nodes"])
    gen.rng.shuffle(nodes)
    return _op("start-partition-halves", complete_grudge(bisect(nodes)))


def _partition_ring_gen(test, ctx):
    return _op("start-partition-ring",
               majorities_ring(list(test["nodes"])))


def full_generator(opts: dict):
    """Cycle each enabled fault family, mixed and staggered by the
    interval (reference: nemesis.clj:158-186 full-generator)."""
    modes = []
    if opts.get("kill-alpha?"):
        modes.append(gen.cycle([_op("kill-alpha"), _op("restart-alpha")]))
    if opts.get("kill-zero?"):
        modes.append(gen.cycle([_op("kill-zero"), _op("restart-zero")]))
    if opts.get("fix-alpha?"):
        modes.append(gen.repeat(_op("fix-alpha")))
    if opts.get("partition-halves?"):
        modes.append(gen.flip_flop(
            _partition_halves_gen,
            gen.repeat(_op("stop-partition-halves"))))
    if opts.get("partition-ring?"):
        modes.append(gen.flip_flop(
            _partition_ring_gen,
            gen.repeat(_op("stop-partition-ring"))))
    if opts.get("skew-clock?"):
        modes.append(gen.cycle([_op("start-skew"), _op("stop-skew")]))
    if opts.get("move-tablet?"):
        modes.append(gen.repeat(_op("move-tablet")))
    if not modes:
        return None
    return gen.stagger(opts.get("interval", 10), gen.mix(modes))


def final_generator(opts: dict):
    """The recovery ops for everything the enabled faults may have
    broken, in heal-before-restart order (reference: nemesis.clj
    :187-202; package() adds the reference's 5 s spacing)."""
    fs = []
    if opts.get("partition-halves?"):
        fs.append("stop-partition-halves")
    if opts.get("partition-ring?"):
        fs.append("stop-partition-ring")
    if opts.get("skew-clock?"):
        fs.append("stop-skew")
    if opts.get("kill-zero?"):
        fs.append("restart-zero")
    if opts.get("kill-alpha?"):
        fs.append("restart-alpha")
    return [_op(f) for f in fs]


#: faults the menu claims; anything else rides the generic packages
KNOWN_FAULTS = frozenset({
    "kill-alpha", "kill-zero", "fix-alpha", "move-tablet",
    "partition-halves", "partition-ring", "skew-clock",
})


def _flags(opts: dict) -> dict:
    faults = set(opts.get("faults", ()))
    return {
        "kill-alpha?": "kill-alpha" in faults,
        "kill-zero?": "kill-zero" in faults,
        "fix-alpha?": "fix-alpha" in faults,
        "move-tablet?": "move-tablet" in faults,
        "partition-halves?": "partition-halves" in faults,
        "partition-ring?": "partition-ring" in faults,
        "skew-clock?": "skew-clock" in faults,
        "interval": opts.get("interval", 10),
        # a requested skew fault must actually skew: default to the
        # small preset rather than silently bumping clocks by 0 ms
        "skew": opts.get("skew")
        or ("small" if "skew-clock" in faults else None),
    }


def package(opts: dict, db) -> dict:
    """{nemesis, generator, final_generator} bundle for build_test
    (reference: nemesis.clj:188-202 nemesis/0)."""
    flags = _flags(opts)
    final = final_generator(flags)
    return {
        "nemesis": full_nemesis(db, flags),
        "generator": full_generator(flags),
        # 5 s between recovery steps (reference: gen/delay-til 5)
        "final_generator": gen.delay(5, final) if final else None,
        "perf": set(),
    }
