"""Dgraph suite.

Reference: dgraph/src/jepsen/dgraph/support.clj — one ``dgraph zero`` on
the first node plus a ``dgraph alpha`` on every node (ports 5080/6080
zero, 7080/8080/9080 alpha; support.clj:24-60), installed from the
release tarball; clients (dgraph/client.clj) run upsert-style
transactions.  Workloads mirror dgraph/{set,bank,delete,upsert,
linearizable_register,long_fork,sequential,wr}.clj.

The reference speaks gRPC; this client uses Dgraph's equivalent HTTP
API: ``/alter`` for schema, ``/mutate?commitNow=true`` with RDF/JSON,
``/query`` with GraphQL+- — register CAS runs as a single upsert block
(query + conditional mutation), which Dgraph executes transactionally.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "v1.1.0"
DIR = "/opt/dgraph"  # (reference: support.clj:22 dir)
ALPHA_PORT = 8080
ZERO_PORT = 5080
ZERO_PUBLIC_PORT = 6080


class DgraphDB(common.DaemonDB):
    """zero on nodes[0], alpha everywhere (reference: support.clj)."""

    dir = DIR
    binary = "dgraph"
    logfile = f"{DIR}/alpha.log"   # (reference: support.clj:27)
    pidfile = f"{DIR}/alpha.pid"
    zero_logfile = f"{DIR}/zero.log"
    zero_pidfile = f"{DIR}/zero.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        url = (
            "https://github.com/dgraph-io/dgraph/releases/download/"
            f"{self.version}/dgraph-linux-amd64.tar.gz"
        )
        with sudo():
            cu.install_archive(url, DIR)

    def start(self, test, node):
        zero_node = test["nodes"][0]
        if node == zero_node:
            cu.start_daemon(
                {"logfile": self.zero_logfile, "pidfile": self.zero_pidfile,
                 "chdir": DIR},
                f"{DIR}/dgraph", "zero",
                "--my", f"{node}:{ZERO_PORT}",
                "--replicas", str(len(test["nodes"])),
            )
            cu.await_tcp_port(ZERO_PUBLIC_PORT, timeout_s=60)
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/dgraph", "alpha",
            "--my", f"{node}:7080",
            "--zero", f"{zero_node}:{ZERO_PORT}",
        )

    def kill(self, test, node):
        cu.stop_daemon(pidfile=self.pidfile, cmd="dgraph")
        cu.stop_daemon(pidfile=self.zero_pidfile, cmd="dgraph")

    def await_ready(self, test, node):
        cu.await_tcp_port(ALPHA_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/p", f"{DIR}/w", f"{DIR}/zw")

    def log_files(self, test, node):
        return [self.logfile, self.zero_logfile]


SCHEMA = "key: int @index(int) @upsert .\nvalue: int .\n"


class DgraphClient(client_mod.Client):
    """Register ops as upsert blocks over the HTTP API
    (reference: dgraph/client.clj + linearizable_register.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", ALPHA_PORT),
            timeout=10.0,
        )
        return c

    def setup(self, test):
        try:
            self.conn.post("/alter", SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass

    def _query(self, q: str):
        _, body = self.conn.post(
            "/query", q, headers={"Content-Type": "application/graphql+-"},
            ok=(200,),
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        return body.get("data", {})

    def _upsert(self, query: str, mutations: list):
        payload = json.dumps({"query": query, "mutations": mutations})
        _, out = self.conn.post(
            "/mutate?commitNow=true", payload,
            headers={"Content-Type": "application/json"}, ok=(200,),
        )
        if "errors" in (out or {}):
            raise HttpError(200, out["errors"])
        return out

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        q = (
            f'{{ q(func: eq(key, {k})) {{ u as uid, value }} }}'
        )
        try:
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(key, {k})) {{ value }} }}'
                )
                rows = data.get("q", [])
                val = rows[0]["value"] if rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                # update when the key exists, else create a fresh node —
                # both branches in one transactional upsert
                self._upsert(q, [
                    {"cond": "@if(gt(len(u), 0))",
                     "set_nquads": f'uid(u) <value> "{v}" .'},
                    {"cond": "@if(eq(len(u), 0))",
                     "set_nquads": f'_:n <key> "{k}" .\n_:n <value> "{v}" .'},
                ])
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                qc = (
                    f'{{ q(func: eq(key, {k})) @filter(eq(value, {old})) '
                    f'{{ u as uid }} }}'
                )
                out = self._upsert(qc, [
                    {"cond": "@if(gt(len(u), 0))",
                     "set_nquads": f'uid(u) <value> "{new}" .'},
                ])
                # the mutate response echoes the upsert query's matches;
                # the conditional mutation applied iff q was non-empty
                matched = (out.get("data") or {}).get("queries", {}).get("q")
                if matched:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            if op["f"] == "add":
                self._upsert(
                    f'{{ q(func: eq(key, {op["value"]})) {{ u as uid }} }}',
                    [{"cond": "@if(eq(len(u), 0))",
                      "set_nquads": f'_:n <key> "{op["value"]}" .'}],
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return DgraphDB(opts)


def client(opts: Optional[dict] = None):
    return DgraphClient(opts)


class DgraphSetClient(DgraphClient):
    """Set workload: add via key-only upsert, read via a full key scan.
    (reference: dgraph/set.clj)"""

    def invoke(self, test, op):
        if op["f"] == "read":
            try:
                data = self._query(
                    '{ q(func: has(key)) { key } }'
                )
                rows = data.get("q", [])
                return {**op, "type": "ok",
                        "value": sorted(r["key"] for r in rows)}
            except IndeterminateError as e:
                return {**op, "type": "info", "error": str(e)}
            except HttpError as e:
                return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}
        return super().invoke(test, op)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    return {
        "register": common.register_workload(opts),
        "set": common.set_workload(opts),
        "upsert": upsert_workload(opts),
        "delete": delete_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {
        "set": DgraphSetClient,
        "upsert": DgraphUpsertClient,
        "delete": DgraphDeleteClient,
    }.get(wname, DgraphClient)(opts)
    return common.build_test(
        f"dgraph-{wname}", opts, db=DgraphDB(opts), client=c, workload=w,
    )


# ---------------------------------------------------------------------
# upsert workload
# ---------------------------------------------------------------------

UPSERT_SCHEMA = "email: string @index(exact) @upsert .\n"


class DgraphUpsertClient(DgraphClient):
    """Concurrent insert-if-absent on an indexed predicate; at most one
    node per key may ever be created.

    Reference: dgraph/src/jepsen/dgraph/upsert.clj:13-55 — :upsert
    creates an email node unless an index read finds one (ok iff it
    inserted); :read returns the sorted uids matching the key.
    """

    def setup(self, test):
        try:
            self.conn.post("/alter", UPSERT_SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, _v = op["value"]
        try:
            if op["f"] == "upsert":
                out = self._upsert(
                    f'{{ q(func: eq(email, "{k}")) {{ u as uid }} }}',
                    [{"cond": "@if(eq(len(u), 0))",
                      "set_nquads": f'_:n <email> "{k}" .'}],
                )
                uids = (out.get("data") or {}).get("uids") or {}
                if uids:
                    return {**op, "type": "ok",
                            "value": independent.kv(k, sorted(uids.values()))}
                return {**op, "type": "fail", "error": "exists"}
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(email, "{k}")) {{ uid }} }}'
                )
                uids = sorted(r["uid"] for r in data.get("q", []))
                return {**op, "type": "ok", "value": independent.kv(k, uids)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class UpsertChecker(checker_mod.Checker):
    """At most one uid may ever be read, and at most one upsert may
    succeed, per key.  (reference: upsert.clj:57-71)"""

    def check(self, test, history, opts=None):
        from ..history import OK as _OK

        reads = [op for op in history if op.type == _OK and op.f == "read"]
        upserts = [op for op in history if op.type == _OK and op.f == "upsert"]
        bad_reads = [
            {"index": op.index, "value": list(op.value)}
            for op in reads
            if op.value is not None and len(op.value) > 1
        ]
        return {
            "valid?": not bad_reads and len(upserts) <= 1,
            "bad-reads": bad_reads,
            "ok-upsert-count": len(upserts),
        }


def upsert_workload(opts: Optional[dict] = None) -> dict:
    """Per key: every thread races one upsert, then every thread reads.
    (reference: upsert.clj:73-86)"""

    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def fgen(k):
        return gen_mod.phases(
            gen_mod.each_thread(
                gen_mod.once({"type": "invoke", "f": "upsert", "value": None})
            ),
            gen_mod.each_thread(
                gen_mod.once({"type": "invoke", "f": "read", "value": None})
            ),
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(UpsertChecker()),
        "concurrency": 4 * n,
    }


# ---------------------------------------------------------------------
# delete workload
# ---------------------------------------------------------------------


class DgraphDeleteClient(DgraphClient):
    """Create and delete indexed records per key; reads must never see
    a half-indexed state (zero records or exactly one well-formed one).

    Reference: dgraph/src/jepsen/dgraph/delete.clj:22-62 — :upsert
    creates {key k} unless present, :delete removes the record found by
    an index read, :read returns the matching records.  Both mutations
    ride one transactional upsert block.
    """

    def invoke(self, test, op):
        k, _v = op["value"]
        q = f'{{ q(func: eq(key, {int(k)})) {{ u as uid }} }}'
        try:
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(key, {int(k)})) {{ uid key }} }}'
                )
                return {**op, "type": "ok",
                        "value": independent.kv(k, data.get("q", []))}
            if op["f"] == "upsert":
                out = self._upsert(q, [
                    {"cond": "@if(eq(len(u), 0))",
                     "set_nquads": f'_:n <key> "{int(k)}" .'},
                ])
                if (out.get("data") or {}).get("uids"):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "present"}
            if op["f"] == "delete":
                out = self._upsert(q, [
                    {"cond": "@if(gt(len(u), 0))",
                     "del_nquads": "uid(u) * * ."},
                ])
                matched = (out.get("data") or {}).get("queries", {}).get("q")
                if matched:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "not-found"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class DeleteChecker(checker_mod.Checker):
    """Every ok read sees zero records, or exactly one {uid, key=k}.
    (reference: delete.clj:64-90)"""

    def check(self, test, history, opts=None):
        from ..history import OK as _OK

        k = (opts or {}).get("history-key")
        bad = []
        for op in history:
            if op.type != _OK or op.f != "read" or op.value is None:
                continue
            recs = op.value
            well_formed = len(recs) == 0 or (
                len(recs) == 1
                and set(recs[0].keys()) == {"uid", "key"}
                and (k is None or str(recs[0]["key"]) == str(k))
            )
            if not well_formed:
                bad.append({"index": op.index, "value": recs})
        return {"valid?": not bad, "bad-reads": bad}


def delete_workload(opts: Optional[dict] = None) -> dict:
    """Mixed upsert/delete/read ops per independent key.
    (reference: delete.clj:92-103)"""
    import random as _random

    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def rw(test, ctx):
        f = _random.choice(["read", "upsert", "delete"])
        return {"type": "invoke", "f": f, "value": None}

    def fgen(k):
        return gen_mod.limit(12, rw)

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(DeleteChecker()),
        "concurrency": 4 * n,
    }
