"""Dgraph suite.

Reference: dgraph/src/jepsen/dgraph/support.clj — one ``dgraph zero`` on
the first node plus a ``dgraph alpha`` on every node (ports 5080/6080
zero, 7080/8080/9080 alpha; support.clj:24-60), installed from the
release tarball; clients (dgraph/client.clj) run upsert-style
transactions.  Workloads mirror dgraph/{set,bank,delete,upsert,
linearizable_register,long_fork,sequential,wr}.clj.

The reference speaks gRPC; this client uses Dgraph's equivalent HTTP
API: ``/alter`` for schema, ``/mutate?commitNow=true`` with RDF/JSON,
``/query`` with GraphQL+- — register CAS runs as a single upsert block
(query + conditional mutation), which Dgraph executes transactionally.
"""

from __future__ import annotations

import json
from typing import Optional

from .. import checker as checker_mod
from .. import client as client_mod
from .. import independent
from ..control import util as cu
from ..control import execute, sudo
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

VERSION = "v1.1.0"
DIR = "/opt/dgraph"  # (reference: support.clj:22 dir)
ALPHA_PORT = 8080
ZERO_PORT = 5080
ZERO_PUBLIC_PORT = 6080


class DgraphDB(common.DaemonDB):
    """zero on nodes[0], alpha everywhere (reference: support.clj)."""

    dir = DIR
    binary = "dgraph"
    logfile = f"{DIR}/alpha.log"   # (reference: support.clj:27)
    pidfile = f"{DIR}/alpha.pid"
    zero_logfile = f"{DIR}/zero.log"
    zero_pidfile = f"{DIR}/zero.pid"

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.version = (opts or {}).get("version", VERSION)

    def install(self, test, node):
        url = (
            "https://github.com/dgraph-io/dgraph/releases/download/"
            f"{self.version}/dgraph-linux-amd64.tar.gz"
        )
        with sudo():
            cu.install_archive(url, DIR)

    def zero_nodes(self, test) -> list:
        """Zero runs on the first node (reference: support.clj)."""
        return [test["nodes"][0]]

    def start_zero(self, test, node):
        cu.start_daemon(
            {"logfile": self.zero_logfile, "pidfile": self.zero_pidfile,
             "chdir": DIR},
            f"{DIR}/dgraph", "zero",
            "--my", f"{node}:{ZERO_PORT}",
            "--replicas", str(len(test["nodes"])),
        )
        cu.await_tcp_port(ZERO_PUBLIC_PORT, timeout_s=60)

    def start_alpha(self, test, node):
        zero_node = test["nodes"][0]
        cu.start_daemon(
            {"logfile": self.logfile, "pidfile": self.pidfile, "chdir": DIR},
            f"{DIR}/dgraph", "alpha",
            "--my", f"{node}:7080",
            "--zero", f"{zero_node}:{ZERO_PORT}",
        )

    def stop_alpha(self, test, node):
        # pidfile-only: a killall would take the co-located zero down
        # as collateral, breaking the fault isolation the targeted
        # alpha/zero nemeses promise
        cu.stop_daemon(pidfile=self.pidfile)

    def stop_zero(self, test, node):
        cu.stop_daemon(pidfile=self.zero_pidfile)

    def alpha_running(self, test, node):
        return cu.daemon_running(self.pidfile)

    def start(self, test, node):
        if node in self.zero_nodes(test):
            self.start_zero(test, node)
        self.start_alpha(test, node)

    def kill(self, test, node):
        self.stop_alpha(test, node)
        self.stop_zero(test, node)
        # teardown-grade sweep: catch strays the pidfiles don't track
        cu.stop_daemon(cmd="dgraph")

    # -- zero cluster-management API (reference: support.clj
    # zero-state / move-tablet! via zero's HTTP port 6080) -------------

    def _zero_http(self, node) -> JsonHttpClient:
        return JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("zero-public-port", ZERO_PUBLIC_PORT),
            timeout=5.0,
        )

    def zero_state(self, test, node):
        """The zero /state map (groups → tablets, zero leader), or
        "timeout" when zero is unreachable."""
        c = self._zero_http(node)
        try:
            status, body = c.get("/state", ok=(200,),
                                 raise_on_error=False)
            return body if status == 200 else "timeout"
        except Exception:  # noqa: BLE001 - nemesis probes must not throw
            return "timeout"
        finally:
            c.close()

    def move_tablet(self, test, node, predicate, group):
        """Ask the zero leader to rebalance one predicate onto a
        group.  Returns (status, body); (None, error) when zero is
        unreachable — like zero_state, nemesis probes must not throw."""
        c = self._zero_http(node)
        try:
            return c.get(
                "/moveTablet",
                params={"tablet": str(predicate), "group": str(group)},
                ok=(200,), raise_on_error=False,
            )
        except Exception as e:  # noqa: BLE001
            return None, repr(e)
        finally:
            c.close()

    def await_ready(self, test, node):
        cu.await_tcp_port(ALPHA_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/p", f"{DIR}/w", f"{DIR}/zw")

    def log_files(self, test, node):
        return [self.logfile, self.zero_logfile]


SCHEMA = "key: int @index(int) @upsert .\nvalue: int .\n"


class DgraphClient(client_mod.Client):
    """Register ops as upsert blocks over the HTTP API
    (reference: dgraph/client.clj + linearizable_register.clj)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", ALPHA_PORT),
            timeout=10.0,
        )
        return c

    def setup(self, test):
        try:
            self.conn.post("/alter", SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass

    def _query(self, q: str):
        _, body = self.conn.post(
            "/query", q, headers={"Content-Type": "application/graphql+-"},
            ok=(200,),
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        return body.get("data", {})

    def _upsert(self, query: str, mutations: list):
        payload = json.dumps({"query": query, "mutations": mutations})
        _, out = self.conn.post(
            "/mutate?commitNow=true", payload,
            headers={"Content-Type": "application/json"}, ok=(200,),
        )
        if "errors" in (out or {}):
            raise HttpError(200, out["errors"])
        return out

    def invoke(self, test, op):
        k, v = op["value"] if isinstance(op["value"], (list, tuple)) else (
            0, op["value"])
        q = (
            f'{{ q(func: eq(key, {k})) {{ u as uid, value }} }}'
        )
        try:
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(key, {k})) {{ value }} }}'
                )
                rows = data.get("q", [])
                val = rows[0]["value"] if rows else None
                return {**op, "type": "ok", "value": independent.kv(k, val)}
            if op["f"] == "write":
                # update when the key exists, else create a fresh node —
                # both branches in one transactional upsert
                self._upsert(q, [
                    {"cond": "@if(gt(len(u), 0))",
                     "set_nquads": f'uid(u) <value> "{v}" .'},
                    {"cond": "@if(eq(len(u), 0))",
                     "set_nquads": f'_:n <key> "{k}" .\n_:n <value> "{v}" .'},
                ])
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                qc = (
                    f'{{ q(func: eq(key, {k})) @filter(eq(value, {old})) '
                    f'{{ u as uid }} }}'
                )
                out = self._upsert(qc, [
                    {"cond": "@if(gt(len(u), 0))",
                     "set_nquads": f'uid(u) <value> "{new}" .'},
                ])
                # the mutate response echoes the upsert query's matches;
                # the conditional mutation applied iff q was non-empty
                matched = (out.get("data") or {}).get("queries", {}).get("q")
                if matched:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-miss"}
            if op["f"] == "add":
                self._upsert(
                    f'{{ q(func: eq(key, {op["value"]})) {{ u as uid }} }}',
                    [{"cond": "@if(eq(len(u), 0))",
                      "set_nquads": f'_:n <key> "{op["value"]}" .'}],
                )
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return DgraphDB(opts)


def client(opts: Optional[dict] = None):
    return DgraphClient(opts)


class DgraphSetClient(DgraphClient):
    """Set workload: add via key-only upsert, read via a full key scan.
    (reference: dgraph/set.clj)"""

    def invoke(self, test, op):
        if op["f"] == "read":
            try:
                data = self._query(
                    '{ q(func: has(key)) { key } }'
                )
                rows = data.get("q", [])
                return {**op, "type": "ok",
                        "value": sorted(r["key"] for r in rows)}
            except IndeterminateError as e:
                return {**op, "type": "info", "error": str(e)}
            except HttpError as e:
                return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}
        return super().invoke(test, op)


def workloads(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    from ..workloads import bank as bank_wl

    return {
        "register": common.register_workload(opts),
        "set": common.set_workload(opts),
        "upsert": upsert_workload(opts),
        "delete": delete_workload(opts),
        # flagship probes (reference: dgraph/bank.clj, wr.clj,
        # long_fork.clj, sequential.clj)
        "bank": bank_wl.test(opts),
        "wr": common.generic_workload("rw-register", opts),
        "long-fork": common.generic_workload("long-fork", opts),
        "sequential": sequential_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "register")
    w = workloads(opts)[wname]
    c = {
        "set": DgraphSetClient,
        "upsert": DgraphUpsertClient,
        "delete": DgraphDeleteClient,
        "bank": DgraphBankClient,
        "wr": DgraphTxnClient,
        "long-fork": DgraphTxnClient,
        "sequential": DgraphSequentialClient,
    }.get(wname, DgraphClient)(opts)
    db_obj = DgraphDB(opts)
    # per-suite fault menu: alpha/zero targeting, tablet moves, skew
    # (reference: dgraph/nemesis.clj via runner's nemesis wiring)
    pkg = None
    from . import dgraph_nemesis

    if set(opts.get("faults", ())) & dgraph_nemesis.KNOWN_FAULTS:
        pkg = common.suite_nemesis_package(
            opts, db_obj, dgraph_nemesis.package(opts, db_obj),
            dgraph_nemesis.KNOWN_FAULTS,
        )
    return common.build_test(
        f"dgraph-{wname}", opts, db=db_obj, client=c, workload=w,
        nemesis_package=pkg,
    )


# ---------------------------------------------------------------------
# upsert workload
# ---------------------------------------------------------------------

UPSERT_SCHEMA = "email: string @index(exact) @upsert .\n"


class DgraphUpsertClient(DgraphClient):
    """Concurrent insert-if-absent on an indexed predicate; at most one
    node per key may ever be created.

    Reference: dgraph/src/jepsen/dgraph/upsert.clj:13-55 — :upsert
    creates an email node unless an index read finds one (ok iff it
    inserted); :read returns the sorted uids matching the key.
    """

    def setup(self, test):
        try:
            self.conn.post("/alter", UPSERT_SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass

    def invoke(self, test, op):
        k, _v = op["value"]
        try:
            if op["f"] == "upsert":
                out = self._upsert(
                    f'{{ q(func: eq(email, "{k}")) {{ u as uid }} }}',
                    [{"cond": "@if(eq(len(u), 0))",
                      "set_nquads": f'_:n <email> "{k}" .'}],
                )
                uids = (out.get("data") or {}).get("uids") or {}
                if uids:
                    return {**op, "type": "ok",
                            "value": independent.kv(k, sorted(uids.values()))}
                return {**op, "type": "fail", "error": "exists"}
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(email, "{k}")) {{ uid }} }}'
                )
                uids = sorted(r["uid"] for r in data.get("q", []))
                return {**op, "type": "ok", "value": independent.kv(k, uids)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class UpsertChecker(checker_mod.Checker):
    """At most one uid may ever be read, and at most one upsert may
    succeed, per key.  (reference: upsert.clj:57-71)"""

    def check(self, test, history, opts=None):
        from ..history import OK as _OK

        reads = [op for op in history if op.type == _OK and op.f == "read"]
        upserts = [op for op in history if op.type == _OK and op.f == "upsert"]
        bad_reads = [
            {"index": op.index, "value": list(op.value)}
            for op in reads
            if op.value is not None and len(op.value) > 1
        ]
        return {
            "valid?": not bad_reads and len(upserts) <= 1,
            "bad-reads": bad_reads,
            "ok-upsert-count": len(upserts),
        }


def upsert_workload(opts: Optional[dict] = None) -> dict:
    """Per key: every thread races one upsert, then every thread reads.
    (reference: upsert.clj:73-86)"""

    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def fgen(k):
        return gen_mod.phases(
            gen_mod.each_thread(
                gen_mod.once({"type": "invoke", "f": "upsert", "value": None})
            ),
            gen_mod.each_thread(
                gen_mod.once({"type": "invoke", "f": "read", "value": None})
            ),
        )

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(UpsertChecker()),
        "concurrency": 4 * n,
    }


# ---------------------------------------------------------------------
# delete workload
# ---------------------------------------------------------------------


class DgraphDeleteClient(DgraphClient):
    """Create and delete indexed records per key; reads must never see
    a half-indexed state (zero records or exactly one well-formed one).

    Reference: dgraph/src/jepsen/dgraph/delete.clj:22-62 — :upsert
    creates {key k} unless present, :delete removes the record found by
    an index read, :read returns the matching records.  Both mutations
    ride one transactional upsert block.
    """

    def invoke(self, test, op):
        k, _v = op["value"]
        q = f'{{ q(func: eq(key, {int(k)})) {{ u as uid }} }}'
        try:
            if op["f"] == "read":
                data = self._query(
                    f'{{ q(func: eq(key, {int(k)})) {{ uid key }} }}'
                )
                return {**op, "type": "ok",
                        "value": independent.kv(k, data.get("q", []))}
            if op["f"] == "upsert":
                out = self._upsert(q, [
                    {"cond": "@if(eq(len(u), 0))",
                     "set_nquads": f'_:n <key> "{int(k)}" .'},
                ])
                if (out.get("data") or {}).get("uids"):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "present"}
            if op["f"] == "delete":
                out = self._upsert(q, [
                    {"cond": "@if(gt(len(u), 0))",
                     "del_nquads": "uid(u) * * ."},
                ])
                matched = (out.get("data") or {}).get("queries", {}).get("q")
                if matched:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "not-found"}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


class DeleteChecker(checker_mod.Checker):
    """Every ok read sees zero records, or exactly one {uid, key=k}.
    (reference: delete.clj:64-90)"""

    def check(self, test, history, opts=None):
        from ..history import OK as _OK

        k = (opts or {}).get("history-key")
        bad = []
        for op in history:
            if op.type != _OK or op.f != "read" or op.value is None:
                continue
            recs = op.value
            well_formed = len(recs) == 0 or (
                len(recs) == 1
                and set(recs[0].keys()) == {"uid", "key"}
                and (k is None or str(recs[0]["key"]) == str(k))
            )
            if not well_formed:
                bad.append({"index": op.index, "value": recs})
        return {"valid?": not bad, "bad-reads": bad}


def delete_workload(opts: Optional[dict] = None) -> dict:
    """Mixed upsert/delete/read ops per independent key.
    (reference: delete.clj:92-103)"""
    import random as _random

    from .. import generator as gen_mod

    opts = dict(opts or {})
    n = max(1, len(opts.get("nodes", ["n1"])))

    def rw(test, ctx):
        f = _random.choice(["read", "upsert", "delete"])
        return {"type": "invoke", "f": f, "value": None}

    def fgen(k):
        return gen_mod.limit(12, rw)

    return {
        "generator": independent.concurrent_generator(
            2 * n, range(100_000), fgen
        ),
        "checker": independent.checker(DeleteChecker()),
        "concurrency": 4 * n,
    }


# ---------------------------------------------------------------------
# Multi-op transactions over the HTTP txn protocol
# ---------------------------------------------------------------------


class TxnAborted(Exception):
    """Commit-time conflict — the TxnConflictException of the HTTP API
    (reference: dgraph/client.clj catches io.dgraph.TxnConflictException
    and fails the op; bank.clj imports it at :12)."""


class _DgraphTxn:
    """One read-modify-write transaction: queries and mutations carry a
    shared startTs; /commit applies them atomically or aborts.  This is
    Dgraph's native HTTP transaction flow (the gRPC client the reference
    uses does the same under the hood: begin ts from the first response,
    staged mutations, commit with the accumulated keys/preds)."""

    def __init__(self, conn: JsonHttpClient):
        self.conn = conn
        self.start_ts = 0
        self.keys: list = []
        self.preds: list = []

    def _merge_txn(self, body: dict) -> None:
        txn = (body or {}).get("extensions", {}).get("txn", {})
        if txn.get("start_ts"):
            self.start_ts = txn["start_ts"]
        self.keys += txn.get("keys", [])
        self.preds += txn.get("preds", [])

    def query(self, q: str) -> dict:
        path = "/query"
        if self.start_ts:
            path += f"?startTs={self.start_ts}"
        _, body = self.conn.post(
            path, q, headers={"Content-Type": "application/graphql+-"},
            ok=(200,),
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        self._merge_txn(body)
        return body.get("data", {})

    def mutate(self, set_nquads: str = "", del_nquads: str = "") -> dict:
        path = "/mutate"
        if self.start_ts:
            path += f"?startTs={self.start_ts}"
        payload: dict = {}
        if set_nquads:
            payload["set_nquads"] = set_nquads
        if del_nquads:
            payload["del_nquads"] = del_nquads
        _, body = self.conn.post(
            path, json.dumps(payload),
            headers={"Content-Type": "application/json"}, ok=(200,),
        )
        if "errors" in (body or {}):
            raise HttpError(200, body["errors"])
        self._merge_txn(body)
        return body

    def commit(self) -> None:
        status, body = self.conn.request(
            "POST",
            f"/commit?startTs={self.start_ts}",
            body={"keys": self.keys, "preds": self.preds},
            ok=(200,),
            raise_on_error=False,
        )
        if status == 409 or (
            isinstance(body, dict) and "errors" in body
        ):
            # definite abort: the commit did not apply
            raise TxnAborted(str(body))
        if status != 200:
            # anything else (5xx through a faulted proxy, truncated
            # body, …) leaves the commit outcome UNKNOWN — acking it as
            # ok would corrupt the history exactly in the faulted runs
            # this suite exists to test
            raise IndeterminateError(
                f"commit status {status}: {str(body)[:200]}"
            )


# ---------------------------------------------------------------------
# bank workload (reference: dgraph/src/jepsen/dgraph/bank.clj:1-199)
# ---------------------------------------------------------------------

PRED_COUNT = 7  # (reference: bank.clj:15-16)


def gen_pred(prefix: str, k: int) -> str:
    """Key-striped predicate name (reference: client.clj gen-pred,
    consumed at bank.clj:63-66)."""
    return f"{prefix}_{int(k) % PRED_COUNT}"


def gen_preds(prefix: str) -> list:
    return [f"{prefix}_{i}" for i in range(PRED_COUNT)]


BANK_SCHEMA = "\n".join(
    f"{p}: int @index(int) .\n" for p in gen_preds("key") + gen_preds("amount")
) + "\n".join(f"{p}: string @index(exact) .\n" for p in gen_preds("type"))


class DgraphBankClient(DgraphClient):
    """Transfers as read-modify-write transactions over key-striped
    predicates; commit conflicts fail the op.

    Reference: dgraph/bank.clj — striped preds (:15-16, gen-pred via
    client.clj), read-accounts merging per-type-predicate queries
    (:36-57), find-account by key (:59-80), write-account! deleting
    zero-amount nodes (:82-103), transfer as one txn (:105-140)."""

    def setup(self, test):
        try:
            self.conn.post("/alter", BANK_SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass
        accounts = list(test.get("accounts", range(8)))
        total = int(test.get("total-amount", 100))
        if not accounts:
            return
        k = accounts[0]
        try:
            self._upsert(
                f'{{ q(func: eq({gen_pred("key", k)}, {int(k)})) '
                "{ u as uid } }",
                [{"cond": "@if(eq(len(u), 0))",
                  "set_nquads": (
                      f'_:a <{gen_pred("key", k)}> "{int(k)}" .\n'
                      f'_:a <{gen_pred("amount", k)}> "{total}" .\n'
                      f'_:a <{gen_pred("type", k)}> "account" .'
                  )}],
            )
        except (HttpError, IndeterminateError):
            pass

    def _find_account(self, txn: _DgraphTxn, k: int) -> dict:
        """(reference: bank.clj:59-80 find-account)"""
        kp, ap = gen_pred("key", k), gen_pred("amount", k)
        data = txn.query(
            f"{{ q(func: eq({kp}, {int(k)})) {{ uid {kp} {ap} }} }}"
        )
        rows = data.get("q", [])
        if rows:
            r = rows[0]
            return {"uid": r["uid"], "key": k,
                    "amount": int(r.get(ap) or 0)}
        return {"uid": None, "key": k, "amount": 0}

    def _write_account(self, txn: _DgraphTxn, acct: dict) -> None:
        """(reference: bank.clj:82-103 write-account!)"""
        k = acct["key"]
        kp, ap, tp = (
            gen_pred("key", k), gen_pred("amount", k), gen_pred("type", k)
        )
        if acct["uid"] is None:
            txn.mutate(set_nquads=(
                f'_:a <{kp}> "{int(k)}" .\n'
                f'_:a <{ap}> "{acct["amount"]}" .\n'
                f'_:a <{tp}> "account" .'
            ))
        elif acct["amount"] == 0:
            txn.mutate(del_nquads=f'<{acct["uid"]}> * * .')
        else:
            txn.mutate(set_nquads=(
                f'<{acct["uid"]}> <{ap}> "{acct["amount"]}" .'
            ))

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                txn = _DgraphTxn(self.conn)
                out: dict = {}
                for tp in gen_preds("type"):
                    fields = " ".join(
                        gen_preds("key") + gen_preds("amount")
                    )
                    data = txn.query(
                        f'{{ q(func: eq({tp}, "account")) {{ {fields} }} }}'
                    )
                    for row in data.get("q", []):
                        key = amount = None
                        for pred, value in row.items():
                            if value is None:
                                continue
                            if pred.startswith("key_"):
                                key = int(value)
                            elif pred.startswith("amount_"):
                                amount = int(value)
                        if key is not None:
                            out[key] = amount
                # commit the read-only txn: validates the read set, so a
                # transfer landing between the per-predicate scans
                # aborts this read instead of yielding a torn total
                txn.commit()
                return {**op, "type": "ok", "value": out}
            if op["f"] == "transfer":
                frm = int(op["value"]["from"])
                to = int(op["value"]["to"])
                amt = int(op["value"]["amount"])
                txn = _DgraphTxn(self.conn)
                a = self._find_account(txn, frm)
                b = self._find_account(txn, to)
                a2 = {**a, "amount": a["amount"] - amt}
                b2 = {**b, "amount": b["amount"] + amt}
                if a2["amount"] < 0 and not test.get("negative-balances?"):
                    return {**op, "type": "fail",
                            "error": "insufficient funds"}
                self._write_account(txn, a2)
                self._write_account(txn, b2)
                txn.commit()
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except TxnAborted as e:
            return {**op, "type": "fail", "error": f"conflict: {e}"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


# ---------------------------------------------------------------------
# wr (rw-register) + long-fork txn client
# (reference: dgraph/src/jepsen/dgraph/wr.clj:1-32, long_fork.clj)
# ---------------------------------------------------------------------

WR_SCHEMA = (
    "key: int @index(int) @upsert .\n"
    "value: int .\n"
)


class DgraphTxnClient(DgraphClient):
    """Micro-op transactions ([f k v] lists) through one Dgraph txn:
    reads by key index, writes upserting value nodes; commit conflicts
    fail the whole txn.  Serves the wr (Elle rw-register) and long-fork
    workloads.  (reference: wr.clj:13-27 — mop execution in one
    (c/with-txn), conflicts → :fail via client.clj)"""

    def setup(self, test):
        try:
            self.conn.post("/alter", WR_SCHEMA, ok=(200,))
        except (HttpError, IndeterminateError):
            pass

    def _mop(self, txn: _DgraphTxn, local: dict, f, k, v):
        if f == "r":
            # read-your-writes inside the txn: the gRPC client's staged
            # mutations are visible to its own queries; the HTTP staging
            # is not, so mirror it client-side
            if k in local:
                return ["r", k, local[k]]
            data = txn.query(
                f"{{ q(func: eq(key, {int(k)})) {{ value }} }}"
            )
            rows = data.get("q", [])
            val = int(rows[0]["value"]) if rows and rows[0].get("value") is not None else None
            return ["r", k, val]
        if f == "w":
            # a second write to the same key in this txn must hit the
            # node staged by the first, not create a duplicate: the
            # committed store has no row yet, so consult the txn-local
            # uid map before querying (staged blank-node uids come back
            # in the mutate response's data.uids)
            uid = local.get(("uid", k))
            if uid is None:
                data = txn.query(
                    f"{{ q(func: eq(key, {int(k)})) {{ uid }} }}"
                )
                rows = data.get("q", [])
                uid = rows[0]["uid"] if rows else None
            if uid is not None:
                txn.mutate(set_nquads=(
                    f'<{uid}> <value> "{int(v)}" .'
                ))
            else:
                body = txn.mutate(set_nquads=(
                    f'_:n <key> "{int(k)}" .\n_:n <value> "{int(v)}" .'
                ))
                uid = (body.get("data", {}).get("uids") or {}).get("n")
            if uid is not None:
                local[("uid", k)] = uid
            local[k] = v
            return ["w", k, v]
        raise ValueError(f"unknown micro-op {f!r}")

    def invoke(self, test, op):
        txn_value = op["value"]
        try:
            txn = _DgraphTxn(self.conn)
            local: dict = {}
            out = [self._mop(txn, local, f, k, v) for f, k, v in txn_value]
            txn.commit()
            return {**op, "type": "ok", "value": out}
        except TxnAborted as e:
            return {**op, "type": "fail", "error": f"conflict: {e}"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


# ---------------------------------------------------------------------
# sequential workload (reference: dgraph/sequential.clj)
# ---------------------------------------------------------------------


class DgraphSequentialClient(DgraphClient):
    """Read and read-increment-write transactions on per-key registers
    (reference: sequential.clj:64-105).  Restricting transactions to
    read-only or write-your-whole-read-set shapes makes snapshot-
    isolation histories serializable, so each process must observe
    monotonically nondecreasing values of an increment-only register —
    the sequential-consistency probe of sequential.clj:1-48."""

    def invoke(self, test, op):
        k, _ = op["value"]
        try:
            txn = _DgraphTxn(self.conn)
            data = txn.query(
                f"{{ q(func: eq(key, {int(k)})) {{ uid value }} }}"
            )
            rows = data.get("q", [])
            uid = rows[0].get("uid") if rows else None
            value = (
                int(rows[0]["value"])
                if rows and rows[0].get("value") is not None
                else 0
            )
            if op["f"] == "inc":
                value += 1
                if uid:
                    txn.mutate(set_nquads=f'<{uid}> <value> "{value}" .')
                else:
                    txn.mutate(set_nquads=(
                        f'_:n <key> "{int(k)}" .\n_:n <value> "{value}" .'
                    ))
                txn.commit()
                return {**op, "type": "ok",
                        "value": independent.kv(k, value)}
            if op["f"] == "read":
                txn.commit()
                return {**op, "type": "ok",
                        "value": independent.kv(k, value)}
            raise ValueError(f"unknown f {op['f']!r}")
        except TxnAborted as e:
            return {**op, "type": "fail", "error": f"conflict: {e}"}
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}


def sequential_non_monotonic_pairs(history):
    """Pairs of ok ops on one process whose observed value went DOWN
    (reference: sequential.clj:107-126)."""
    from ..history import OK

    last: dict = {}
    errs = []
    for op in history:
        if op.type != OK or not isinstance(op.value, int):
            continue
        prev = last.get(op.process)
        prev_value = prev.value if prev is not None else 0
        if op.value < prev_value:
            errs.append([
                {"op-index": prev.index, "value": prev.value},
                {"op-index": op.index, "value": op.value},
            ])
        last[op.process] = op
    return errs


class SequentialChecker(checker_mod.Checker):
    """Per-process monotonicity of an increment-only register
    (reference: sequential.clj:128-136; generalized over keys by
    independent.checker exactly as the reference does)."""

    def check(self, test, history, opts=None):
        errs = sequential_non_monotonic_pairs(history)
        return {"valid?": not errs, "non-monotonic": errs}


def merged_windows(s, points):
    """[lower, upper] windows of s elements around each point, merged
    when overlapping (reference: sequential.clj:138-158)."""
    if not points:
        return []
    points = sorted(points)
    windows = []
    lower, upper = points[0] - s, points[0] + s
    for p in points[1:]:
        # bounds are inclusive (the plotter slices upper+1), so
        # touching windows merge; split only past the boundary
        if p - s > upper:
            windows.append([lower, upper])
            lower, upper = p - s, p + s
        else:
            upper = p + s
    windows.append([lower, upper])
    return windows


class SequentialPlotter(checker_mod.Checker):
    """Per-process value-over-time SVGs of the ±32-event windows around
    each non-monotonic spot (reference: sequential.clj:160-227; the
    gnuplot rendering is replaced by the framework's self-rendered SVG
    scatter, checker/perf.py)."""

    WINDOW = 32

    def check(self, test, history, opts=None):
        from ..history import NEMESIS, OK
        from ..checker import perf

        interesting = [
            op for op in history
            if (op.type == OK and isinstance(op.value, int))
            or op.process == NEMESIS
        ]
        last: dict = {}
        spots = []
        for i, op in enumerate(interesting):
            if op.process == NEMESIS:
                continue
            prev = last.get(op.process)
            if op.value < (prev.value if prev is not None else 0):
                spots.append(i)
            last[op.process] = op
        for w, (lower, upper) in enumerate(
            merged_windows(self.WINDOW, spots)
        ):
            window = interesting[max(lower, 0):max(upper + 1, 0)]
            series: dict = {}
            for op in window:
                if op.process == NEMESIS:
                    continue
                series.setdefault(op.process, []).append(
                    (op.time / 1e9, op.value)
                )
            if not series:
                continue
            perf.scatter_plot(
                test,
                series,
                path_components=list((opts or {}).get("subdirectory", []))
                + [f"sequential-{w}.svg"],
                title=f"{test.get('name', 'test')} sequential by process",
                ylabel="register value",
                history=history,
            )
        return {"valid?": True}


def sequential_workload(opts: Optional[dict] = None) -> dict:
    """(reference: sequential.clj:229-247 workload)"""
    from .. import generator as gen_mod
    from ..checker import timeline

    opts = dict(opts or {})

    def inc_gen(test, ctx):
        return {"type": "invoke", "f": "inc",
                "value": independent.kv(gen_mod.rng.randrange(8), None)}

    def read_gen(test, ctx):
        return {"type": "invoke", "f": "read",
                "value": independent.kv(gen_mod.rng.randrange(8), None)}

    return {
        "generator": gen_mod.mix([inc_gen, read_gen]),
        "checker": independent.checker(checker_mod.compose({
            "sequential": SequentialChecker(),
            "plot": SequentialPlotter(),
            "timeline": timeline.html(),
        })),
    }
