"""Elasticsearch suite.

Reference: elasticsearch/src/jepsen/elasticsearch/{core,sets,dirty_read}.clj
— install a tarball + JDK8 (core.clj:212-230), write elasticsearch.yml
with static unicast discovery over the test's nodes, start the
``bin/elasticsearch`` daemon (core.clj:247-266), and exercise two
workloads: **sets** (index one doc per element, final search must find
them all; sets.clj) and **dirty-read** (reads-by-id vs search visibility;
dirty_read.clj).  The reference's Java client becomes the JSON REST API.
"""

from __future__ import annotations

from typing import Optional

from .. import client as client_mod
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

DEFAULT_TARBALL = (
    "https://artifacts.elastic.co/downloads/elasticsearch/"
    "elasticsearch-5.0.0.tar.gz"
)
DIR = "/opt/elasticsearch"
HTTP_PORT = 9200
TRANSPORT_PORT = 9300
INDEX = "jepsen"


class ElasticsearchDB(common.DaemonDB):
    dir = DIR
    binary = "bin/elasticsearch"
    logfile = f"{DIR}/logs/stdout.log"
    pidfile = f"{DIR}/es.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        # (reference: core.clj:212-230 install!)
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def configure(self, test, node):
        # (reference: core.clj:232-245 configure! — unicast discovery)
        hosts = ", ".join(f'"{n}:{TRANSPORT_PORT}"' for n in test["nodes"])
        config = "\n".join(
            [
                f"cluster.name: jepsen",
                f"node.name: {node}",
                "network.host: 0.0.0.0",
                f"discovery.zen.ping.unicast.hosts: [{hosts}]",
                f"discovery.zen.minimum_master_nodes: "
                f"{len(test['nodes']) // 2 + 1}",
            ]
        )
        with sudo():
            cu.write_file(config, f"{DIR}/config/elasticsearch.yml")

    def start_args(self, test, node):
        return ["-d", "-p", self.pidfile]

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTP_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data", f"{DIR}/logs")


class EsSetClient(client_mod.Client):
    """Set workload client: add → index a doc keyed by the element;
    read → search with a large size, collecting ids.
    (reference: elasticsearch/sets.clj)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", HTTP_PORT),
            timeout=10.0,
        )
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.put(
                    f"/{INDEX}/elements/{op['value']}",
                    {"value": op["value"]},
                    params={"refresh": "true"},
                    ok=(200, 201),
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                # force a refresh, then scroll through everything (a
                # plain search is capped by index.max_result_window;
                # the reference uses the scroll API too —
                # elasticsearch/core.clj:109-150 all-results)
                self.conn.post(f"/{INDEX}/_refresh", ok=(200,))
                _, body = self.conn.post(
                    f"/{INDEX}/_search",
                    {"size": 1000, "query": {"match_all": {}}},
                    params={"scroll": "1m"},
                    ok=(200,),
                )
                values = [h["_source"]["value"] for h in body["hits"]["hits"]]
                scroll_id = body.get("_scroll_id")
                while scroll_id:
                    _, body = self.conn.post(
                        "/_search/scroll",
                        {"scroll": "1m", "scroll_id": scroll_id},
                        ok=(200,),
                    )
                    hits = body["hits"]["hits"]
                    if not hits:
                        break
                    values.extend(h["_source"]["value"] for h in hits)
                    scroll_id = body.get("_scroll_id")
                return {**op, "type": "ok", "value": sorted(values)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return ElasticsearchDB(opts)


def client(opts: Optional[dict] = None):
    return EsSetClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    # dirty-read shares crate's workload/checker shape — the reference's
    # es dirty-read client is literally wrapped by crate's
    # (crate/dirty_read.clj:97-141 es-client)
    from . import crate as crate_suite

    opts = dict(opts or {})
    return {
        "set": common.set_workload(opts),
        "dirty-read": crate_suite.dirty_read_workload(opts),
    }


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    wname = opts.get("workload", "set")
    w = workloads(opts)[wname]
    c = (
        EsDirtyReadClient(opts)
        if wname == "dirty-read"
        else EsSetClient(opts)
    )
    return common.build_test(
        f"elasticsearch-{wname}", opts, db=ElasticsearchDB(opts),
        client=c, workload=w,
    )


# ---------------------------------------------------------------------
# dirty-read
# (reference: elasticsearch/src/jepsen/elasticsearch/dirty_read.clj)
# ---------------------------------------------------------------------

DR_INDEX = "dirty_read"


class EsDirtyReadClient(EsSetClient):
    """Index-by-id writes vs GET-by-id reads vs a refresh + search-all
    strong read — the probe that found Elasticsearch's dirty/lost reads.
    (reference: dirty_read.clj:30-105 — write indexes {id}, read GETs
    the doc ok/fail, refresh must succeed on all shards, strong-read
    collects every id; the workload/checker shape is shared with
    crate's dirty-read, whose client the reference literally wraps)"""

    def setup(self, test):
        # the probe is about replica visibility: the index must span
        # every node (reference dirty_read.clj creates it up front;
        # crate's sibling sets number_of_replicas = "0-all")
        try:
            self.conn.put(
                f"/{DR_INDEX}",
                {"settings": {"index": {"auto_expand_replicas": "0-all"}}},
                ok=(200, 201),
            )
        except (HttpError, IndeterminateError):
            pass  # already exists

    def invoke(self, test, op):
        try:
            if op["f"] == "write":
                self.conn.put(
                    f"/{DR_INDEX}/default/{int(op['value'])}",
                    {"id": int(op["value"])},
                    ok=(200, 201),
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                status, _body = self.conn.request(
                    "GET",
                    f"/{DR_INDEX}/default/{int(op['value'])}",
                    ok=(200,),
                    raise_on_error=False,
                )
                return {**op, "type": "ok" if status == 200 else "fail"}
            if op["f"] == "refresh":
                _, body = self.conn.post(f"/{DR_INDEX}/_refresh", ok=(200,))
                shards = (body or {}).get("_shards", {})
                if shards.get("successful") != shards.get("total"):
                    # a partial refresh means the strong read may miss
                    # docs — reporting ok here would turn that into
                    # false "lost" findings (reference: dirty_read.clj
                    # retries until successful == total)
                    return {**op, "type": "fail",
                            "error": f"partial refresh: {shards}"}
                return {**op, "type": "ok"}
            if op["f"] == "strong-read":
                # scroll, don't one-shot: a plain search is capped by
                # index.max_result_window (10k) — same pagination as
                # EsSetClient.read above
                _, body = self.conn.post(
                    f"/{DR_INDEX}/_search",
                    {"size": 1000, "query": {"match_all": {}}},
                    params={"scroll": "1m"},
                    ok=(200,),
                )
                ids = [int(h["_source"]["id"]) for h in body["hits"]["hits"]]
                scroll_id = body.get("_scroll_id")
                while scroll_id:
                    _, body = self.conn.post(
                        "/_search/scroll",
                        {"scroll": "1m", "scroll_id": scroll_id},
                        ok=(200,),
                    )
                    hits = body["hits"]["hits"]
                    if not hits:
                        break
                    ids.extend(int(h["_source"]["id"]) for h in hits)
                    scroll_id = body.get("_scroll_id")
                return {**op, "type": "ok", "value": sorted(ids)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}
