"""Elasticsearch suite.

Reference: elasticsearch/src/jepsen/elasticsearch/{core,sets,dirty_read}.clj
— install a tarball + JDK8 (core.clj:212-230), write elasticsearch.yml
with static unicast discovery over the test's nodes, start the
``bin/elasticsearch`` daemon (core.clj:247-266), and exercise two
workloads: **sets** (index one doc per element, final search must find
them all; sets.clj) and **dirty-read** (reads-by-id vs search visibility;
dirty_read.clj).  The reference's Java client becomes the JSON REST API.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .. import client as client_mod
from .. import generator as gen
from .. import checker as checker_mod
from ..control import util as cu
from ..control import execute, sudo
from ..os_setup import debian
from . import common
from .proto import IndeterminateError
from .proto.http import HttpError, JsonHttpClient

DEFAULT_TARBALL = (
    "https://artifacts.elastic.co/downloads/elasticsearch/"
    "elasticsearch-5.0.0.tar.gz"
)
DIR = "/opt/elasticsearch"
HTTP_PORT = 9200
TRANSPORT_PORT = 9300
INDEX = "jepsen"


class ElasticsearchDB(common.DaemonDB):
    dir = DIR
    binary = "bin/elasticsearch"
    logfile = f"{DIR}/logs/stdout.log"
    pidfile = f"{DIR}/es.pid"
    proc_name = "java"  # the server runs under the JVM

    def __init__(self, opts: Optional[dict] = None):
        super().__init__(opts)
        self.tarball = (opts or {}).get("tarball", DEFAULT_TARBALL)

    def install(self, test, node):
        # (reference: core.clj:212-230 install!)
        debian.install(["openjdk-8-jre-headless"])
        with sudo():
            cu.install_archive(self.tarball, DIR)

    def configure(self, test, node):
        # (reference: core.clj:232-245 configure! — unicast discovery)
        hosts = ", ".join(f'"{n}:{TRANSPORT_PORT}"' for n in test["nodes"])
        config = "\n".join(
            [
                f"cluster.name: jepsen",
                f"node.name: {node}",
                "network.host: 0.0.0.0",
                f"discovery.zen.ping.unicast.hosts: [{hosts}]",
                f"discovery.zen.minimum_master_nodes: "
                f"{len(test['nodes']) // 2 + 1}",
            ]
        )
        with sudo():
            cu.write_file(config, f"{DIR}/config/elasticsearch.yml")

    def start_args(self, test, node):
        return ["-d", "-p", self.pidfile]

    def await_ready(self, test, node):
        cu.await_tcp_port(HTTP_PORT, timeout_s=120)

    def wipe(self, test, node):
        with sudo():
            execute("rm", "-rf", f"{DIR}/data", f"{DIR}/logs")


class EsSetClient(client_mod.Client):
    """Set workload client: add → index a doc keyed by the element;
    read → search with a large size, collecting ids.
    (reference: elasticsearch/sets.clj)"""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}
        self.conn: Optional[JsonHttpClient] = None

    def open(self, test, node):
        c = type(self)(self.opts)
        c.conn = JsonHttpClient(
            self.opts.get("host", str(node)),
            self.opts.get("port", HTTP_PORT),
            timeout=10.0,
        )
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.put(
                    f"/{INDEX}/elements/{op['value']}",
                    {"value": op["value"]},
                    params={"refresh": "true"},
                    ok=(200, 201),
                )
                return {**op, "type": "ok"}
            if op["f"] == "read":
                # force a refresh, then scroll through everything (a
                # plain search is capped by index.max_result_window;
                # the reference uses the scroll API too —
                # elasticsearch/core.clj:109-150 all-results)
                self.conn.post(f"/{INDEX}/_refresh", ok=(200,))
                _, body = self.conn.post(
                    f"/{INDEX}/_search",
                    {"size": 1000, "query": {"match_all": {}}},
                    params={"scroll": "1m"},
                    ok=(200,),
                )
                values = [h["_source"]["value"] for h in body["hits"]["hits"]]
                scroll_id = body.get("_scroll_id")
                while scroll_id:
                    _, body = self.conn.post(
                        "/_search/scroll",
                        {"scroll": "1m", "scroll_id": scroll_id},
                        ok=(200,),
                    )
                    hits = body["hits"]["hits"]
                    if not hits:
                        break
                    values.extend(h["_source"]["value"] for h in hits)
                    scroll_id = body.get("_scroll_id")
                return {**op, "type": "ok", "value": sorted(values)}
            raise ValueError(f"unknown f {op['f']!r}")
        except IndeterminateError as e:
            return {**op, "type": "info", "error": str(e)}
        except HttpError as e:
            return {**op, "type": "fail", "error": f"{e.status}: {e.body}"}

    def close(self, test):
        if self.conn:
            self.conn.close()


def db(opts: Optional[dict] = None):
    return ElasticsearchDB(opts)


def client(opts: Optional[dict] = None):
    return EsSetClient(opts)


def workloads(opts: Optional[dict] = None) -> dict:
    return {"set": common.set_workload(dict(opts or {}))}


def test(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    w = workloads(opts)[opts.get("workload", "set")]
    return common.build_test(
        "elasticsearch-set", opts, db=ElasticsearchDB(opts),
        client=EsSetClient(opts), workload=w,
    )
